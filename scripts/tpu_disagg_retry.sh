#!/usr/bin/env bash
# Burst-mode retry of the disagg A/B (the north-star measurement): waits
# for the tunnel, then runs a shorter A/B with per-request timeouts and
# incremental --out so a mid-phase tunnel wedge keeps the finished phase.
# Run AFTER the main watcher queue (single chip — no concurrent stages).
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/tpu
mkdir -p "$OUT"

probe_once() {
  timeout 120 python -c \
    "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
    >/dev/null 2>&1
}
n=0
while ! probe_once; do
  n=$((n + 1))
  echo "$(date -u +%H:%M:%S) tunnel down (probe $n); retry in 10 min"
  sleep 600
done
echo "$(date -u +%H:%M:%S) tunnel OK after $n failed probes"

timeout 3000 python -m benchmarks.disagg_bench \
  --model llama3-1b --dtype bfloat16 --page-size 64 --num-pages 1024 \
  --max-context 4096 --max-local-prefill 256 --requests 24 --isl 1024 \
  --osl 64 --concurrency 8 --warmup 8 \
  --request-timeout 120 --out "$OUT/disagg_ab.json" \
  > "$OUT/disagg_ab.log" 2> "$OUT/disagg_ab.err"
rc=$?
echo "disagg_ab retry rc=$rc"
tail -c 400 "$OUT/disagg_ab.json" 2>/dev/null; echo

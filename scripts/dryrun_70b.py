"""70B-readiness dryrun (VERDICT r2 item 6; BASELINE config 4).

Two halves:

1. HBM accounting for REAL llama3-70b shapes on a v5e-16 mesh (4 hosts x
   4 chips, tp=8 x dp=2): per-leaf sharded bytes from eval_shape + the
   parallel/shardings specs — no weights materialize anywhere. Asserts
   int8 weights + bf16 KV page pool + workspace fit 16GB/chip and
   records the full bytes/chip table.

2. Execution proof on a 16-virtual-device CPU mesh: a 70B-ARCHITECTURE
   config (80 layers, 64 q / 8 kv heads, GQA ratio 8 — dims scaled down)
   runs one serving step (prefill + decode + sample) under the exact
   same sharding specs, proving the tp=8 x dp=2 layout compiles and
   executes end to end.

Writes artifacts/dryrun_70b.json. Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=16 JAX_PLATFORMS=cpu \
      python scripts/dryrun_70b.py

A third, chip-free mode (ISSUE 20):
  python scripts/dryrun_70b.py --check-rules
dry-resolves EVERY registry preset's logical axis names through the
one rule table under both the 1-host layout and the tp=8 x dp=2 pod
layout — no weights, no mesh, no devices. A model declaring a logical
axis the table doesn't know fails here (UnknownLogicalAxisError) as a
fast tier-1 test instead of an on-chip surprise.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dynamo_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

import numpy as np  # noqa: E402

V5E_HBM = 16 * 1024**3  # bytes/chip
TP, DP = 8, 2  # llama3-70b has 8 kv heads -> tp=8 keeps GQA head-sharded


def _sharded_bytes(shape, dtype_size, spec, axis_sizes) -> int:
    """Bytes per device for one leaf under a PartitionSpec."""
    n = dtype_size
    for dim, name in zip(shape, tuple(spec) + (None,) * len(shape)):
        if name is not None:
            dim = -(-dim // axis_sizes[name])
        n *= dim
    return n


def accounting() -> dict:
    import jax

    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.models.registry import get_model
    from dynamo_tpu.parallel.shardings import kv_cache_spec

    cfg = LlamaConfig.llama3_70b()
    adapter = get_model("llama3-70b", dtype="bfloat16")
    shapes = jax.eval_shape(
        lambda k: adapter.init_params(k), jax.random.key(0)
    )
    specs = adapter.param_specs(quantized=False)
    axis = {"tp": TP, "dp": DP}

    rows = []
    bf16_total = 0
    int8_total = 0
    from jax.sharding import PartitionSpec

    flat_shapes = jax.tree_util.tree_leaves_with_path(shapes)
    flat_specs = jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )
    for (path, s), spec in zip(flat_shapes, flat_specs):
        name = jax.tree_util.keystr(path)
        b16 = _sharded_bytes(s.shape, 2, spec, axis)
        # int8 weight-only halves every quantized dense leaf; norms/embeds
        # stay bf16. Scales are ~1/in_dim of the weight — counted at 1%.
        quantizable = any(
            k in name
            for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
        )
        b8 = (b16 // 2 + b16 // 100) if quantizable else b16
        bf16_total += b16
        int8_total += b8
        rows.append(
            {
                "leaf": name,
                "global_shape": list(s.shape),
                "spec": str(spec),
                "bf16_bytes_per_chip": b16,
                "int8_bytes_per_chip": b8,
            }
        )

    # KV pool: [L, P, S, Hkv, D] bf16, kv-heads sharded over tp. Pages
    # budget = whatever fits after weights + workspace.
    kv_spec = kv_cache_spec()
    page_shape = (cfg.num_layers, 1, 64, cfg.num_kv_heads, cfg.head_dim)
    per_page = 2 * _sharded_bytes(page_shape, 2, kv_spec, axis)  # k + v
    workspace = 2 * 1024**3  # activations + XLA scratch headroom
    budget = V5E_HBM - int8_total - workspace
    pages = budget // per_page
    ctx_tokens = pages * 64 // DP  # dp halves the batch, not the ctx

    return {
        "mesh": {"tp": TP, "dp": DP, "chips": TP * DP, "hosts": 4},
        "weights_bf16_bytes_per_chip": bf16_total,
        "weights_int8_bytes_per_chip": int8_total,
        "kv_bytes_per_page_per_chip": per_page,
        "workspace_reserve_bytes": workspace,
        "kv_pages_possible_int8": int(pages),
        "kv_tokens_possible_int8": int(pages * 64),
        "fits_bf16": bool(
            bf16_total + workspace + 64 * per_page < V5E_HBM
        ),
        "fits_int8": bool(
            int8_total + workspace + 64 * per_page < V5E_HBM
        ),
        "leaves": rows,
        "note": (
            "bf16 70B weights alone are "
            f"{bf16_total / 2**30:.1f}GB/chip on v5e-16 — int8 "
            "weight-only is the serving configuration (BASELINE.md's "
            "reference config serves 70B FP8 for the same reason)"
        ),
        "ctx_tokens_note": int(ctx_tokens),
    }


def execution_proof() -> dict:
    import time

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.parallel.mesh import MeshConfig

    assert len(jax.devices()) >= 16, "needs 16 virtual devices"
    # 70B architecture (layer count, head layout, GQA=8), hidden dims
    # scaled so 80 layers compile quickly on CPU
    cfg = LlamaConfig(
        vocab_size=512,
        hidden_size=512,
        intermediate_size=1024,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=8,
        dtype=jnp.float32,
        tie_word_embeddings=False,
    )
    t0 = time.time()
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.models.registry import _LLAMA_PRESETS

    _LLAMA_PRESETS["dryrun-70b-arch"] = lambda: cfg
    eng = JaxEngine(
        EngineConfig(
            model="dryrun-70b-arch",
            tp=TP,
            dp=DP,
            num_pages=64,
            page_size=16,
            max_pages_per_seq=8,
            decode_buckets=(2, 4),
            prefill_chunk=32,
            max_seqs=8,
            dtype="float32",
        ),
        mesh_config=MeshConfig(dp=DP, tp=TP),
    )
    rng = np.random.default_rng(3)
    for i in range(4):
        eng.add_request(
            f"r{i}",
            [int(x) for x in rng.integers(1, 500, 20 + 7 * i)],
            SamplingParams(temperature=0.0, max_tokens=4),
        )
    done = eng.run_to_completion()
    assert all(len(v) == 4 for v in done.values()), done
    return {
        "mesh": f"tp={TP} x dp={DP} over 16 virtual devices",
        "layers": 80,
        "heads": "64q/8kv (GQA 8)",
        "requests_served": len(done),
        "wall_s": round(time.time() - t0, 1),
    }


#: axis sizes of the two layouts --check-rules validates against
CHECK_RULES_LAYOUTS = {
    "1-host": {"dp": 1, "sp": 1, "ep": 1, "tp": 1},
    "tp=8,dp=2": {"dp": 2, "sp": 1, "ep": 1, "tp": 8},
}


def check_rules() -> dict:
    """Dry-resolve every registry preset x {fp, quantized} through the
    logical-axis rule table; validate every resolved PartitionSpec only
    references mesh axes the layouts actually have. Raises on any
    unknown logical axis name. Pure metadata — no arrays, no devices."""
    import jax
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.models.registry import get_model, list_presets
    from dynamo_tpu.parallel.logical import default_rules
    from dynamo_tpu.parallel.shardings import kv_logical_axes

    rules = default_rules()
    mesh_axes = {a for _, a in rules.rules if a is not None}
    for layout, sizes in CHECK_RULES_LAYOUTS.items():
        missing = mesh_axes - set(sizes)
        assert not missing, f"{layout} lacks mesh axes {missing}"

    presets = list_presets()
    report = {}
    for name in presets:
        adapter = get_model(name, dtype="bfloat16")
        row = {"leaves": 0, "sharded": {}, "quantized_leaves": 0}
        for quantized in (False, True):
            tree = adapter.logical_axes(quantized=quantized)
            specs = rules.tree_specs(tree)  # raises on unknown names
            leaves = jax.tree.leaves(
                specs, is_leaf=lambda x: isinstance(x, P)
            )
            for spec in leaves:
                for axis in spec:
                    if axis is None:
                        continue
                    assert axis in mesh_axes, (
                        f"{name}: resolved spec {spec} references "
                        f"unknown mesh axis {axis!r}"
                    )
                    if not quantized:
                        row["sharded"][axis] = (
                            row["sharded"].get(axis, 0) + 1
                        )
            key = "quantized_leaves" if quantized else "leaves"
            row[key] = len(leaves)
        assert row["sharded"].get("tp"), (
            f"{name}: no dim resolves to 'tp' — the rule table left the "
            "whole model replicated under tensor parallelism"
        )
        report[name] = row
    # the KV page pool rides the same table
    kv_spec = rules.spec(kv_logical_axes())
    return {
        "presets_checked": len(presets),
        "layouts": CHECK_RULES_LAYOUTS,
        "rules": rules.doc(),
        "kv_pool_spec": str(kv_spec),
        "per_preset": report,
    }


def main() -> None:
    if "--check-rules" in sys.argv:
        print(json.dumps(check_rules(), indent=2))
        return
    out = {"accounting": accounting(), "execution": execution_proof()}
    path = Path(__file__).resolve().parent.parent / "artifacts"
    path.mkdir(parents=True, exist_ok=True)
    (path / "dryrun_70b.json").write_text(json.dumps(out, indent=2))
    acc = out["accounting"]
    print(
        json.dumps(
            {
                "fits_int8": acc["fits_int8"],
                "fits_bf16": acc["fits_bf16"],
                "weights_int8_gb_per_chip": round(
                    acc["weights_int8_bytes_per_chip"] / 2**30, 2
                ),
                "kv_pages_possible": acc["kv_pages_possible_int8"],
                "execution": out["execution"],
            }
        )
    )


if __name__ == "__main__":
    main()

"""FT on hardware: SIGKILL the prefill worker while a DEVICE-plane KV
pull is in flight; the decode worker must fall back and finish the
request.

The CPU fault-tolerance suite covers prefill death on the HOST transfer
path only (tests/fault_tolerance/test_scenarios.py) because the CPU
backend's transfer server cannot survive a cross-process pull (see
disagg/device_transfer.py docstring). This script is the TPU complement:
a real cross-process pull over the PjRt transfer fabric, interrupted by
killing the sender the moment the receiver logs "device KV pull start".

Mirrors the reference's kill-injection methodology
(/root/reference/tests/fault_tolerance/scenarios.py) applied to the NIXL
analog plane. Writes artifacts/tpu/ft_device_kill.json.

Usage (tunnel alive): python scripts/tpu_ft_device_kill.py
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks._procs import ManagedProc, cli, free_port  # noqa: E402

MODEL = ["--model", "llama3-1b", "--dtype", "bfloat16", "--page-size", "16",
         "--num-pages", "256", "--max-context", "2048"]
ISL = 512  # ~16 MB of 1b-shape KV: the pull is a real multi-frame transfer
OSL = 8


def wait_log(proc: ManagedProc, needle: str, timeout: float) -> bool:
    """Tight poll (2 ms) so the kill lands inside the pull window."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        with open(proc.log_path) as f:
            if needle in f.read():
                return True
        time.sleep(0.002)
    return False


def main() -> None:
    out: dict = {"platform": None, "ok": False}
    procs: list[ManagedProc] = []
    try:
        import jax

        out["platform"] = jax.devices()[0].platform
        fport, hport = free_port(), free_port()
        fabric = ManagedProc("fabric", cli("fabric", "--port", str(fport)))
        procs.append(fabric)
        fabric.wait_for("listening|fabric server on")
        decode = ManagedProc(
            "decode",
            cli("run", "in=dyn", "out=jax", *MODEL,
                "--disagg", "--max-local-prefill", "64",
                "--transfer-timeout", "10",
                "--fabric", f"127.0.0.1:{fport}"),
        )
        procs.append(decode)
        decode.wait_for(r"worker \w+ up", timeout=900)
        prefill = ManagedProc(
            "prefill",
            cli("run", "in=dyn", "out=jax", *MODEL, "--role", "prefill",
                "--fabric", f"127.0.0.1:{fport}"),
        )
        procs.append(prefill)
        prefill.wait_for(r"prefill worker \w+ up", timeout=900)
        frontend = ManagedProc(
            "frontend",
            cli("run", "in=http", "out=dyn",
                "--fabric", f"127.0.0.1:{fport}", "--port", str(hport)),
        )
        procs.append(frontend)
        frontend.wait_for("listening on")
        frontend.wait_for("model attached", timeout=120)

        # Warm the compile caches end to end (remote path included) so the
        # measured request's timing is dominated by the transfer, not XLA.
        t_warm = time.time()
        status0, _ = _request(hport, "w" * ISL, OSL, timeout=900)
        out["warm"] = {"status": status0, "s": round(time.time() - t_warm, 1)}
        _clear_kv(hport)

        # The measured request: kill the sender at pull start.
        res: dict = {}

        def _one():
            t0 = time.time()
            try:
                status, ntok = _request(hport, "x" * ISL, OSL, timeout=120)
            except Exception as e:  # noqa: BLE001
                status, ntok = -1, 0
                res["error"] = repr(e)
            res.update(status=status, tokens=ntok,
                       latency_s=round(time.time() - t0, 2))

        t_req_start = time.time()
        t = threading.Thread(target=_one)
        t.start()
        saw_pull = wait_log(decode, "device KV pull start", 90)
        kill_t = time.time()
        if saw_pull:
            prefill.proc.send_signal(signal.SIGKILL)
        t.join(timeout=180)
        out["saw_pull_start"] = saw_pull
        out["request"] = res
        dlog = open(decode.log_path).read()
        out["pull_failed_logged"] = "device KV pull failed" in dlog
        out["local_fallback_logged"] = (
            "failed/timed out; local fallback" in dlog
        )
        out["ok"] = bool(
            saw_pull
            and res.get("status") == 200
            and res.get("tokens", 0) > 0
            and (out["pull_failed_logged"] or out["local_fallback_logged"])
        )
        if saw_pull and "latency_s" in res:
            out["kill_to_done_s"] = round(
                t_req_start + res["latency_s"] - kill_t, 2
            )
    finally:
        for p in reversed(procs):
            try:
                p.stop()
            except Exception:  # noqa: BLE001
                pass
    print(json.dumps(out, indent=1))
    sys.exit(0 if out["ok"] else 1)


def _request(port: int, text: str, osl: int, timeout: float) -> tuple[int, int]:
    body = json.dumps({
        "model": "llama3-1b",
        "messages": [{"role": "user", "content": text}],
        "max_tokens": osl, "stream": False,
    }).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/chat/completions", data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        data = json.loads(resp.read())
        usage = data.get("usage") or {}
        return resp.status, usage.get("completion_tokens", 0)


def _clear_kv(port: int) -> None:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/clear_kv_blocks", data=b"{}",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200


if __name__ == "__main__":
    main()

"""Distributed-stack soak: sustained mixed traffic against the real
serving stack (fabric + KV-routed jax workers + HTTP frontend), with
per-process RSS tracking.

tests/test_soak.py bounds a short in-process soak; this script is the
session-scale complement (the reference keeps a soak in
lib/runtime/tests/soak.rs): tens of minutes of continuous mixed load —
unary + streaming, logprobs, penalties, n>1, stop strings, cancels —
asserting zero transport-level failures and a bounded post-warmup RSS
slope on every process (leak detection for the fabric, workers, and
frontend alike).

Usage: python scripts/soak_distributed.py --minutes 20 [--disagg|--spmd]
Writes artifacts/soak_distributed.json (agg), soak_disagg.json, or
soak_spmd.json per topology.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks._procs import ManagedProc as Proc  # noqa: E402
from benchmarks._procs import cli as _cli  # noqa: E402
from benchmarks._procs import free_port as _free_port  # noqa: E402


def rss_mb(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return -1.0


async def one_request(session, url: str, model: str, r: random.Random,
                      stats: dict) -> None:
    body = {
        "model": model,
        "messages": [{"role": "user", "content": "".join(
            chr(97 + r.randrange(26)) for _ in range(r.randrange(4, 12))
        )}],
        "max_tokens": r.randrange(1, 6),
        "temperature": r.choice([0.0, 0.7]),
    }
    kind = r.randrange(6)
    if kind == 1:
        body["logprobs"] = True
        body["top_logprobs"] = 2
    elif kind == 2:
        body["frequency_penalty"] = 0.5
    elif kind == 3:
        body["n"] = 2
    elif kind == 4:
        body["stop"] = ["zz"]
    stream = kind == 5 or r.random() < 0.5
    body["stream"] = stream
    t0 = time.perf_counter()
    try:
        async with session.post(
            f"{url}/v1/chat/completions", json=body,
            timeout=__import__("aiohttp").ClientTimeout(total=60),
        ) as resp:
            if resp.status != 200:
                stats["http_errors"] += 1
                return
            if stream:
                # occasionally abandon mid-stream (exercises the
                # disconnect-cancel path)
                abandon = r.random() < 0.05
                n = 0
                async for _ in resp.content:
                    n += 1
                    if abandon and n >= 2:
                        stats["aborted"] += 1
                        return
            else:
                await resp.json()
        stats["ok"] += 1
        stats["lat_ms"].append((time.perf_counter() - t0) * 1000)
    except Exception:  # noqa: BLE001
        stats["transport_errors"] += 1


async def drive(url: str, model: str, minutes: float, concurrency: int,
                procs: list[Proc]) -> dict:
    import aiohttp

    r = random.Random(99)
    stats = {
        "ok": 0, "http_errors": 0, "transport_errors": 0, "aborted": 0,
        "lat_ms": [],
    }
    rss_series: dict[str, list[float]] = {p.name: [] for p in procs}
    deadline = time.time() + minutes * 60
    sample_every = 15.0
    next_sample = time.time()
    async with aiohttp.ClientSession() as session:
        async def worker(wid: int):
            rr = random.Random(1000 + wid)
            while time.time() < deadline:
                await one_request(session, url, model, rr, stats)

        async def sampler():
            nonlocal next_sample
            while time.time() < deadline:
                if time.time() >= next_sample:
                    for p in procs:
                        rss_series[p.name].append(rss_mb(p.proc.pid))
                    next_sample += sample_every
                await asyncio.sleep(1.0)

        await asyncio.gather(
            sampler(), *(worker(i) for i in range(concurrency))
        )

    lat = sorted(stats.pop("lat_ms"))
    out = dict(stats)
    out["requests_total"] = sum(
        stats[k] for k in ("ok", "http_errors", "transport_errors", "aborted")
    )
    if lat:
        out["lat_ms"] = {
            "p50": round(lat[len(lat) // 2], 1),
            "p99": round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 1),
        }
    out["rss_mb"] = {}
    for name, series in rss_series.items():
        if len(series) >= 4:
            # post-warmup slope: compare the 2nd quarter median to the
            # last quarter median (first samples include jit warmup)
            q = len(series) // 4
            early = sorted(series[q:2 * q])[q // 2 if q else 0]
            late = sorted(series[-q:])[q // 2 if q else 0]
            out["rss_mb"][name] = {
                "early": round(early, 1), "late": round(late, 1),
                "growth_pct": round(100 * (late - early) / max(early, 1), 2),
            }
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=20.0)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--disagg", action="store_true",
                    help="decode(+host tier, remote prefill) + prefill "
                    "fleet instead of plain workers")
    ap.add_argument("--spmd", action="store_true",
                    help="one dp=2 x tp=2 model sharded over TWO host "
                    "processes (lockstep broadcast under load)")
    args = ap.parse_args()

    import os as _os

    fport, hport = _free_port(), _free_port()
    engine = [
        "--model", "tiny", "--page-size", "4", "--num-pages", "64",
        "--max-context", "48", "--dtype", "float32", "--router-mode", "kv",
    ]
    procs: list[Proc] = []
    try:
        fb = Proc("fabric", _cli("fabric", "--port", str(fport)))
        procs.append(fb)
        fb.wait_for("listening|fabric server on")
        if args.spmd:
            cport = _free_port()
            spmd = [
                "run", "in=dyn", "out=jax", *engine,
                "--dp", "2", "--tp", "2",
                "--coordinator", f"127.0.0.1:{cport}", "--num-hosts", "2",
                "--fabric", f"127.0.0.1:{fport}",
            ]

            def _env(devices):
                env = {
                    k: v for k, v in _os.environ.items()
                    if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
                }
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (
                    f"--xla_force_host_platform_device_count={devices}"
                )
                env["PYTHONPATH"] = _os.path.dirname(
                    _os.path.dirname(_os.path.abspath(__file__))
                )
                return env

            leader = Proc("leader", [*_cli(*spmd), "--host-id", "0"],
                          env=_env(2))
            procs.append(leader)
            follower = Proc("follower", [*_cli(*spmd), "--host-id", "1"],
                            env=_env(2))
            procs.append(follower)
            follower.wait_for("spmd follower 1 up", timeout=300)
            leader.wait_for(r"worker \w+ up", timeout=300)
        elif args.disagg:
            d = Proc(
                "decode",
                _cli("run", "in=dyn", "out=jax", *engine,
                     "--disagg", "--max-local-prefill", "4",
                     "--transfer-timeout", "5",
                     "--host-kv-bytes", str(1 << 20),
                     "--fabric", f"127.0.0.1:{fport}"),
            )
            procs.append(d)
            d.wait_for(r"worker \w+ up", timeout=300)
            p0 = Proc(
                "prefill",
                _cli("run", "in=dyn", "out=jax", *engine,
                     "--role", "prefill",
                     "--fabric", f"127.0.0.1:{fport}"),
            )
            procs.append(p0)
            p0.wait_for(r"prefill worker \w+ up", timeout=300)
        else:
            for i in range(args.workers):
                w = Proc(
                    f"worker{i}",
                    _cli("run", "in=dyn", "out=jax", *engine,
                         "--fabric", f"127.0.0.1:{fport}"),
                )
                procs.append(w)
                w.wait_for(r"worker \w+ up", timeout=300)
        fe = Proc(
            "frontend",
            _cli("run", "in=http", "out=dyn",
                 "--fabric", f"127.0.0.1:{fport}", "--port", str(hport)),
        )
        procs.append(fe)
        fe.wait_for("model attached", timeout=120)

        out = asyncio.run(
            drive(f"http://127.0.0.1:{hport}", "tiny", args.minutes,
                  args.concurrency, procs)
        )
        out["minutes"] = args.minutes
        out["workers"] = args.workers
        out["topology"] = (
            "spmd-2host" if args.spmd
            else "disagg+tier" if args.disagg else "agg"
        )
        # soak verdict: no transport failures, every process's post-warmup
        # RSS growth bounded
        out["ok_verdict"] = bool(
            out["transport_errors"] == 0
            and out["http_errors"] == 0
            and all(
                v["growth_pct"] < 15.0 for v in out["rss_mb"].values()
            )
        )
        path = Path(__file__).resolve().parent.parent / "artifacts"
        path.mkdir(exist_ok=True)
        name = (
            "soak_spmd.json" if args.spmd
            else "soak_disagg.json" if args.disagg
            else "soak_distributed.json"
        )
        (path / name).write_text(json.dumps(out, indent=1))
        print(json.dumps(out, indent=1))
        sys.exit(0 if out["ok_verdict"] else 1)
    finally:
        for p in reversed(procs):
            p.stop()


if __name__ == "__main__":
    main()

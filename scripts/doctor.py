#!/usr/bin/env python3
"""doctor: one-shot rule-based diagnosis of a dynamo-tpu fleet.

Snapshots the metrics service's `/v1/fleet`, `/v1/debug/flight` and
`/v1/debug/programs`, runs the rule set below over them, and prints one
human-readable report — the "why is this worker slow/stuck" companion
to fleet_top's "what are the numbers" view:

    python scripts/doctor.py --url http://127.0.0.1:9091
    python scripts/doctor.py --snapshot fleet.json --flight flight.json

Rules (each emits severity + worker + evidence + suggested action):
  compile-storm        compile events keep firing in the recent flight
                       window — the program family is churning in steady
                       state (every miss is a full XLA compile)
  pool-exhaustion      free pages pinned at ~0 with the watermark at
                       capacity and/or preemption-by-recompute firing in
                       the window — the KV pool is too small for the
                       workload (preemption thrash burns recompute)
  stalled-worker       the stall watchdog diagnosed wedged streams
                       (stalls_total > 0), or a worker with running
                       requests shows no flight activity
  decode-stall         pure prefill steps are interleaving with decode
                       rows waiting (mixed steps off or ineffective) —
                       running requests pay whole prefill drains as ITL
  dead-worker          a worker stopped publishing (last_seen_s beyond
                       the threshold)
  draining-worker      a worker reports state=draining (planned wind-
                       down via SIGTERM / POST /v1/admin/drain) — an
                       info note, and the dead/stalled rules are
                       suppressed for it so a drain never pages
  handover-worker /    a worker reports state=handover (live KV
  handover-stuck       migration, POST /v1/admin/handover) — info while
                       fresh; escalates to handover-stuck when it went
                       SILENT past the dead threshold mid-migration
                       (the fallback-to-drain path should have ended it)
  handover-fallback-   handovers keep degrading to plain drain fleet-
  storm                wide — successors refusing or the transfer plane
                       failing; upgrades silently lose their warm-KV
                       benefit
  migration-storm      the KV economy's per-prefix migrations are
                       thrashing fleet-wide: transfers keep degrading to
                       cold prefill (the transfer plane is failing), or
                       migrations fire on so large a share of requests
                       that the same hot prefixes must be ping-ponging
                       between workers (backoff / break-even threshold
                       misconfigured)
  tier-pressure        a worker's HBM pool is pegged while its KVBM
                       tier traffic is dominated by DISK hits — the hot
                       working set has been demoted past host slab and
                       every warm hit now pays an NVMe promotion; the
                       fix is HBM capacity (or a higher demotion
                       threshold), not more tiering
  overload             bounded admission is rejecting (overload_rejects
                       climbing -> "shedding, raise capacity"), or the
                       waiting queue is deep while the role burns its
                       SLO budget with ZERO rejects -> "queue unbounded,
                       enable admission caps" (docs/operations.md)
  skewed-worker        one worker's token throughput sits far below its
                       role's mean — a limping replica drags the whole
                       pool's SLA
  sla-burn             a role is burning its error budget (burn rate >1
                       in the merged windows)
  kv-index-drift       the KV-aware routers' prefix index detected
                       sequence gaps / digest drift: info when repaired
                       (resyncs converged), warning while subtrees sit
                       stale (those workers route cold), critical when
                       resyncs keep failing and the index cannot
                       converge
  planner-oscillation  the closed-loop planner's recent decisions
                       alternate scale directions on one role (or flips
                       storm) inside the cooldown window — hysteresis /
                       cooldown knobs are misconfigured and the fleet
                       is thrashing spawn/drain cycles
  sla-unrecovered      the planner has been at its max_decode clamp for
                       N+ consecutive ticks while the fleet still burns
                       its SLO budget — scaling is out of headroom; the
                       fix is capacity or shedding, not the loop
  low-attainment       a program kind's measured ms/dispatch sits far
                       off its cost-model roofline (GET /v1/debug/
                       programs) — host-loop overhead, not the chip, is
                       the limit (ROADMAP item 3)
  slow-trace-          the N worst KEPT traces (metrics service
  attribution          GET /v1/traces?sort=duration — the tail sampler
                       keeps every anomalous trace) are dominated by an
                       actionable phase: queue_wait -> scale the pool /
                       cap admission, transfer -> check the disagg
                       planes, dispatch -> router retries, decode_stall
                       -> enable mixed steps, replay_gap -> worker churn
  control-plane-       the broker is unreachable (or a worker reports
  degraded             broker-less degraded mode): the fleet serves from
                       cached discovery, KV scores go stale-cold, the
                       planner HOLDs — warning while frames are still
                       fresh / one worker degraded, CRITICAL when the
                       metrics service itself is degraded and the whole
                       fleet's frames have gone stale (docs/operations.md
                       "Control-plane HA")
  replication-lag      the warm standby's acked replication watermark
                       trails the primary's journal by more than the
                       threshold — promoting NOW would lose that tail
                       (leases/keys/ring records); hold the failover or
                       find the lagging link

`diagnose()` is pure (snapshots in, findings out) and unit-tested
against recorded snapshots in tests/test_doctor.py. Dependency-free
(urllib only), like fleet_top.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Optional

#: last_seen_s beyond this = the worker stopped publishing
DEAD_AFTER_S = 10.0
#: fraction of the role-mean tok/s below which a worker counts as skewed
SKEW_FRACTION = 0.25
#: compile events in more than this fraction of the recent window's
#: steps = the program family is churning, not warming up
COMPILE_STORM_FRACTION = 0.3
#: free pages at or below this fraction of total = exhausted
POOL_FREE_FRACTION = 0.02
#: decode-attainment below this = the host loop, not the chip, rules
ATTAINMENT_FLOOR = 0.05
#: waiting queue deeper than max(this, 4x running) while the role burns
#: its SLO budget = saturated with no admission caps
QUEUE_DEPTH_FLOOR = 8
#: consecutive burn-above-band ticks at the max_decode clamp before
#: sla-unrecovered fires
BURN_UNRECOVERED_TICKS = 5
#: direction reversals (up->down->up on one role) inside the oscillation
#: window before planner-oscillation fires
OSCILLATION_REVERSALS = 2
#: flip pairs inside the flip oscillation window before a storm fires
FLIP_STORM_COUNT = 2
#: the oscillation window is this multiple of the advertised cooldown:
#: ControlRunner already ENFORCES the cooldown (recorded same-role
#: decisions are never closer than cooldown_s apart), so the thrash
#: signature is a reversal landing shortly AFTER each cooldown expiry —
#: up at t, down at t+cooldown, up at t+2*cooldown. Comparing against
#: the bare cooldown would make the rule unsatisfiable.
OSCILLATION_WINDOW_FACTOR = 3.0
#: standby replication lag (records behind the primary's journal) above
#: which the standby is not safe to promote
REPL_LAG_WARN_RECORDS = 256
#: fallback window (seconds) when the frame advertises no cooldown
OSCILLATION_WINDOW_FLOOR_S = 60.0
#: handover drain-fallbacks (exceeding completions) before the
#: fallback-storm rule fires
FALLBACK_STORM_COUNT = 3
#: per-prefix migration fallbacks (exceeding completions) before
#: migration-storm's transfer-failure branch fires
MIGRATION_FALLBACK_STORM_COUNT = 3
#: completed migrations below this never count as churn — a warming
#: fleet legitimately migrates its first few hot prefixes
MIGRATION_CHURN_FLOOR = 10
#: completed migrations per fleet request above which the same hot
#: prefixes must be ping-ponging between workers (the router's backoff
#: window or break-even threshold is set too loose)
MIGRATION_CHURN_RATIO = 0.2
#: tiered (host+disk) KV hits before tier-pressure can judge the mix
TIER_HIT_FLOOR = 8
#: disk share of tiered hits above which the hot working set has been
#: demoted past the host slab onto NVMe
TIER_DISK_HIT_SHARE = 0.5
#: host-skew (multi-host SPMD stragglers): a host whose worst dispatch
#: p95 exceeds the fastest host's by this ratio is a straggler — under
#: lockstep SPMD every dispatch waits for it (docs/observability.md
#: "Reading the perf plane")
HOST_SKEW_RATIO = 1.5
#: dispatch p95s below this never count as skew — sub-threshold jitter
#: on near-idle hosts is noise, not a straggler
HOST_SKEW_FLOOR_MS = 5.0
#: worst kept traces the slow-trace-attribution rule examines
TRACE_WORST_N = 5
#: a phase must explain at least this share of a trace's wall time to
#: count as its dominant phase for attribution
TRACE_DOMINANT_SHARE = 0.4
#: traces shorter than this never attribute — a 2 ms admin call is
#: trivially "dominated" by whatever it did, not a latency problem
TRACE_MIN_TOTAL_MS = 50.0
#: dominant-phase -> what to do about it. decode/prefill-dominant slow
#: traces are just long generations — not findings.
TRACE_PHASE_ACTIONS = {
    "queue_wait": (
        "requests spend their time waiting for admission — scale the "
        "pool up (planner --mode closed does this on burn) or enable "
        "admission caps (--max-waiting / --max-inflight) so excess "
        "load answers 429 instead of queueing"
    ),
    "transfer": (
        "the disagg KV hand-off dominates — check which transfer plane "
        "requests actually ride (dynamo_tpu_worker_kv_transfer_*: a "
        "device/shm plane silently falling back to inline host doubles "
        "the hand-off) and the prefill queue depth"
    ),
    "dispatch": (
        "router dispatch overhead dominates — workers are refusing or "
        "down (read the router.dispatch spans' mark_down/overloaded "
        "events and retry_backoff_ms in the kept traces)"
    ),
    "decode_stall": (
        "prefill-induced decode stalls dominate — enable mixed steps "
        "(drop --no-mixed-steps) so decode rows keep emitting while "
        "prompt bursts drain (docs/engine.md 'Mixed steps')"
    ),
    "replay_gap": (
        "time lost between stream-replay attempts dominates — workers "
        "are dying mid-stream; GET /v1/fleet/events names the kills/"
        "handovers these traces overlapped"
    ),
}


def _finding(severity: str, rule: str, worker: Optional[str], summary: str,
             evidence: dict, action: str) -> dict:
    return {
        "severity": severity, "rule": rule, "worker": worker,
        "summary": summary, "evidence": evidence, "action": action,
    }


def _flight_records(flight: dict, iid: str) -> list[dict]:
    w = (flight or {}).get("workers", {}).get(iid) or {}
    recs = w.get("records")
    return recs if isinstance(recs, list) else []


def diagnose(
    fleet: dict,
    flight: Optional[dict] = None,
    programs: Optional[dict] = None,
    traces: Optional[dict] = None,
    ledger: Optional[list] = None,
) -> list[dict]:
    """Pure rule pass: (/v1/fleet, /v1/debug/flight, /v1/debug/programs,
    /v1/traces) snapshots [+ perf-ledger rows] -> ordered findings
    (severity: critical > warning > info)."""
    findings: list[dict] = []
    workers = (fleet or {}).get("workers") or {}
    roles = (fleet or {}).get("roles") or {}
    findings.extend(_control_plane_rules(fleet, workers))
    #: flight data present at all? The silent-worker rule needs the
    #: distinction between "no flight doc" and "enabled but silent"
    flight_collected = bool((flight or {}).get("workers"))

    # per-role token-throughput means for the skew rule
    role_tok: dict[str, list[float]] = {}
    for iid, w in workers.items():
        role_tok.setdefault(str(w.get("role", "?")), []).append(
            float(w.get("tok_s") or 0.0)
        )
    role_mean = {
        r: (sum(v) / len(v) if v else 0.0) for r, v in role_tok.items()
    }
    #: worst (shortest-window) burn rate per role, for the overload rule
    role_burn: dict[str, float] = {}
    for role, r in roles.items():
        for wd in ((r.get("slo") or {}).get("windows") or {}).values():
            burn = (wd or {}).get("burn_rate")
            if burn is not None:
                role_burn[role] = max(role_burn.get(role, 0.0), float(burn))

    #: fleet-wide handover fallback tally (storm rule below)
    handover_done = handover_fb = 0
    #: fleet-wide KV-economy migration tally (migration-storm rule below)
    migration_done = migration_fb = fleet_requests = 0

    for iid, w in sorted(workers.items()):
        age = float(w.get("last_seen_s") or 0.0)
        handover_done += int(w.get("handovers_total") or 0)
        handover_fb += int(w.get("handover_fallbacks_total") or 0)
        migration_done += int(w.get("kv_migrations_total") or 0)
        migration_fb += int(w.get("kv_migration_fallbacks_total") or 0)
        fleet_requests += int(w.get("requests_received") or 0)
        if str(w.get("state") or "") == "handover":
            # live KV migration (POST /v1/admin/handover / planner
            # scale-down / rolling upgrade): planned, suppress the
            # dead/stalled/skew rules like a drain. But EVERY phase is
            # deadline-bounded and any failure degrades to drain — a
            # handover that went silent past the dead threshold is stuck.
            wedged = age > DEAD_AFTER_S
            findings.append(_finding(
                "warning" if wedged else "info",
                "handover-stuck" if wedged else "handover-worker", iid,
                (f"{iid} is mid-handover but went silent "
                 f"(last_seen {age:.1f}s ago, phase="
                 f"{w.get('handover_phase') or '?'}) — the fallback-to-"
                 "drain path should have ended this"
                 if wedged else
                 f"{iid} is handing over (phase="
                 f"{w.get('handover_phase') or '?'}, "
                 f"{w.get('num_running') or 0} running)"),
                {"state": "handover", "last_seen_s": age,
                 "handover_phase": w.get("handover_phase"),
                 "num_running": w.get("num_running"),
                 "handover_bytes_total": w.get("handover_bytes_total")},
                ("check the worker's JSONL log for the stuck phase; if "
                 "the process is alive, SIGTERM it — the drain path "
                 "still exits 0 and streams replay on survivors"
                 if wedged else
                 "no action: KV pages are migrating to a successor; the "
                 "worker exits 0 when done (or falls back to a plain "
                 "drain on any failure)"),
            ))
            continue
        if str(w.get("state") or "") == "draining":
            # planned wind-down (SIGTERM / POST /v1/admin/drain): the
            # dead/stalled/skew rules below would misread a drain as an
            # outage — suppress them. But a drain is supposed to END
            # (budget default 30s, then exit 0 and the snapshot entry
            # ages out) — one that went SILENT past the dead threshold
            # is a wedged drain, which must still surface as a warning.
            # (stalls_total is lifetime-cumulative, so a pre-drain stall
            # must not read as a wedged drain — only silence does.)
            wedged = age > DEAD_AFTER_S
            findings.append(_finding(
                "warning" if wedged else "info", "draining-worker", iid,
                (f"{iid} is draining but looks wedged "
                 f"(last_seen {age:.1f}s ago) — the drain budget "
                 "should have ended this"
                 if wedged else
                 f"{iid} is draining (planned wind-down; "
                 f"{w.get('num_running') or 0} running)"),
                {"state": "draining", "last_seen_s": age,
                 "num_running": w.get("num_running"),
                 "stalls_total": w.get("stalls_total")},
                ("verify the process exited 0; if it is still alive "
                 "past its --drain-budget, read its /v1/debug/stalls "
                 "and JSONL log — in-flight work may be wedged"
                 if wedged else
                 "no action: the worker deregistered and is finishing "
                 "in-flight requests; it exits 0 when drained (or when "
                 "its --drain-budget lapses)"),
            ))
            continue
        if age > DEAD_AFTER_S:
            findings.append(_finding(
                "critical", "dead-worker", iid,
                f"{iid} stopped publishing {age:.1f}s ago",
                {"last_seen_s": age},
                "check the worker process / its fabric connection; "
                "deregister or restart it",
            ))
            continue  # stale numbers would double-diagnose below

        stalls = int(w.get("stalls_total") or 0)
        if stalls > 0:
            findings.append(_finding(
                "critical", "stalled-worker", iid,
                f"{iid} diagnosed {stalls} stalled stream(s) "
                f"({w.get('stalls_by_cause')})",
                {"stalls_total": stalls,
                 "stalls_by_cause": w.get("stalls_by_cause")},
                "read the watchdog diagnosis in the worker's JSONL log "
                "(thread stacks + flight window + trace ids); "
                "GET /v1/debug/stalls on the worker's process",
            ))

        recs = _flight_records(flight or {}, iid)
        if recs:
            n = len(recs)
            compile_steps = sum(1 for r in recs if r.get("compiles"))
            if n >= 8 and compile_steps / n > COMPILE_STORM_FRACTION:
                findings.append(_finding(
                    "warning", "compile-storm", iid,
                    f"{iid}: compile events in {compile_steps}/{n} of the "
                    "recent steps — the program family is churning",
                    {"compile_steps": compile_steps, "window": n},
                    "inspect GET /v1/debug/programs for the churning "
                    "kind; pin decode buckets / prefill chunking so "
                    "shapes stop multiplying",
                ))
            preempted = sum(r.get("preempted", 0) for r in recs)
            free = recs[-1].get("free_pages", None)
            total = int(w.get("kv_total_pages") or 0)
            if preempted > 0 or (
                free is not None and total
                and free <= total * POOL_FREE_FRACTION
            ):
                findings.append(_finding(
                    "warning", "pool-exhaustion", iid,
                    f"{iid}: page pool under pressure (free={free}, "
                    f"watermark={recs[-1].get('watermark')}, "
                    f"preemptions_in_window={preempted})",
                    {"free_pages": free, "preempted": preempted,
                     "watermark": recs[-1].get("watermark"),
                     "total_pages": total},
                    "grow --num-pages (or add workers / enable "
                    "--kv-quantize int8 for ~2x effective capacity); "
                    "preemption-by-recompute burns whole prompts",
                ))
            # prefill-induced decode stall: pure prefill dispatches while
            # decode rows exist and no mixed steps are being taken
            pure_prefill = sum(
                1 for r in recs
                if r.get("kind") == "prefill" and r.get("running", 0) > r.get("n_prefill", 0)
            )
            mixed_steps = sum(1 for r in recs if r.get("kind") == "mixed")
            if pure_prefill >= 3 and mixed_steps == 0:
                findings.append(_finding(
                    "warning", "decode-stall", iid,
                    f"{iid}: {pure_prefill} pure prefill steps ran while "
                    "decode rows waited and no mixed steps fired — "
                    "running requests pay the prefill drain as ITL",
                    {"pure_prefill_steps": pure_prefill,
                     "mixed_steps": mixed_steps, "window": n},
                    "enable mixed steps (drop --no-mixed-steps) or lower "
                    "the prefill budget; see docs/engine.md 'Mixed steps'",
                ))
        elif flight_collected and int(w.get("num_running") or 0) > 0:
            # only meaningful when flight data WAS collected for this
            # fleet — in --snapshot-only mode (no flight doc) a busy
            # worker with no records is the norm, not a wedge
            findings.append(_finding(
                "warning", "stalled-worker", iid,
                f"{iid}: {w.get('num_running')} running request(s) but no "
                "recent flight records — the engine loop may be wedged",
                {"num_running": w.get("num_running")},
                "check the worker's /v1/debug/stalls and JSONL log; a "
                "dispatch stuck in the device tunnel shows in the "
                "engine thread's stack",
            ))

        # overload (docs/operations.md "Overload & draining"): two
        # mirror-image states — bounded admission actively shedding
        # (capacity is the fix), vs a deep unbounded queue silently
        # burning the SLO budget (admission caps are the fix)
        rejects = int(w.get("overload_rejects") or 0)
        waiting = int(w.get("num_waiting") or 0)
        running = int(w.get("num_running") or 0)
        burn = role_burn.get(str(w.get("role", "?")), 0.0)
        if rejects > 0:
            findings.append(_finding(
                "warning", "overload", iid,
                f"{iid}: bounded admission rejected {rejects} request(s) "
                f"(waiting={waiting}) — this worker is shedding",
                {"overload_rejects": rejects, "num_waiting": waiting,
                 "num_running": running,
                 "deadline_expired": w.get("deadline_expired")},
                "shedding is working as designed; raise capacity (add "
                "workers / grow the pool) if the 429 rate is above what "
                "clients tolerate — dynamo_tpu_shed_total{reason} at the "
                "frontend names the shed reasons",
            ))
        elif waiting > max(QUEUE_DEPTH_FLOOR, 4 * running) and burn > 1.0:
            findings.append(_finding(
                "warning", "overload", iid,
                f"{iid}: {waiting} requests queued against {running} "
                f"running while the role burns its SLO budget at "
                f"{burn:.1f}x, with ZERO admission rejects — the queue "
                "is unbounded",
                {"num_waiting": waiting, "num_running": running,
                 "burn_rate": burn, "overload_rejects": 0},
                "enable admission caps (--max-waiting on workers, "
                "--max-inflight at the frontend) so excess load answers "
                "429 + Retry-After instead of queueing past its deadline",
            ))

        # tier-pressure (docs/operations.md "The KV economy"): the HBM
        # pool is pegged at its demotion watermark AND the KVBM tier
        # traffic is dominated by DISK hits — the hot working set has
        # been demoted past the host slab, so every "warm" hit now pays
        # an NVMe promotion. More tiering can't fix that; HBM capacity
        # (or a higher demotion threshold) can.
        host_hits = int(w.get("kvbm_host_hits_total") or 0)
        disk_hits = int(w.get("kvbm_disk_hits_total") or 0)
        tier_hits = host_hits + disk_hits
        demotions = int(w.get("kvbm_demotions_total") or 0)
        free_pages = w.get("kv_free_pages")
        total_pages = int(w.get("kv_total_pages") or 0)
        hbm_pegged = (
            free_pages is not None and total_pages > 0
            and int(free_pages) <= total_pages * POOL_FREE_FRACTION
        )
        if (
            demotions > 0 and hbm_pegged and tier_hits >= TIER_HIT_FLOOR
            and disk_hits >= tier_hits * TIER_DISK_HIT_SHARE
        ):
            findings.append(_finding(
                "warning", "tier-pressure", iid,
                f"{iid}: HBM pool pegged ({free_pages}/{total_pages} "
                f"free) with {disk_hits}/{tier_hits} tiered KV hits "
                "served from DISK — the hot working set was demoted "
                "past host slab and warm hits now pay NVMe promotion",
                {"kv_free_pages": free_pages,
                 "kv_total_pages": total_pages,
                 "kvbm_demotions_total": demotions,
                 "kvbm_host_hits_total": host_hits,
                 "kvbm_disk_hits_total": disk_hits,
                 "kvbm_host_blocks": w.get("kvbm_host_blocks"),
                 "kvbm_disk_blocks": w.get("kvbm_disk_blocks")},
                "add HBM capacity (workers or --num-pages) or raise the "
                "demotion threshold so the hot set stays resident; the "
                "router already discounts disk-tier warmth, so persistent "
                "disk hits mean demand, not misrouting",
            ))

        mean = role_mean.get(str(w.get("role", "?")), 0.0)
        tok = float(w.get("tok_s") or 0.0)
        if mean > 1.0 and tok < mean * SKEW_FRACTION:
            findings.append(_finding(
                "warning", "skewed-worker", iid,
                f"{iid}: {tok:.1f} tok/s vs role mean {mean:.1f} — a "
                "limping replica drags the pool's SLA",
                {"tok_s": tok, "role_mean_tok_s": round(mean, 1)},
                "compare its flight window and /v1/debug/programs "
                "attainment against a healthy peer; drain + restart if "
                "the hardware is degraded",
            ))

    for role, r in sorted(roles.items()):
        slo = r.get("slo") or {}
        for win, wd in sorted((slo.get("windows") or {}).items()):
            burn = (wd or {}).get("burn_rate")
            if burn is not None and burn > 1.0:
                findings.append(_finding(
                    "warning", "sla-burn", None,
                    f"role {role}: burning error budget at {burn:.1f}x "
                    f"over the {win}s window "
                    f"(attainment {wd.get('attainment')})",
                    {"role": role, "window_s": win, "burn_rate": burn},
                    "scale the role up (planner/operator) or shed load; "
                    "fleet_top's BURN column names the worst workers",
                ))

    if handover_fb >= FALLBACK_STORM_COUNT and handover_fb > handover_done:
        findings.append(_finding(
            "warning", "handover-fallback-storm", None,
            f"{handover_fb} handover(s) degraded to plain drain vs "
            f"{handover_done} completed — upgrades are losing their "
            "warm-KV benefit fleet-wide",
            {"handover_fallbacks_total": handover_fb,
             "handovers_total": handover_done},
            "read the retiring workers' logs for the failing phase "
            "(extract / offer / transfer / adopt); common causes: "
            "successors with full pools, a partitioned transfer plane, "
            "or single-worker pools with no successor at all",
        ))

    # migration-storm: two failure signatures over the KV economy's
    # per-prefix migrations. (1) transfers keep DEGRADING — every
    # attempt falls back to cold prefill, so the fleet pays migration
    # overhead with none of the warm-TTFT benefit. (2) transfers
    # SUCCEED but fire on so large a share of requests that the same
    # hot prefixes must be ping-ponging between workers.
    if (
        migration_fb >= MIGRATION_FALLBACK_STORM_COUNT
        and migration_fb > migration_done
    ):
        findings.append(_finding(
            "warning", "migration-storm", None,
            f"{migration_fb} prefix migration(s) degraded to cold "
            f"prefill vs {migration_done} completed — the KV economy "
            "is paying transfer overhead with no warm-TTFT benefit",
            {"kv_migration_fallbacks_total": migration_fb,
             "kv_migrations_total": migration_done},
            "read the source workers' logs for the failing phase "
            "(extract / offer / transfer); common causes: destinations "
            "with full pools or a partitioned transfer plane — the "
            "router's backoff fences repeat attempts, but the break-even "
            "gate cannot see transport failures",
        ))
    elif (
        migration_done >= MIGRATION_CHURN_FLOOR
        and migration_done > fleet_requests * MIGRATION_CHURN_RATIO
    ):
        findings.append(_finding(
            "warning", "migration-storm", None,
            f"{migration_done} prefix migration(s) completed against "
            f"{fleet_requests} fleet request(s) — more than one "
            f"migration per {int(1 / MIGRATION_CHURN_RATIO)} requests "
            "means hot prefixes are ping-ponging between workers",
            {"kv_migrations_total": migration_done,
             "fleet_requests_received": fleet_requests,
             "kv_migration_fallbacks_total": migration_fb},
            "raise the router's migration backoff window and/or "
            "DYN_KV_ECONOMY_MIN_FLOPS_PER_BYTE so only clearly "
            "profitable moves clear the break-even gate; see "
            "docs/operations.md 'The KV economy'",
        ))

    findings.extend(_kv_index_rules((fleet or {}).get("kv_index")))
    findings.extend(_planner_rules((fleet or {}).get("planner")))
    findings.extend(_trace_rules(traces, workers))
    findings.extend(_host_skew_rules(workers))
    findings.extend(_perf_regression_rules(ledger))

    for iid, p in sorted(((programs or {}).get("workers") or {}).items()):
        for kind, k in sorted((p.get("kinds") or {}).items()):
            att = k.get("attainment")
            if att is not None and att < ATTAINMENT_FLOOR and kind in (
                "decode", "decode_multi", "mixed"
            ):
                findings.append(_finding(
                    "info", "low-attainment", iid,
                    f"{iid}: {kind} runs at {att * 100:.2f}% of its "
                    "cost-model roofline "
                    f"({k.get('measured_ms_per_dispatch')}ms measured vs "
                    f"{k.get('roofline_ms')}ms roofline)",
                    {"kind": kind, **{
                        f: k.get(f) for f in (
                            "attainment", "measured_ms_per_dispatch",
                            "roofline_ms", "flops", "bytes",
                        )
                    }},
                    "the host loop, not the chip, is the limit — see "
                    "docs/PERF.md (decode roofline) and ROADMAP item 3 "
                    "(on-device multi-step scheduling)",
                ))

    order = {"critical": 0, "warning": 1, "info": 2}
    findings.sort(key=lambda f: (order.get(f["severity"], 9), str(f["worker"])))
    return findings


def _host_skew_rules(workers: dict) -> list[dict]:
    """host-skew: under multi-host SPMD every lockstep dispatch runs at
    the SLOWEST host's pace — group the live workers' flight-window
    dispatch p95 by their `host` (jax.process_index()) and name the
    straggler. Needs >= 2 hosts reporting; single-host fleets (and
    workers without the HBM/mesh plane) never fire it."""
    by_host: dict[str, float] = {}
    members: dict[str, list[str]] = {}
    for iid, w in sorted(workers.items()):
        p95 = w.get("dispatch_p95_ms")
        if not isinstance(p95, (int, float)):
            continue
        if float(w.get("last_seen_s") or 0.0) > DEAD_AFTER_S:
            continue  # the dead-worker rule owns stale frames
        h = str(int(w.get("host") or 0))
        by_host[h] = max(by_host.get(h, 0.0), float(p95))
        members.setdefault(h, []).append(iid)
    if len(by_host) < 2:
        return []
    fastest = min(by_host.values())
    out: list[dict] = []
    for h, p95 in sorted(by_host.items()):
        if p95 < HOST_SKEW_FLOOR_MS:
            continue
        if fastest > 0 and p95 > fastest * HOST_SKEW_RATIO:
            out.append(_finding(
                "warning", "host-skew", None,
                f"host {h} dispatches at p95 {p95:.1f}ms vs the fastest "
                f"host's {fastest:.1f}ms ({p95 / fastest:.1f}x) — under "
                "lockstep SPMD every dispatch waits for it",
                {"host": h, "dispatch_p95_ms": p95,
                 "fastest_host_p95_ms": fastest,
                 "workers": members.get(h, [])},
                "compare GET /v1/debug/mesh dispatch sections across "
                "hosts; look for thermal throttling, a noisy neighbor, "
                "or host-side input work pinned to that process "
                "(docs/observability.md 'Reading the perf plane')",
            ))
    return out


def _import_perf_ledger():
    """Lazy import of dynamo_tpu.telemetry.perf_ledger — the doctor
    stays dependency-free unless the ledger plane is actually used.
    Running as `python scripts/doctor.py` puts scripts/ (not the repo
    root) on sys.path, so fall back to the parent directory."""
    try:
        from dynamo_tpu.telemetry import perf_ledger
    except ImportError:
        import os

        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        try:
            from dynamo_tpu.telemetry import perf_ledger
        except ImportError:
            return None
    return perf_ledger


def _perf_regression_rules(ledger: Optional[list]) -> list[dict]:
    """perf-regression: compare each round's latest ledger row against
    the previous ok row with the SAME config fingerprint (same
    workload), using the shared tolerance bands. Fires one warning per
    regressed comparison — the doctor flags drift; scripts/perf_diff.py
    is the CI gate."""
    if not ledger:
        return []
    perf_ledger = _import_perf_ledger()
    if perf_ledger is None:
        return []
    by_round = perf_ledger.rows_by_round(ledger)
    ordered = [r for r in by_round.values() if r["ok"]]
    out: list[dict] = []
    for prev, cur in zip(ordered, ordered[1:]):
        if prev.get("fingerprint") != cur.get("fingerprint"):
            continue
        result = perf_ledger.compare_rows(prev, cur)
        if not result["regressions"]:
            continue
        worst = max(
            (r for r in result["rows"] if r["verdict"] == "REGRESSION"),
            key=lambda r: abs(r["rel"] or 0.0),
        )
        out.append(_finding(
            "warning", "perf-regression", None,
            f"round {cur['round']} regressed "
            f"{', '.join(result['regressions'])} vs {prev['round']} "
            f"(worst: {worst['metric']} {worst['rel']:+.1%}, band "
            f"{worst['band']:.0%})",
            {"round_a": prev["round"], "round_b": cur["round"],
             "fingerprint": cur.get("fingerprint"),
             "regressions": result["regressions"],
             "rows": [r for r in result["rows"]
                      if r["verdict"] == "REGRESSION"]},
            "rerun the round to rule out noise, then bisect: "
            f"`python scripts/perf_diff.py {prev['round']} "
            f"{cur['round']}` shows the full table "
            "(docs/observability.md 'Reading the perf plane')",
        ))
    return out


def _control_plane_rules(fleet: dict, workers: dict) -> list[dict]:
    """control-plane-degraded + replication-lag over the /v1/fleet
    `control_plane` section (docs/operations.md "Control-plane HA")."""
    out: list[dict] = []
    cp = (fleet or {}).get("control_plane") or {}
    if cp.get("degraded"):
        ages = [
            float(w.get("last_seen_s") or 0.0) for w in workers.values()
        ]
        all_stale = not ages or all(a > DEAD_AFTER_S for a in ages)
        out.append(_finding(
            "critical" if all_stale else "warning",
            "control-plane-degraded", None,
            (
                "the metrics service cannot reach any broker "
                f"({cp.get('disconnected_s', 0)}s) and every worker's "
                "frames are stale — the WHOLE fleet is in broker-less "
                "degraded mode (serving from cached discovery, KV "
                "scores stale-cold, planner holding)"
                if all_stale else
                "the metrics service lost its broker "
                f"({cp.get('disconnected_s', 0)}s); worker frames are "
                "still fresh, so this may be a partial partition"
            ),
            {"disconnected_s": cp.get("disconnected_s"),
             "addresses": cp.get("addresses"),
             "degraded_total": cp.get("degraded_total"),
             "workers_stale": all_stale},
            "restart/restore a broker (or promote the standby: `run "
            "fabric --promote <standby>`); chats keep serving over "
            "direct ingress meanwhile, and KV indexes resync on "
            "reconnect",
        ))
    for iid, w in sorted(workers.items()):
        if int(w.get("degraded") or 0) and float(
            w.get("last_seen_s") or 0.0
        ) <= DEAD_AFTER_S:
            out.append(_finding(
                "warning", "control-plane-degraded", iid,
                f"{iid} reports broker-less degraded mode "
                f"(dropped {w.get('kv_events_dropped_total') or 0} KV "
                f"event(s), {w.get('kv_events_pending') or 0} pending)",
                {"degraded": 1,
                 "degraded_entries_total": w.get("degraded_entries_total"),
                 "kv_events_dropped_total":
                     w.get("kv_events_dropped_total"),
                 "kv_events_pending": w.get("kv_events_pending")},
                "this worker cannot reach the broker others can — check "
                "its --fabric list and the network path; its KV events "
                "buffer (bounded) and the index resyncs on reconnect",
            ))
    broker = cp.get("broker") or {}
    lag = int(broker.get("repl_lag_records") or 0)
    if int(broker.get("repl_subscribers") or 0) > 0 and (
        lag > REPL_LAG_WARN_RECORDS
    ):
        out.append(_finding(
            "warning", "replication-lag", None,
            f"the warm standby trails the primary's journal by {lag} "
            f"records — promoting now would LOSE that tail",
            {"repl_lag_records": lag,
             "repl_subscribers": broker.get("repl_subscribers"),
             "fence": broker.get("fence")},
            "hold any manual failover; check the standby host/link "
            "(fabric repl_lag_records should sit near 0) — the detector "
            "still promotes on primary death, accepting the gap "
            "(sequencing consumers resync)",
        ))
    return out


def _trace_rules(traces: Optional[dict], workers: dict) -> list[dict]:
    """slow-trace-attribution: attribute each of the N worst KEPT traces
    (the fleet trace plane's GET /v1/traces, tail-sampled so anomalies
    are all there) to its dominant breakdown phase; actionable dominant
    phases fold into one finding per phase, naming the traces and — when
    the traces agree on a pool — the role to act on."""
    findings: list[dict] = []
    if not isinstance(traces, dict):
        return findings
    kept = [t for t in traces.get("traces") or [] if isinstance(t, dict)]
    kept.sort(
        key=lambda t: float(t.get("duration_ms") or 0.0), reverse=True
    )
    by_phase: dict[str, list[dict]] = {}
    for t in kept[:TRACE_WORST_N]:
        bd = t.get("breakdown") or {}
        total = float(bd.get("total_ms") or 0.0)
        dominant = bd.get("dominant")
        if not dominant or total < TRACE_MIN_TOTAL_MS:
            continue
        share = float((bd.get("phases") or {}).get(dominant) or 0.0) / total
        if share < TRACE_DOMINANT_SHARE:
            continue
        if dominant in TRACE_PHASE_ACTIONS:
            by_phase.setdefault(dominant, []).append(t)
    for phase, ts in sorted(by_phase.items()):
        roles = {
            str((workers.get(w) or {}).get("role"))
            for t in ts
            for w in t.get("workers") or ()
            if w in workers
        } - {"None"}
        pool = (
            f" on the {next(iter(roles))} pool" if len(roles) == 1 else ""
        )
        worst = ts[0]
        findings.append(_finding(
            "warning", "slow-trace-attribution", None,
            f"{len(ts)} of the {min(TRACE_WORST_N, len(kept))} worst "
            f"kept traces are dominated by {phase}{pool} (worst: "
            f"{worst.get('trace_id')} at "
            f"{float(worst.get('duration_ms') or 0):.0f} ms, "
            f"{float((worst.get('breakdown') or {}).get('phases', {}).get(phase) or 0):.0f} ms "
            f"in {phase})",
            {"phase": phase, "roles": sorted(roles),
             "traces": [
                 {"trace_id": t.get("trace_id"),
                  "duration_ms": t.get("duration_ms"),
                  "kept_reasons": t.get("kept_reasons"),
                  "breakdown": (t.get("breakdown") or {}).get("phases")}
                 for t in ts
             ]},
            TRACE_PHASE_ACTIONS[phase],
        ))
    return findings


def _kv_index_rules(kv_index: Optional[dict]) -> list[dict]:
    """KV index consistency (fleet snapshot `kv_index` section,
    published by KV-aware routers over kv_index.status — docs/
    operations.md "KV index consistency"). Drift that is detected AND
    repaired is an info note (the plane converged); subtrees sitting
    stale are a warning (those workers route cold — real prefix hits
    are being recomputed); stale subtrees with FAILING resyncs are
    critical when repair has never succeeded (the index cannot
    converge: snapshot fetches are failing or sequencing is off)."""
    findings: list[dict] = []
    if not isinstance(kv_index, dict):
        return findings
    stale = int(kv_index.get("stale_workers") or 0)
    gaps = int(kv_index.get("gaps_total") or 0)
    mismatches = int(kv_index.get("digest_mismatches_total") or 0)
    resyncs = int(kv_index.get("resyncs_total") or 0)
    failures = int(kv_index.get("resync_failures_total") or 0)
    drift = int(kv_index.get("drift_blocks_total") or 0)
    evidence = {
        "stale_workers": stale, "gaps_total": gaps,
        "digest_mismatches_total": mismatches,
        "resyncs_total": resyncs, "resync_failures_total": failures,
        "drift_blocks_total": drift,
    }
    if stale > 0:
        wedged = failures > 0 and resyncs == 0
        findings.append(_finding(
            "critical" if wedged else "warning", "kv-index-drift", None,
            (f"{stale} index subtree(s) stale and every resync attempt "
             f"has failed ({failures} failure(s), 0 succeeded) — the "
             "prefix index cannot converge"
             if wedged else
             f"{stale} index subtree(s) stale — prefix routing scores "
             "those workers COLD until their resync lands (warm hits on "
             "them are being recomputed)"),
            evidence,
            ("check that workers run with KV sequencing enabled (no "
             "--no-kv-sequencing) and that the router can reach their "
             "ingress for kv.snapshot; a dead worker clears when its "
             "registration prunes"
             if wedged else
             "usually self-heals within an anti-entropy sweep; if stale "
             "persists, check the worker's ingress reachability and the "
             "router log's kv.snapshot fetch errors"),
        ))
    elif gaps or mismatches:
        findings.append(_finding(
            "info", "kv-index-drift", None,
            f"index drift was detected ({gaps} sequence gap(s), "
            f"{mismatches} digest mismatch(es)) and repaired by "
            f"{resyncs} resync(s), {drift} block(s) corrected — the "
            "event plane is lossy but converging",
            evidence,
            "no action needed now; a climbing gap rate means KV events "
            "are being dropped (fabric outages, ring overflow) — check "
            "dynamo_tpu_kv_index_gaps_total's rate and the fabric's "
            "health",
        ))
    return findings


def _planner_rules(planner: Optional[dict]) -> list[dict]:
    """Closed-loop planner health (fleet snapshot `planner` section,
    published by ControlRunner through the metrics service)."""
    findings: list[dict] = []
    if not isinstance(planner, dict):
        return findings
    setpoint = planner.get("setpoint") or {}
    cooldown = float(setpoint.get("cooldown_s") or 0.0)
    flip_cooldown = float(setpoint.get("flip_cooldown_s") or 0.0)
    osc_window = (
        cooldown * OSCILLATION_WINDOW_FACTOR
        if cooldown > 0.0
        else OSCILLATION_WINDOW_FLOOR_S
    )
    flip_window = (
        flip_cooldown * OSCILLATION_WINDOW_FACTOR
        if flip_cooldown > 0.0
        else OSCILLATION_WINDOW_FLOOR_S
    )
    recent = [
        d for d in (planner.get("recent_decisions") or [])
        if isinstance(d, dict)
    ]

    # planner-oscillation: alternating scale directions on one role
    # inside the oscillation window (a small multiple of the enforced
    # cooldown — see OSCILLATION_WINDOW_FACTOR) — the loop is chasing
    # its own wake
    by_role: dict = {}
    for d in sorted(recent, key=lambda d: float(d.get("ts") or 0.0)):
        if d.get("action") in ("scale_up", "scale_down") and d.get("role"):
            by_role.setdefault(str(d["role"]), []).append(d)
    for role, ds in sorted(by_role.items()):
        reversals = 0
        for a, b in zip(ds, ds[1:]):
            dt = float(b.get("ts") or 0.0) - float(a.get("ts") or 0.0)
            if a["action"] != b["action"] and dt < osc_window:
                reversals += 1
        if reversals >= OSCILLATION_REVERSALS:
            findings.append(_finding(
                "warning", "planner-oscillation", None,
                f"planner reversed scale direction on {role} {reversals} "
                f"time(s) within the {osc_window:.0f}s oscillation "
                "window — the control loop is flapping",
                {"role": role, "reversals": reversals,
                 "cooldown_s": cooldown, "window_s": osc_window,
                 "decisions": ds[-6:]},
                "widen the hysteresis band (burn_low/burn_high) or raise "
                "--cooldown; a loop that spawns then drains the same "
                "worker burns engine cold-starts for nothing",
            ))
    flips = [
        d for d in sorted(recent, key=lambda d: float(d.get("ts") or 0.0))
        if d.get("action") == "flip"
    ]
    # a storm is ALTERNATION (A->B then B->A — the same capacity bounced
    # back), not a same-direction flip train, which is a legitimate ramp
    # (e.g. flipping several idle prefill workers into a flash crowd)
    storm = sum(
        1
        for a, b in zip(flips, flips[1:])
        if (
            float(b.get("ts") or 0.0) - float(a.get("ts") or 0.0)
            < flip_window
            and (a.get("src"), a.get("dst")) == (b.get("dst"), b.get("src"))
        )
    )
    if storm >= FLIP_STORM_COUNT:
        findings.append(_finding(
            "warning", "planner-oscillation", None,
            f"{len(flips)} role flips with {storm} pair(s) inside the "
            f"{flip_window:.0f}s flip oscillation window — a flip storm "
            "thrashes pool roles (each flip drains a worker)",
            {"flips": len(flips), "storm_pairs": storm,
             "flip_cooldown_s": flip_cooldown,
             "window_s": flip_window},
            "raise --flip-cooldown or disable --flip until the pressure "
            "signals stop alternating between the pools",
        ))

    # sla-unrecovered: scaled to the ceiling, still burning
    burn_ticks = int(planner.get("burn_high_ticks") or 0)
    if burn_ticks >= BURN_UNRECOVERED_TICKS and planner.get("at_max"):
        signals = planner.get("signals") or {}
        limits = planner.get("limits") or {}
        findings.append(_finding(
            "critical", "sla-unrecovered", None,
            f"fleet has burned its SLO budget for {burn_ticks} "
            f"consecutive planner ticks with the decode pool pinned at "
            f"max_decode={limits.get('max_decode')} — the control loop "
            "is out of headroom",
            {"burn_high_ticks": burn_ticks,
             "burn_rate": signals.get("burn_rate"),
             "sla_attainment": signals.get("sla_attainment"),
             "limits": limits},
            "raise --max-decode (add capacity) or shed load "
            "(--shed-burn-threshold / --max-inflight); the planner "
            "cannot recover this SLA by itself",
        ))
    return findings


def render_report(fleet: dict, findings: list[dict]) -> str:
    """Findings -> the human-readable report."""
    n_workers = len((fleet or {}).get("workers") or {})
    out = [f"dynamo-tpu doctor: {n_workers} worker(s), "
           f"{len(findings)} finding(s)"]
    if not findings:
        out.append("  all clear: no rule fired")
        return "\n".join(out)
    for f in findings:
        head = f"[{f['severity'].upper():8}] {f['rule']}"
        if f["worker"]:
            head += f" @ {f['worker']}"
        out.append(head)
        out.append(f"  {f['summary']}")
        out.append(f"  -> {f['action']}")
    return "\n".join(out)


def _fetch(url: str, path: str) -> Optional[dict]:
    try:
        with urllib.request.urlopen(f"{url}{path}", timeout=5) as resp:
            return json.loads(resp.read().decode())
    except Exception as e:
        print(f"fetch {url}{path} failed: {e}", file=sys.stderr)
        return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--url", default="http://127.0.0.1:9091",
        help="metrics service base URL",
    )
    ap.add_argument(
        "--snapshot", default=None,
        help="recorded /v1/fleet JSON file instead of fetching",
    )
    ap.add_argument(
        "--flight", default=None,
        help="recorded /v1/debug/flight JSON file instead of fetching",
    )
    ap.add_argument(
        "--programs", default=None,
        help="recorded /v1/debug/programs JSON file instead of fetching",
    )
    ap.add_argument(
        "--traces", default=None,
        help="recorded /v1/traces JSON file instead of fetching",
    )
    ap.add_argument(
        "--ledger", default=None,
        help="perf ledger (artifacts/perf_ledger.jsonl) for the "
             "perf-regression rule; never fetched",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the findings as JSON instead of the text report",
    )
    args = ap.parse_args(argv)

    def load(path):
        with open(path) as f:
            return json.load(f)

    fleet = load(args.snapshot) if args.snapshot else _fetch(args.url, "/v1/fleet")
    if fleet is None:
        return 1
    flight = (
        load(args.flight) if args.flight
        else (_fetch(args.url, "/v1/debug/flight") if not args.snapshot else {})
    )
    programs = (
        load(args.programs) if args.programs
        else (_fetch(args.url, "/v1/debug/programs") if not args.snapshot else {})
    )
    traces = (
        load(args.traces) if args.traces
        else (
            _fetch(
                args.url,
                f"/v1/traces?sort=duration&limit={2 * TRACE_WORST_N}",
            )
            if not args.snapshot
            else {}
        )
    )
    ledger_rows = None
    if args.ledger:
        perf_ledger = _import_perf_ledger()
        if perf_ledger is None:
            print("ledger: dynamo_tpu.telemetry.perf_ledger not "
                  "importable", file=sys.stderr)
        else:
            try:
                ledger_rows, skipped = perf_ledger.read_rows(args.ledger)
                for p in skipped:
                    print(f"ledger: skipped {p}", file=sys.stderr)
            except OSError as e:
                print(f"ledger {args.ledger} unreadable: {e}",
                      file=sys.stderr)
    findings = diagnose(
        fleet, flight or {}, programs or {}, traces or {}, ledger_rows
    )
    if args.json:
        print(json.dumps(findings, indent=2))
    else:
        print(render_report(fleet, findings))
    return 2 if any(f["severity"] == "critical" for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env bash
# BASELINE config 1's exact model (DeepSeek-R1-Distill-Llama-8B,
# architecturally llama3-8b) served end-to-end through the canonical
# `run in=http out=jax` pipeline. On CPU-fallback this proves the CLI
# path + preset + model card; the chip bench rides
# scripts/tpu_dsr1_bench.sh / BENCH_MODEL=deepseek-r1-distill-llama-8b.
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/dsr1_distill_cli.json
PORT=8871
LOG=/tmp/dsr1_serve.log
env PYTHONPATH=. JAX_PLATFORMS=cpu python -u -m dynamo_tpu.cli.run run \
  in=http out=jax --model deepseek-r1-distill-llama-8b --dtype bfloat16 \
  --page-size 16 --num-pages 96 --max-context 256 --max-seqs 2 \
  --port $PORT > "$LOG" 2>&1 &
SRV=$!
trap 'kill $SRV 2>/dev/null' EXIT
for i in $(seq 1 240); do
  grep -q "listening on" "$LOG" && break
  sleep 5
done
T0=$(date +%s)
RESP=$(curl -s -m 1800 http://127.0.0.1:$PORT/v1/chat/completions \
  -H 'Content-Type: application/json' \
  -d '{"model":"deepseek-r1-distill-llama-8b","messages":[{"role":"user","content":"Hi"}],"max_tokens":2,"temperature":0}')
T1=$(date +%s)
python - "$RESP" "$((T1-T0))" << 'PY' > "$OUT"
import json, sys
resp = json.loads(sys.argv[1])
print(json.dumps({
  "what": "DeepSeek-R1-Distill-Llama-8B (BASELINE config 1) served "
          "end-to-end via `run in=http out=jax` (CPU fallback, random "
          "weights - 8B bf16 arch proof; chip stage: tpu_dsr1_bench.sh)",
  "model": resp.get("model"),
  "usage": resp.get("usage"),
  "finish_reason": resp["choices"][0].get("finish_reason"),
  "wall_s_request": int(sys.argv[2]),
  "platform": "cpu-1core-fallback",
  "date": "2026-07-31",
}, indent=1))
PY
cat "$OUT"

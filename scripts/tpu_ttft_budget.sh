#!/usr/bin/env bash
# Saturation-TTFT knob on the chip: sweep prefill_token_budget at c=64
# on llama3-1b (the round-3 cliff config: p50 2,232 ms at budget 2048).
# Run AFTER scripts/tpu_watch_queue.sh drains (probe first, like it does).
# Artifact: artifacts/tpu/ttft_budget.json
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/tpu
mkdir -p "$OUT"

if ! timeout 120 python -c \
  "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
  >/dev/null 2>&1; then
  echo "tunnel down; not running" >&2
  exit 1
fi

python - << 'PY' > "$OUT/ttft_budget.json" 2> "$OUT/ttft_budget.err"
import json, subprocess, sys

rows = {}
cases = [("2048", []), ("4096", []), ("8192", []),
         # the adaptive policy at the DEFAULT budget: drains the c=64
         # burst in O(1) dispatches without raising the idle budget
         ("adaptive", ["--prefill-policy", "adaptive"])]
for name, extra in cases:
    # one wedged/timed-out run must not discard the cases already
    # measured — chip time is the scarce resource here
    budget = name if name.isdigit() else "2048"
    try:
        out = subprocess.run(
            [sys.executable, "-m", "benchmarks.perf", "--mode", "engine",
             "--model", "llama3-1b", "--dtype", "bfloat16",
             "--num-pages", "1024", "--page-size", "64",
             "--num-requests", "64", "--isl", "512", "--osl", "64",
             "--prefill-budget", budget, "--concurrency", "16,64",
             "--decode-steps", "64", *extra],
            capture_output=True, text=True, timeout=3000,
        ).stdout
        rows[name] = json.loads(out[out.index("{"):])["sweep"]
    except Exception as e:
        rows[name] = {"error": repr(e)}
print(json.dumps({
    "what": "prefill_token_budget sweep at saturation (docs/PERF.md round-5 "
            "TTFT-cliff section); round-3 baseline: c=64 p50 2232 ms",
    "sweep_by_budget": rows,
}, indent=1))
PY
rc=$?
tail -c 300 "$OUT/ttft_budget.json"
exit $rc

#!/usr/bin/env bash
# Post-queue chain: run the round-5 extras (TTFT budget sweep, DSR1
# bench), then retry any stage whose artifact is still empty/missing.
# Single chip — run only after the main watcher queue exits.
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/tpu

bash scripts/tpu_ttft_budget.sh || true
bash scripts/tpu_dsr1_bench.sh || true
bash scripts/tpu_mm_serve.sh || true

# re-record bench_8b under the per-(platform, model, quantize) baseline
# semantics (VERDICT r4 weak #3: the committed artifact still carries the
# misleading cross-model vs_baseline 0.36)
if timeout 120 python -c \
  "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
  >/dev/null 2>&1; then
  BENCH_MODEL=llama3-8b BENCH_QUANTIZE=int8 BENCH_REQUESTS=64 \
    BENCH_ATTENTION=auto \
    timeout 3600 python bench.py > "$OUT/bench_8b.json" 2> "$OUT/bench_8b.err" \
    || true
fi

# retry empties via the queue's own stage functions (fresh queue pass
# with an explicit stage list keeps run_stage semantics + tunnel waits)
retries=()
# error artifacts (bench.py emits value 0.0 on an engine/compile failure)
# deserve one more attempt — the 09:33 bench_dsv2 failure was a transient
# remote-compile HTTP 500
if grep -q '"value": 0.0' "$OUT/bench_dsv2.json" 2>/dev/null; then
  retries+=(bench_dsv2)
fi
[ -s "$OUT/disagg_ab.json" ]     || retries+=(disagg_ab)
[ -s "$OUT/ft_device_kill.json" ] || retries+=(ft_kill)
[ -s "$OUT/perf_sweep_8b.json" ] || retries+=(sweep_8b)
[ -s "$OUT/profile_sla_8b.json" ] || retries+=(sla_8b)
[ -s "$OUT/bench_1b.json" ]      || retries+=(bench_1b_sweep)
[ -s "$OUT/decode_prof.json" ]   || retries+=(decode_profile)
[ -s "$OUT/pallas_gate.json" ]   || retries+=(pallas_gate)
if [ ${#retries[@]} -gt 0 ]; then
  echo "retrying: ${retries[*]}"
  bash scripts/tpu_watch_queue.sh "${retries[@]}"
fi
echo "followup complete"

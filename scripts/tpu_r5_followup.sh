#!/usr/bin/env bash
# Post-queue chain: run the round-5 extras (TTFT budget sweep, DSR1
# bench), then retry any stage whose artifact is still empty/missing.
# Single chip — run only after the main watcher queue exits.
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/tpu

bash scripts/tpu_ttft_budget.sh || true
bash scripts/tpu_dsr1_bench.sh || true

# retry empties via the queue's own stage functions (fresh queue pass
# with an explicit stage list keeps run_stage semantics + tunnel waits)
retries=()
[ -s "$OUT/disagg_ab.json" ]     || retries+=(disagg_ab)
[ -s "$OUT/perf_sweep_8b.json" ] || retries+=(sweep_8b)
[ -s "$OUT/profile_sla_8b.json" ] || retries+=(sla_8b)
[ -s "$OUT/bench_1b.json" ]      || retries+=(bench_1b_sweep)
[ -s "$OUT/decode_prof.json" ]   || retries+=(decode_profile)
[ -s "$OUT/pallas_gate.json" ]   || retries+=(pallas_gate)
if [ ${#retries[@]} -gt 0 ]; then
  echo "retrying: ${retries[*]}"
  bash scripts/tpu_watch_queue.sh "${retries[@]}"
fi
echo "followup complete"

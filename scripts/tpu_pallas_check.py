"""On-device Mosaic validation of every Pallas kernel in dynamo_tpu.ops.

Interpret mode (the CI path, tests/test_ops_paged_attention.py) proves
semantics but not Mosaic lowering — VMEM budgets, DMA alignment, lane
tiling only fail on the real compiler. This script compiles each kernel
with interpret=False on the live chip and asserts numeric agreement with
an XLA reference computation, then writes artifacts/tpu/pallas_check.json.

Run: python scripts/tpu_pallas_check.py          (requires live TPU)
"""

from __future__ import annotations

import json
import math
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dynamo_tpu.ops.flash_prefill import (  # noqa: E402
    flash_prefill_attention,
    paged_prefill_attention,
)
from dynamo_tpu.ops.kv_update import paged_write  # noqa: E402
from dynamo_tpu.ops.paged_attention import paged_decode_attention  # noqa: E402

RESULTS: list[dict] = []


def record(name: str, fn):
    t0 = time.perf_counter()
    try:
        err = fn()
        RESULTS.append(
            {
                "kernel": name,
                "ok": True,
                "max_abs_err": float(err),
                "seconds": round(time.perf_counter() - t0, 2),
            }
        )
        print(f"PASS {name}: max_abs_err={err:.3e}")
    except Exception as e:  # noqa: BLE001 — record and continue
        RESULTS.append(
            {
                "kernel": name,
                "ok": False,
                "error": f"{type(e).__name__}: {e}"[:2000],
                "seconds": round(time.perf_counter() - t0, 2),
            }
        )
        print(f"FAIL {name}: {type(e).__name__}: {e}")


def _ref_causal(q, k, v, valid, scale_dim):
    """Dense causal reference in f32."""
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    qf = q.astype(jnp.float32) / math.sqrt(scale_dim)
    kf = jnp.repeat(k.astype(jnp.float32), g, axis=2)
    vf = jnp.repeat(v.astype(jnp.float32), g, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", qf, kf)
    pos = jnp.arange(t)
    mask = (pos[None, :] >= pos[:, None])[None, None] | False
    mask = (pos[None, None, :, None] >= pos[None, None, None, :]) & (
        pos[None, None, None, :] < valid[:, None, None, None]
    )
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, vf)


def check_flash_prefill():
    key = jax.random.PRNGKey(0)
    b, t, hq, hkv, d = 2, 384, 8, 2, 128
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, t, hkv, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, t, hkv, d), jnp.bfloat16)
    valid = jnp.array([384, 200], jnp.int32)
    out = flash_prefill_attention(q, k, v, valid, scale_dim=d, interpret=False)
    ref = _ref_causal(q, k, v, valid, d)
    # compare valid rows only (invalid rows are unspecified)
    errs = []
    for i in range(b):
        n = int(valid[i])
        errs.append(
            jnp.max(jnp.abs(out[i, :n].astype(jnp.float32) - ref[i, :n]))
        )
    err = float(jnp.max(jnp.stack(errs)))
    assert err < 0.05, f"flash_prefill mismatch: {err}"
    return err


def check_paged_prefill():
    key = jax.random.PRNGKey(1)
    b, t, hq, hkv, d = 2, 256, 8, 2, 128
    L, P, S, MP = 2, 32, 64, 16
    ks = jax.random.split(key, 6)
    q = jax.random.normal(ks[0], (b, t, hq, d), jnp.bfloat16)
    k_cur = jax.random.normal(ks[1], (b, t, hkv, d), jnp.bfloat16)
    v_cur = jax.random.normal(ks[2], (b, t, hkv, d), jnp.bfloat16)
    k_cache = jax.random.normal(ks[3], (L, P, S, hkv, d), jnp.bfloat16)
    v_cache = jax.random.normal(ks[4], (L, P, S, hkv, d), jnp.bfloat16)
    pt = jnp.tile(jnp.arange(MP, dtype=jnp.int32)[None], (b, 1))
    pt = pt.at[1].set(jnp.arange(MP, dtype=jnp.int32) + MP)
    hist = jnp.array([128, 96], jnp.int32)
    cur = jnp.array([256, 130], jnp.int32)
    layer = jnp.asarray(1, jnp.int32)
    out = paged_prefill_attention(
        q, k_cur, v_cur, k_cache, v_cache, layer, pt, hist, cur,
        scale_dim=d, interpret=False,
    )

    # reference: gather history densely, concat with chunk, causal over abs pos
    g = hq // hkv
    errs = []
    for i in range(b):
        h = int(hist[i])
        c = int(cur[i])
        npages = -(-h // S)
        pages = pt[i, :npages]
        kh = k_cache[1, pages].reshape(-1, hkv, d)[:h]
        vh = v_cache[1, pages].reshape(-1, hkv, d)[:h]
        kfull = jnp.concatenate([kh, k_cur[i, :c]], axis=0).astype(jnp.float32)
        vfull = jnp.concatenate([vh, v_cur[i, :c]], axis=0).astype(jnp.float32)
        kfull = jnp.repeat(kfull, g, axis=1)
        vfull = jnp.repeat(vfull, g, axis=1)
        qf = q[i, :c].astype(jnp.float32) / math.sqrt(d)
        s = jnp.einsum("thd,shd->hts", qf, kfull)
        qpos = h + jnp.arange(c)
        kpos = jnp.arange(h + c)
        mask = kpos[None, None, :] <= qpos[None, :, None]
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("hts,shd->thd", p, vfull)
        errs.append(
            jnp.max(jnp.abs(out[i, :c].astype(jnp.float32) - ref))
        )
    err = float(jnp.max(jnp.stack(errs)))
    assert err < 0.05, f"paged_prefill mismatch: {err}"
    return err


def check_paged_decode():
    key = jax.random.PRNGKey(2)
    b, hq, hkv, d = 4, 8, 2, 128
    L, P, S, MP = 2, 64, 64, 16
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, hq, d), jnp.bfloat16)
    k_cache = jax.random.normal(ks[1], (L, P, S, hkv, d), jnp.bfloat16)
    v_cache = jax.random.normal(ks[2], (L, P, S, hkv, d), jnp.bfloat16)
    pt = jnp.arange(b * MP, dtype=jnp.int32).reshape(b, MP) % P
    hist = jnp.array([512, 130, 64, 0], jnp.int32)
    layer = jnp.asarray(0, jnp.int32)
    acc, m, l = paged_decode_attention(
        q, k_cache, v_cache, layer, pt, hist, scale_dim=d, interpret=False
    )
    g = hq // hkv
    errs = []
    for i in range(b):
        h = int(hist[i])
        if h == 0:
            errs.append(jnp.max(jnp.abs(acc[i])))
            continue
        npages = -(-h // S)
        pages = pt[i, :npages]
        kh = k_cache[0, pages].reshape(-1, hkv, d)[:h].astype(jnp.float32)
        vh = v_cache[0, pages].reshape(-1, hkv, d)[:h].astype(jnp.float32)
        kh = jnp.repeat(kh, g, axis=1)
        vh = jnp.repeat(vh, g, axis=1)
        qf = q[i].astype(jnp.float32) / math.sqrt(d)
        s = jnp.einsum("hd,shd->hs", qf, kh)
        m_ref = jnp.max(s, axis=-1)
        p = jnp.exp(s - m_ref[:, None])
        l_ref = jnp.sum(p, axis=-1)
        acc_ref = jnp.einsum("hs,shd->hd", p, vh)
        # merge-normalize both sides to compare the normalized output
        o_kernel = acc[i] / jnp.maximum(l[i], 1e-30)[:, None]
        o_ref = acc_ref / jnp.maximum(l_ref, 1e-30)[:, None]
        errs.append(jnp.max(jnp.abs(o_kernel - o_ref)))
    err = float(jnp.max(jnp.stack(errs)))
    assert err < 0.05, f"paged_decode mismatch: {err}"
    return err


def check_paged_write():
    key = jax.random.PRNGKey(3)
    L, b, t, hkv, d = 2, 2, 64, 2, 128
    P, S, MP = 32, 64, 8
    ks = jax.random.split(key, 2)
    k_cache = jnp.zeros((L, P, S, hkv, d), jnp.bfloat16)
    v_cache = jnp.zeros((L, P, S, hkv, d), jnp.bfloat16)
    k_stage = jax.random.normal(ks[0], (L, b, t, hkv, d), jnp.bfloat16)
    v_stage = jax.random.normal(ks[1], (L, b, t, hkv, d), jnp.bfloat16)
    pt = jnp.arange(b * MP, dtype=jnp.int32).reshape(b, MP)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None], (b, 1)) + 64
    valid = jnp.ones((b, t), bool)
    k1, v1 = paged_write(
        k_cache, v_cache, k_stage, v_stage, pt, positions, valid,
        use_kernel=True,
    )
    k2, v2 = paged_write(
        k_cache, v_cache, k_stage, v_stage, pt, positions, valid,
        use_kernel=False,
    )
    err = float(
        jnp.maximum(
            jnp.max(jnp.abs(k1.astype(jnp.float32) - k2.astype(jnp.float32))),
            jnp.max(jnp.abs(v1.astype(jnp.float32) - v2.astype(jnp.float32))),
        )
    )
    assert err == 0.0, f"paged_write kernel != scatter: {err}"
    return err


def check_paged_write_int8():
    """Quantized pools: the DMA writer must land the same int8 rows +
    f32 scale planes as the XLA scatter (page-aligned full-run writes)."""
    from dynamo_tpu.models.llama import init_kv_pages, LlamaConfig

    key = jax.random.PRNGKey(4)
    L, b, t, hkv, d = 2, 2, 64, 2, 128
    P, S, MP = 32, 64, 8
    ks = jax.random.split(key, 2)
    cfg = LlamaConfig(
        num_layers=L, num_kv_heads=hkv, head_dim=d, attention_impl="pallas"
    )
    k_stage = jax.random.normal(ks[0], (L, b, t, hkv, d), jnp.bfloat16)
    v_stage = jax.random.normal(ks[1], (L, b, t, hkv, d), jnp.bfloat16)
    pt = jnp.arange(b * MP, dtype=jnp.int32).reshape(b, MP)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None], (b, 1)) + 64
    valid = jnp.ones((b, t), bool)
    outs = []
    for use_kernel in (True, False):
        kv = init_kv_pages(cfg, P, S, kv_quantize="int8")
        outs.append(paged_write(
            kv.k, kv.v, k_stage, v_stage, pt, positions, valid,
            use_kernel=use_kernel, k_scale=kv.k_scale, v_scale=kv.v_scale,
        ))
    err = 0.0
    for a, b_ in zip(*outs):
        err = max(err, float(jnp.max(jnp.abs(
            a.astype(jnp.float32) - b_.astype(jnp.float32)
        ))))
    assert err == 0.0, f"quantized paged_write kernel != scatter: {err}"
    return err


def check_paged_decode_int8():
    """int8 pages + in-kernel dequant vs the dense dequantized XLA
    reference — the Mosaic proof of the scale-plane DMA + VMEM dequant."""
    from dynamo_tpu.models.llama import dequantize_kv_rows, quantize_kv_rows

    key = jax.random.PRNGKey(5)
    b, hq, hkv, d = 4, 8, 2, 128
    L, P, S, MP = 2, 64, 64, 16
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, hq, d), jnp.bfloat16)
    k_f = jax.random.normal(ks[1], (L, P, S, hkv, d), jnp.float32)
    v_f = jax.random.normal(ks[2], (L, P, S, hkv, d), jnp.float32)
    k_cache, k_scale = quantize_kv_rows(k_f, "int8")
    v_cache, v_scale = quantize_kv_rows(v_f, "int8")
    pt = jnp.arange(b * MP, dtype=jnp.int32).reshape(b, MP) % P
    hist = jnp.array([512, 130, 64, 0], jnp.int32)
    layer = jnp.asarray(0, jnp.int32)
    acc, m, l = paged_decode_attention(
        q, k_cache, v_cache, layer, pt, hist, scale_dim=d, interpret=False,
        k_scale=k_scale, v_scale=v_scale,
    )
    kd = dequantize_kv_rows(k_cache, k_scale, jnp.float32)
    vd = dequantize_kv_rows(v_cache, v_scale, jnp.float32)
    g = hq // hkv
    errs = []
    for i in range(b):
        h = int(hist[i])
        if h == 0:
            errs.append(jnp.max(jnp.abs(acc[i])))
            continue
        npages = -(-h // S)
        pages = pt[i, :npages]
        kh = jnp.repeat(kd[0, pages].reshape(-1, hkv, d)[:h], g, axis=1)
        vh = jnp.repeat(vd[0, pages].reshape(-1, hkv, d)[:h], g, axis=1)
        qf = q[i].astype(jnp.float32) / math.sqrt(d)
        s = jnp.einsum("hd,shd->hs", qf, kh)
        m_ref = jnp.max(s, axis=-1)
        p = jnp.exp(s - m_ref[:, None])
        l_ref = jnp.sum(p, axis=-1)
        acc_ref = jnp.einsum("hs,shd->hd", p, vh)
        o_kernel = acc[i] / jnp.maximum(l[i], 1e-30)[:, None]
        o_ref = acc_ref / jnp.maximum(l_ref, 1e-30)[:, None]
        errs.append(jnp.max(jnp.abs(o_kernel - o_ref)))
    err = float(jnp.max(jnp.stack(errs)))
    assert err < 0.05, f"quantized paged_decode mismatch: {err}"
    return err


def main():
    plat = jax.devices()[0].platform
    print(f"platform: {plat} ({jax.devices()})")
    if plat == "cpu":
        print("refusing to run Mosaic check on CPU")
        sys.exit(1)
    record("flash_prefill_attention", check_flash_prefill)
    record("paged_prefill_attention", check_paged_prefill)
    record("paged_decode_attention", check_paged_decode)
    record("paged_write", check_paged_write)
    record("paged_write_int8", check_paged_write_int8)
    record("paged_decode_attention_int8", check_paged_decode_int8)
    out = {
        "platform": plat,
        "device": str(jax.devices()[0]),
        "results": RESULTS,
        "all_ok": all(r["ok"] for r in RESULTS),
    }
    path = Path(__file__).resolve().parent.parent / "artifacts/tpu"
    path.mkdir(parents=True, exist_ok=True)
    (path / "pallas_check.json").write_text(json.dumps(out, indent=2))
    print(json.dumps({k: out[k] for k in ("platform", "all_ok")}))
    sys.exit(0 if out["all_ok"] else 2)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Tier-1 marker audit: fleet/collective-plane tests must be `slow`.

Codifies the PR 1 gloo-wedge fix as a check instead of tribal knowledge:
any test that spawns a subprocess fleet (a multi-process jax.distributed
group, or a CLI worker fleet joined via `--coordinator`) or requires the
cross-process collective plane can wedge on a flaky gloo rendezvous —
past the whole tier-1 budget, with no timeout inside a collective. Such
tests MUST carry the `slow` marker so the quick suite (`-m 'not slow'`)
never runs them; the gate probe (`collective_plane_available`) protects
the slow lane, not the budget.

Chaos and fault-injection scenarios that spawn process fleets (the
tests/fault_tolerance harness, ChaosCluster) are forced `slow` the same
way: a cluster bring-up plus kill/drain schedules costs minutes of wall
clock and has subprocess-wedge failure modes tier-1 must never inherit.

Static (AST) scan, `-p no:randomly`-safe: no test module is imported, so
the audit cannot be perturbed by plugin ordering or collection order.
A test function is RISKY when its own source — or the source of any
fixture it requests (transitively, through same-module and conftest.py
fixture chains alike) — mentions one of the fleet tokens below. A risky test passes the audit when it (or its
module's `pytestmark`) carries `pytest.mark.slow`, including through a
module-level alias (`fleet = pytest.mark.slow`).

Exit 0 = clean; exit 1 = violations (one line each); exit 2 = usage.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

#: source substrings that mean "this test spawns a fleet / needs the
#: collective plane". Single-process `init_multihost(..., num_hosts=1)`
#: smokes deliberately do NOT match.
RISK_TOKENS = (
    "spawn_two_hosts",  # tests/helpers/spmd_host.py fleet spawner
    "--coordinator",    # CLI worker fleet joining a jax.distributed group
    "collective_plane_available",  # the gate probe itself needs the plane
    # chaos / fault-injection fleets (docs/operations.md "Overload &
    # draining"): the FT harness spawns a whole CLI process cluster
    # (fabric + frontend + workers) and drives it with injected kills,
    # drains and saturation — minutes of wall clock, never tier-1
    "ManagedProc",      # benchmarks/_procs.py process spawner
    "fault_tolerance.harness",  # importing the cluster harness at all
    "ChaosCluster",     # tests/test_chaos.py process-level scenarios
)


def _is_slow_marker(expr: ast.expr, aliases: set[str]) -> bool:
    src = ast.unparse(expr)
    return "mark.slow" in src or src in aliases


def _module_facts(tree: ast.Module):
    """(slow_aliases, module_is_slow) from top-level assignments."""
    aliases: set[str] = set()
    module_slow = False
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        src = ast.unparse(node.value)
        names = [
            t.id for t in node.targets if isinstance(t, ast.Name)
        ]
        if "mark.slow" in src:
            for name in names:
                if name == "pytestmark":
                    module_slow = True
                else:
                    aliases.add(name)
    return aliases, module_slow


def _collect_fixtures(src: str, tree: ast.Module) -> dict:
    """fixture name -> (source text incl. decorators, fixture names it
    requests) — enough to walk fixture chains without re-parsing."""
    out: dict[str, tuple] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and any(
            "fixture" in ast.unparse(d) for d in node.decorator_list
        ):
            out[node.name] = (_fn_text(src, node), _requested_fixtures(node))
    return out


def _fn_text(src: str, node) -> str:
    """Function source INCLUDING decorators (get_source_segment starts
    at `def`, which would hide @pytest.mark.usefixtures arguments)."""
    parts = [ast.unparse(d) for d in node.decorator_list]
    parts.append(ast.get_source_segment(src, node) or "")
    return "\n".join(parts)


def _requested_fixtures(node) -> list[str]:
    """Fixture names a test can pull in: positional, positional-only and
    keyword-only parameters, plus @pytest.mark.usefixtures entries."""
    names = [
        a.arg
        for a in (
            node.args.posonlyargs + node.args.args + node.args.kwonlyargs
        )
    ]
    for d in node.decorator_list:
        text = ast.unparse(d)
        if "usefixtures" in text and isinstance(d, ast.Call):
            names.extend(
                a.value for a in d.args if isinstance(a, ast.Constant)
            )
    return names


def audit_file(path: Path, conftest_fixtures: dict) -> list[str]:
    src = path.read_text()
    if not any(tok in src for tok in RISK_TOKENS) and not conftest_fixtures:
        return []
    tree = ast.parse(src)
    aliases, module_slow = _module_facts(tree)

    funcs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs[node.name] = node
    #: same-module fixtures shadow conftest ones (pytest resolution)
    fixtures = dict(conftest_fixtures)
    fixtures.update(_collect_fixtures(src, tree))

    def risky(text: str, requested: list[str], seen: set[str]) -> bool:
        if any(tok in text for tok in RISK_TOKENS):
            return True
        for name in requested:
            if name in fixtures and name not in seen:
                seen.add(name)
                if risky(*fixtures[name], seen):
                    return True
        return False

    errors = []
    for name, node in funcs.items():
        if not name.startswith("test_"):
            continue
        if not risky(
            _fn_text(src, node), _requested_fixtures(node), set()
        ):
            continue
        slow = module_slow or any(
            _is_slow_marker(d, aliases) for d in node.decorator_list
        )
        if not slow:
            errors.append(
                f"{path}:{node.lineno}: {name} spawns a subprocess fleet "
                "or needs the collective plane but lacks "
                "@pytest.mark.slow — a flaky gloo rendezvous can wedge "
                "it past the tier-1 budget (see PR 1 / "
                "tests/helpers/spmd_host.py)"
            )
    return errors


def main(argv: list[str]) -> int:
    root = Path(argv[1]) if len(argv) > 1 else (
        Path(__file__).resolve().parent.parent / "tests"
    )
    if not root.exists():
        print(f"check_markers: no such path {root}", file=sys.stderr)
        return 2
    files = (
        sorted(root.rglob("test_*.py")) if root.is_dir() else [root]
    )
    #: fleet-spawning fixtures defined in conftest.py must be visible to
    #: every test module that can request them
    conftest_fixtures: dict = {}
    conftests = (
        sorted(root.rglob("conftest.py")) if root.is_dir() else []
    )
    for cf in conftests:
        cf_src = cf.read_text()
        conftest_fixtures.update(
            _collect_fixtures(cf_src, ast.parse(cf_src))
        )
    errors: list[str] = []
    for f in files:
        errors.extend(audit_file(f, conftest_fixtures))
    for e in errors:
        print(e)
    if errors:
        print(
            f"check_markers: {len(errors)} unmarked fleet test(s)",
            file=sys.stderr,
        )
        return 1
    print(f"check_markers: {len(files)} files OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))

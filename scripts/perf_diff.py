#!/usr/bin/env python
"""perf_diff.py — diff two rounds of the perf ledger, exit nonzero on
regression.

    python scripts/perf_diff.py r02 r03
    python scripts/perf_diff.py BASELINE r05 --baseline BASELINE.json
    python scripts/perf_diff.py --list

Compares the LATEST row of round B against the latest row of round A
(A is the reference, B the candidate), metric by metric, with the
direction and tolerance tables from dynamo_tpu/telemetry/perf_ledger.py
(--tolerance metric=frac overrides per metric). A worse-direction move
past the band is a REGRESSION; an improvement or in-band move is OK;
metrics present on only one side are reported but never flagged.

Exit codes (CI contract, pinned in tests/test_perf_ledger.py):
  0  no regression (including "nothing comparable" — a failed round has
     no metrics, and a config-fingerprint mismatch downgrades the whole
     diff to advisory: different workloads can't regress each other)
  1  usage/data error (missing round, unreadable ledger)
  2  at least one metric regressed past its band

docs/observability.md "Reading the perf plane" walks through a session.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.telemetry import perf_ledger  # noqa: E402
from dynamo_tpu.telemetry.perf_ledger import compare_rows  # noqa: E402,F401


def render(result: dict) -> str:
    lines = [
        f"perf_diff: {result['round_a']} -> {result['round_b']}",
    ]
    if result["note"]:
        lines.append(f"  note: {result['note']}")
    if result["rows"]:
        w = max(len(r["metric"]) for r in result["rows"])
        for r in result["rows"]:
            a = "-" if r["a"] is None else f"{r['a']:.6g}"
            b = "-" if r["b"] is None else f"{r['b']:.6g}"
            rel = "" if r["rel"] is None else f" {r['rel']:+.1%}"
            band = "" if r["band"] is None else f" (band {r['band']:.0%})"
            lines.append(
                f"  {r['metric']:<{w}}  {a:>12} -> {b:>12}"
                f"{rel}{band}  {r['verdict']}"
            )
    if result["regressions"]:
        lines.append(
            f"  RESULT: {len(result['regressions'])} regression(s): "
            + ", ".join(result["regressions"])
        )
    elif result["comparable"]:
        lines.append("  RESULT: no regressions")
    else:
        lines.append("  RESULT: nothing comparable")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two perf-ledger rounds; exit 2 on regression"
    )
    ap.add_argument("round_a", nargs="?", help="reference round (or BASELINE)")
    ap.add_argument("round_b", nargs="?", help="candidate round")
    ap.add_argument("--ledger", default=perf_ledger.DEFAULT_LEDGER)
    ap.add_argument(
        "--baseline", default="BASELINE.json",
        help="BASELINE.json to satisfy the literal round name BASELINE",
    )
    ap.add_argument(
        "--tolerance", action="append", default=[], metavar="METRIC=FRAC",
        help="override a metric's band, e.g. --tolerance tok_s=0.02",
    )
    ap.add_argument("--list", action="store_true",
                    help="list rounds in the ledger and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable result on stdout")
    args = ap.parse_args(argv)

    try:
        rows, problems = perf_ledger.read_rows(args.ledger)
    except OSError as e:
        print(f"perf_diff: cannot read ledger: {e}", file=sys.stderr)
        return 1
    for p in problems:
        print(f"perf_diff: skipped {p}", file=sys.stderr)
    by_round = perf_ledger.rows_by_round(rows)

    if args.list:
        for name, row in by_round.items():
            print(f"{name:>12}  source={row['source']:<16} ok={row['ok']} "
                  f"metrics={','.join(sorted(row['metrics'])) or '-'}")
        return 0
    if not args.round_a or not args.round_b:
        ap.error("need ROUND_A and ROUND_B (or --list)")

    tol = {}
    for spec in args.tolerance:
        name, _, frac = spec.partition("=")
        try:
            tol[name] = float(frac)
        except ValueError:
            ap.error(f"bad --tolerance {spec!r}")

    picked = {}
    for which in (args.round_a, args.round_b):
        if which in by_round:
            picked[which] = by_round[which]
        elif which == "BASELINE":
            try:
                with open(args.baseline) as f:
                    picked[which] = perf_ledger.row_from_baseline(
                        json.load(f)
                    )
            except (OSError, ValueError) as e:
                print(f"perf_diff: cannot read {args.baseline}: {e}",
                      file=sys.stderr)
                return 1
        else:
            known = ", ".join(by_round) or "(empty ledger)"
            print(f"perf_diff: round {which!r} not in ledger "
                  f"({known})", file=sys.stderr)
            return 1

    result = compare_rows(picked[args.round_a], picked[args.round_b], tol)
    if args.as_json:
        print(json.dumps(result, indent=1))
    else:
        print(render(result))
    return 2 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())

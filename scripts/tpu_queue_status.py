"""Summarize the TPU stage queue: which artifacts are fresh, stale, empty.

Usage: python scripts/tpu_queue_status.py
Prints one line per known stage artifact with age and a one-word verdict,
so a recovering tunnel session can see at a glance what still needs chip
time (the round-4 lesson: budget tunnel-down time explicitly).
"""

from __future__ import annotations

import json
import time
from pathlib import Path

OUT = Path(__file__).resolve().parent.parent / "artifacts" / "tpu"

STAGES = [
    "pallas_kernels", "prewarm", "disagg_ab", "disagg_ab_partial",
    "perf_sweep_8b", "profile_sla_8b", "ft_device_kill", "routing_engine",
    "offload_ab", "bench_dsv2", "decode_prof", "bench_1b", "pallas_gate",
    "transfer", "ttft_budget", "bench_dsr1", "mm_serve",
]


def main() -> None:
    now = time.time()
    for name in STAGES:
        p = OUT / f"{name}.json"
        if not p.exists():
            print(f"{name:18s} MISSING")
            continue
        size = p.stat().st_size
        age_h = (now - p.stat().st_mtime) / 3600
        if size == 0:
            print(f"{name:18s} EMPTY   (age {age_h:5.1f} h)")
            continue
        verdict = "ok"
        try:
            text = p.read_text().strip()
            try:
                doc = json.loads(text)
            except ValueError:
                # run_stage captures whole stdout; the JSON document is
                # the last line (stage scripts print progress above it)
                doc = json.loads(text.splitlines()[-1])
            plat = None
            if isinstance(doc, dict):
                plat = doc.get("platform") or doc.get("extras", {}).get(
                    "platform"
                )
            if plat and plat != "tpu":
                verdict = f"non-tpu ({plat})"
        except (OSError, ValueError):
            # artifact rewritten/deleted mid-poll by the watcher queue —
            # report and keep listing
            verdict = "unparseable"
        print(f"{name:18s} {verdict:14s} {size:7d} B  age {age_h:5.1f} h")


if __name__ == "__main__":
    main()

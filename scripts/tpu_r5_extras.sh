#!/usr/bin/env bash
# Round-5 chip extras: wait for the main watcher queue to drain, then run
# the budget sweep + the DeepSeek-R1-distill bench (each self-probes).
set -u
cd "$(dirname "$0")/.."
LOG=artifacts/tpu/watch_r5.log
while ! grep -q "queue complete" "$LOG" 2>/dev/null; do
  sleep 300
done
bash scripts/tpu_ttft_budget.sh
bash scripts/tpu_dsr1_bench.sh
echo "extras complete"

#!/usr/bin/env python3
"""fleet_top: terminal view of the metrics service's /v1/fleet snapshot.

One-shot by default; `--watch N` redraws every N seconds. For operators
who want the fleet at a glance without Grafana:

    python scripts/fleet_top.py --url http://127.0.0.1:9091
    python scripts/fleet_top.py --watch 2
    python scripts/fleet_top.py --snapshot artifacts/fleet.json  # offline

Per worker: role, model, req/s, tok/s, TTFT/ITL p50/p95, KV-pool %,
live MFU, jit compiles, stall count (dynamo_tpu_stalls_total, via the
worker frames' stalls_total), SLO burn rate (shortest attainment
window), last_seen age. Fleet footer: merged percentiles, SLA
attainment + burn rates, goodput. Dependency-free (urllib only);
`render()` is a pure function smoke-tested against a recorded snapshot
in tests/test_fleet_telemetry.py.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def _fmt(v, nd: int = 1, suffix: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}{suffix}"
    return f"{v}{suffix}"


def _pct(slo: dict, metric: str, q: str):
    return (slo or {}).get(metric, {}).get(q)


def _worker_burn(slo: dict):
    """Per-worker burn rate from its SHORTEST attainment window (the
    fast-paging one of the multi-window pair)."""
    windows = (slo or {}).get("windows") or {}
    if not windows:
        return None
    shortest = min(windows, key=lambda x: int(x))
    return (windows[shortest] or {}).get("burn_rate")


def render(snap: dict) -> str:
    """Pure snapshot -> text table (no I/O; unit-testable)."""
    cols = (
        ("WORKER", 22), ("ROLE", 8), ("MODEL", 12), ("REQ/S", 7),
        ("TOK/S", 8), ("TTFT p50/p95", 14), ("ITL p50/p95", 12),
        ("KV%", 6), ("WM", 6), ("MFU", 7), ("COMP", 5), ("PREEMPT", 7),
        ("SPEC%", 6), ("STALLS", 6), ("BURN", 6), ("AGE s", 6),
    )
    out = [" ".join(f"{h:<{w}}" for h, w in cols)]
    for iid, w in sorted((snap.get("workers") or {}).items()):
        slo = w.get("slo") or {}
        kv = w.get("kv_usage")
        burn = _worker_burn(slo)
        row = (
            iid[:22], w.get("role", "?"), str(w.get("model", "?"))[:12],
            _fmt(w.get("req_s")), _fmt(w.get("tok_s")),
            f"{_fmt(_pct(slo, 'ttft_ms', 'p50'), 0)}/"
            f"{_fmt(_pct(slo, 'ttft_ms', 'p95'), 0)}",
            f"{_fmt(_pct(slo, 'itl_ms', 'p50'), 0)}/"
            f"{_fmt(_pct(slo, 'itl_ms', 'p95'), 0)}",
            _fmt(kv * 100.0 if kv is not None else None, 0),
            _fmt(w.get("kv_pages_watermark"), 0),
            _fmt(w.get("mfu"), 4), _fmt(w.get("compiles"), 0),
            _fmt(w.get("preemptions"), 0),
            # live draft-acceptance rate (speculative decoding), keyed
            # on the windowed draft count so the three states read
            # apart: a rate (incl. "0" = actively-failing draft) while
            # the window has drafts, "idle" when speculation ran before
            # but the window drained, "-" when it never ran
            (
                _fmt((w.get("spec_accept_rate") or 0.0) * 100.0, 0)
                if w.get("spec_window_drafted")
                else ("idle" if w.get("spec_drafted") else "-")
            ),
            _fmt(w.get("stalls_total"), 0),
            _fmt(burn, 1, "x") if burn is not None else "-",
            _fmt(w.get("last_seen_s")),
        )
        out.append(
            " ".join(f"{str(v):<{wd}}" for v, (_, wd) in zip(row, cols))
        )
    fleet = snap.get("fleet") or {}
    out.append("")
    out.append(f"fleet: {fleet.get('workers', 0)} workers")
    slo = fleet.get("slo")
    if slo:
        for m, label in (
            ("ttft_ms", "ttft"), ("itl_ms", "itl"), ("e2e_ms", "e2e"),
        ):
            q = slo.get(m)
            if q:
                out.append(
                    f"  {label:<5} p50 {_fmt(q.get('p50'))} ms   "
                    f"p95 {_fmt(q.get('p95'))} ms   "
                    f"p99 {_fmt(q.get('p99'))} ms   (n={q.get('n')})"
                )
        out.append(
            f"  sla   attainment {_fmt(slo.get('attainment'), 4)}   "
            f"goodput {slo.get('goodput_tokens_total', 0)}/"
            f"{slo.get('tokens_total', 0)} tokens"
        )
        for w_s, wd in sorted(
            (slo.get("windows") or {}).items(), key=lambda x: int(x[0])
        ):
            out.append(
                f"    {w_s:>4}s window: attainment "
                f"{_fmt(wd.get('attainment'), 4)}  burn rate "
                f"{_fmt(wd.get('burn_rate'), 2)}x  "
                f"({wd.get('requests', 0)} req)"
            )
    for role, r in sorted((snap.get("roles") or {}).items()):
        out.append(
            f"  {role:<6} {r.get('workers', 0)} workers  "
            f"tok/s {_fmt(r.get('tokens_per_s'))}  "
            f"mfu {_fmt(r.get('mfu'), 4)}  "
            f"kv {_fmt((r.get('kv_usage') or 0) * 100, 0)}%  "
            f"compiles {sum((r.get('compiles_by_kind') or {}).values())}"
        )
    return "\n".join(out)


def fetch(url: str) -> dict:
    with urllib.request.urlopen(f"{url}/v1/fleet", timeout=5) as resp:
        return json.loads(resp.read().decode())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--url", default="http://127.0.0.1:9091",
        help="metrics service base URL",
    )
    ap.add_argument(
        "--watch", type=float, default=0.0, metavar="SECONDS",
        help="redraw every N seconds (0 = one shot)",
    )
    ap.add_argument(
        "--snapshot", default=None,
        help="render a recorded snapshot JSON file instead of fetching",
    )
    args = ap.parse_args(argv)
    while True:
        if args.snapshot:
            with open(args.snapshot) as f:
                snap = json.load(f)
        else:
            try:
                snap = fetch(args.url)
            except Exception as e:
                print(f"fetch {args.url}/v1/fleet failed: {e}", file=sys.stderr)
                if not args.watch:
                    return 1
                time.sleep(args.watch)
                continue
        text = render(snap)
        if args.watch:
            print("\x1b[2J\x1b[H" + text, flush=True)
            time.sleep(args.watch)
        else:
            print(text)
            return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""fleet_top: terminal view of the metrics service's /v1/fleet snapshot.

One-shot by default; `--watch N` redraws every N seconds. For operators
who want the fleet at a glance without Grafana:

    python scripts/fleet_top.py --url http://127.0.0.1:9091
    python scripts/fleet_top.py --watch 2
    python scripts/fleet_top.py --snapshot artifacts/fleet.json  # offline
    python scripts/fleet_top.py --events            # fleet event timeline
    python scripts/fleet_top.py --events --watch 2  # tail it

Per worker: role, model, req/s, tok/s, TTFT/ITL p50/p95, KV-pool %,
live MFU, jit compiles, stall count (dynamo_tpu_stalls_total, via the
worker frames' stalls_total), KVBM tier residency + hit split
(TIER/HIT — docs/operations.md "The KV economy"), HBM byte breakdown
(HBM w/kv/free — the worker frames' hbm_*_bytes gauges, summed over
its local devices; docs/observability.md "Reading the perf plane"),
SLO burn rate
(shortest attainment window), the worst KEPT trace touching the worker (fleet trace plane,
GET /v1/traces — its id pastes straight into /v1/traces/{id}),
last_seen age. Fleet footer: merged percentiles, SLA attainment + burn
rates, goodput. `--events` tails GET /v1/fleet/events instead — one
severity-colored line per control-plane event (flips, handovers, shed
episodes, replays, resyncs, planner decisions). Dependency-free
(urllib only); `render()` / `render_events()` are pure functions
smoke-tested against recorded snapshots in tests/test_fleet_telemetry.py.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def _fmt(v, nd: int = 1, suffix: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}{suffix}"
    return f"{v}{suffix}"


def _pct(slo: dict, metric: str, q: str):
    return (slo or {}).get(metric, {}).get(q)


def _bshort(v) -> str:
    """Compact byte count for fixed-width columns: 427K, 3.2G, 24G."""
    if v is None:
        return "-"
    v = float(v)
    for div, s in ((2**40, "T"), (2**30, "G"), (2**20, "M"), (2**10, "K")):
        if v >= div:
            x = v / div
            return f"{x:.1f}{s}" if x < 10 else f"{x:.0f}{s}"
    return f"{int(v)}"


def _worker_burn(slo: dict):
    """Per-worker burn rate from its SHORTEST attainment window (the
    fast-paging one of the multi-window pair)."""
    windows = (slo or {}).get("windows") or {}
    if not windows:
        return None
    shortest = min(windows, key=lambda x: int(x))
    return (windows[shortest] or {}).get("burn_rate")


def _worst_traces_by_worker(traces) -> dict:
    """worker id -> (trace_id, duration_ms) of the slowest kept trace
    that touched it (fleet trace plane summaries)."""
    worst: dict = {}
    for t in traces or ():
        if not isinstance(t, dict):
            continue
        dur = t.get("duration_ms")
        if dur is None:
            continue
        for w in t.get("workers") or ():
            cur = worst.get(w)
            if cur is None or dur > cur[1]:
                worst[w] = (str(t.get("trace_id") or ""), float(dur))
    return worst


def render(snap: dict, traces=None) -> str:
    """Pure snapshot -> text table (no I/O; unit-testable). `traces`
    is the metrics service's kept-trace summary list (GET /v1/traces);
    the WORST-TRACE column shows the slowest kept trace touching each
    worker as `<id prefix> <ms>`."""
    cols = (
        ("WORKER", 22), ("ROLE", 8), ("MODEL", 12), ("REQ/S", 7),
        ("TOK/S", 8), ("TTFT p50/p95", 14), ("ITL p50/p95", 12),
        ("KV%", 6), ("WM", 6), ("MFU", 7), ("COMP", 5), ("PREEMPT", 7),
        ("SPEC%", 6), ("TIER/HIT", 12), ("HBM w/kv/free", 15),
        ("STALLS", 6), ("BURN", 6),
        ("WORST-TRACE", 16), ("AGE s", 6),
    )
    worst = _worst_traces_by_worker(traces)
    out = [" ".join(f"{h:<{w}}" for h, w in cols)]
    for iid, w in sorted((snap.get("workers") or {}).items()):
        slo = w.get("slo") or {}
        kv = w.get("kv_usage")
        burn = _worker_burn(slo)
        wt = worst.get(iid)
        row = (
            iid[:22], w.get("role", "?"), str(w.get("model", "?"))[:12],
            _fmt(w.get("req_s")), _fmt(w.get("tok_s")),
            f"{_fmt(_pct(slo, 'ttft_ms', 'p50'), 0)}/"
            f"{_fmt(_pct(slo, 'ttft_ms', 'p95'), 0)}",
            f"{_fmt(_pct(slo, 'itl_ms', 'p50'), 0)}/"
            f"{_fmt(_pct(slo, 'itl_ms', 'p95'), 0)}",
            _fmt(kv * 100.0 if kv is not None else None, 0),
            _fmt(w.get("kv_pages_watermark"), 0),
            _fmt(w.get("mfu"), 4), _fmt(w.get("compiles"), 0),
            _fmt(w.get("preemptions"), 0),
            # live draft-acceptance rate (speculative decoding), keyed
            # on the windowed draft count so the three states read
            # apart: a rate (incl. "0" = actively-failing draft) while
            # the window has drafts, "idle" when speculation ran before
            # but the window drained, "-" when it never ran
            (
                _fmt((w.get("spec_accept_rate") or 0.0) * 100.0, 0)
                if w.get("spec_window_drafted")
                else ("idle" if w.get("spec_drafted") else "-")
            ),
            # KV economy tier view: lower-tier block residency
            # (host/disk, KVBM write-back demotion) and which tier
            # served prefix-hit continuations — "12h3d 5/1" reads
            # "12 host + 3 disk blocks resident, 5 host / 1 disk hits".
            # Workers without KVBM tiers show "-", never zeros.
            (
                f"{int(w.get('kvbm_host_blocks') or 0)}h"
                f"{int(w.get('kvbm_disk_blocks') or 0)}d "
                f"{int(w.get('kvbm_host_hits_total') or 0)}/"
                f"{int(w.get('kvbm_disk_hits_total') or 0)}"
                if any(
                    w.get(f) is not None for f in (
                        "kvbm_host_blocks", "kvbm_disk_blocks",
                        "kvbm_demotions_total",
                    )
                )
                else "-"
            ),
            # HBM accounting view: weights-resident / KV-pool / free
            # bytes summed over the worker's local devices ("3.2G/1.1G/
            # 11G"). Workers predating the perf plane show "-" — absence
            # of accounting, not an empty device.
            (
                f"{_bshort(w.get('hbm_weights_bytes'))}/"
                f"{_bshort(w.get('hbm_kv_pool_bytes'))}/"
                f"{_bshort(w.get('hbm_free_bytes'))}"
                if any(
                    w.get(f) is not None for f in (
                        "hbm_weights_bytes", "hbm_kv_pool_bytes",
                        "hbm_free_bytes",
                    )
                )
                else "-"
            ),
            _fmt(w.get("stalls_total"), 0),
            _fmt(burn, 1, "x") if burn is not None else "-",
            f"{wt[0][:8]} {wt[1]:.0f}ms" if wt else "-",
            _fmt(w.get("last_seen_s")),
        )
        out.append(
            " ".join(f"{str(v):<{wd}}" for v, (_, wd) in zip(row, cols))
        )
    fleet = snap.get("fleet") or {}
    out.append("")
    out.append(f"fleet: {fleet.get('workers', 0)} workers")
    slo = fleet.get("slo")
    if slo:
        for m, label in (
            ("ttft_ms", "ttft"), ("itl_ms", "itl"), ("e2e_ms", "e2e"),
        ):
            q = slo.get(m)
            if q:
                out.append(
                    f"  {label:<5} p50 {_fmt(q.get('p50'))} ms   "
                    f"p95 {_fmt(q.get('p95'))} ms   "
                    f"p99 {_fmt(q.get('p99'))} ms   (n={q.get('n')})"
                )
        out.append(
            f"  sla   attainment {_fmt(slo.get('attainment'), 4)}   "
            f"goodput {slo.get('goodput_tokens_total', 0)}/"
            f"{slo.get('tokens_total', 0)} tokens"
        )
        for w_s, wd in sorted(
            (slo.get("windows") or {}).items(), key=lambda x: int(x[0])
        ):
            out.append(
                f"    {w_s:>4}s window: attainment "
                f"{_fmt(wd.get('attainment'), 4)}  burn rate "
                f"{_fmt(wd.get('burn_rate'), 2)}x  "
                f"({wd.get('requests', 0)} req)"
            )
    for role, r in sorted((snap.get("roles") or {}).items()):
        out.append(
            f"  {role:<6} {r.get('workers', 0)} workers  "
            f"tok/s {_fmt(r.get('tokens_per_s'))}  "
            f"mfu {_fmt(r.get('mfu'), 4)}  "
            f"kv {_fmt((r.get('kv_usage') or 0) * 100, 0)}%  "
            f"compiles {sum((r.get('compiles_by_kind') or {}).values())}"
        )
    return "\n".join(out)


#: severity -> ANSI color for the --events timeline
_SEV_COLORS = {"info": "\x1b[36m", "warning": "\x1b[33m",
               "critical": "\x1b[31m"}
_RESET = "\x1b[0m"


def render_events(events, color: bool = True) -> str:
    """Pure event list (GET /v1/fleet/events order: newest last) ->
    one line per event, severity-colored: time, type, source, count,
    compact attrs."""
    lines = []
    for e in events or ():
        if not isinstance(e, dict):
            continue
        sev = str(e.get("severity") or "info")
        ts = time.strftime(
            "%H:%M:%S", time.localtime(float(e.get("ts") or 0.0))
        )
        count = int(e.get("count") or 1)
        attrs = " ".join(
            f"{k}={v}" for k, v in sorted((e.get("attrs") or {}).items())
        )
        head = f"{e.get('type', '?'):<16}"
        if color:
            head = f"{_SEV_COLORS.get(sev, '')}{head}{_RESET}"
        lines.append(
            f"{ts} {sev[:4]:<4} {head} "
            f"{str(e.get('source') or '-'):<22}"
            + (f" x{count}" if count > 1 else "")
            + (f"  {attrs}" if attrs else "")
        )
    if not lines:
        lines = ["(no fleet events)"]
    return "\n".join(lines)


def fetch(url: str, path: str = "/v1/fleet") -> dict:
    with urllib.request.urlopen(f"{url}{path}", timeout=5) as resp:
        return json.loads(resp.read().decode())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--url", default="http://127.0.0.1:9091",
        help="metrics service base URL",
    )
    ap.add_argument(
        "--watch", type=float, default=0.0, metavar="SECONDS",
        help="redraw every N seconds (0 = one shot)",
    )
    ap.add_argument(
        "--snapshot", default=None,
        help="render a recorded snapshot JSON file instead of fetching",
    )
    ap.add_argument(
        "--events", action="store_true",
        help="render the fleet event timeline (GET /v1/fleet/events) "
             "instead of the worker table",
    )
    ap.add_argument(
        "--no-color", action="store_true",
        help="disable ANSI severity colors in --events output",
    )
    args = ap.parse_args(argv)
    while True:
        if args.events:
            try:
                doc = fetch(args.url, "/v1/fleet/events")
            except Exception as e:
                print(
                    f"fetch {args.url}/v1/fleet/events failed: {e}",
                    file=sys.stderr,
                )
                if not args.watch:
                    return 1
                time.sleep(args.watch)
                continue
            text = render_events(
                doc.get("events"), color=not args.no_color
            )
        else:
            if args.snapshot:
                with open(args.snapshot) as f:
                    snap = json.load(f)
                traces = None
            else:
                try:
                    snap = fetch(args.url)
                except Exception as e:
                    print(
                        f"fetch {args.url}/v1/fleet failed: {e}",
                        file=sys.stderr,
                    )
                    if not args.watch:
                        return 1
                    time.sleep(args.watch)
                    continue
                try:
                    # kept-trace summaries feed the WORST-TRACE column;
                    # an older metrics service without the trace plane
                    # just loses the column, never the table
                    traces = fetch(
                        args.url, "/v1/traces?sort=duration&limit=64"
                    ).get("traces")
                except Exception:
                    traces = None
            text = render(snap, traces=traces)
        if args.watch:
            print("\x1b[2J\x1b[H" + text, flush=True)
            time.sleep(args.watch)
        else:
            print(text)
            return 0


if __name__ == "__main__":
    sys.exit(main())

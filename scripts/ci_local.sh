#!/usr/bin/env bash
# Execute the CI workflow's steps locally (no Actions runner on the TPU
# pod) and record the outcome in artifacts/ci_run.json — the in-repo
# green-run evidence .github/workflows/ci.yml points at.
set -u
cd "$(dirname "$0")/.."
mkdir -p artifacts

START=$(date -u +%Y-%m-%dT%H:%M:%SZ)
declare -A RESULTS
FAIL=0

step() { # name, command...
  local name=$1; shift
  echo "== $name"
  local t0=$SECONDS
  if "$@" > "artifacts/ci_${name}.log" 2>&1; then
    RESULTS[$name]="pass $(($SECONDS - t0))s"
  else
    RESULTS[$name]="FAIL $(($SECONDS - t0))s"
    FAIL=1
    tail -n 20 "artifacts/ci_${name}.log"
  fi
}

# Same step set as .github/workflows/ci.yml (minus pip install — the
# pod image has the deps baked in; minus the standalone helm template —
# tests/test_helm_chart.py renders the chart inside the suite).
step build_native make -C native
step test_suite python -m pytest tests/ -q

{
  echo "{"
  echo "  \"started\": \"$START\","
  echo "  \"finished\": \"$(date -u +%Y-%m-%dT%H:%M:%SZ)\","
  echo "  \"git\": \"$(git rev-parse HEAD)\","
  echo "  \"steps\": {"
  first=1
  for k in build_native test_suite; do
    [ $first -eq 0 ] && echo ","
    first=0
    printf '    "%s": "%s"' "$k" "${RESULTS[$k]}"
  done
  echo ""
  echo "  },"
  echo "  \"green\": $([ $FAIL -eq 0 ] && echo true || echo false)"
  echo "}"
} > artifacts/ci_run.json
cat artifacts/ci_run.json
exit $FAIL

"""Dissect the decode-step time on the real chip.

bench_1b measures ~13 ms/token at batch 128 for llama3-1b, vs a ~4 ms
memory roofline (2.5 GB bf16 weights + ~0.7 GB KV reads per fused step at
819 GB/s v5e HBM). This script times the SAME jitted fused-decode program
the engine serves with, under both attention impls, plus a dense-only
floor, to locate the gap:

  full_pallas   — engine's decode_multi program, attention_impl=pallas
  full_xla      — same, attention_impl=xla
  dense_floor   — model forward with attention replaced by identity
                  (weight-streaming floor for the dense stack)

Times are per-token (per fused inner step), steady state, K=16 fused
steps per dispatch so the ~65 ms tunnel RTT amortizes to <1 ms/step.
Writes artifacts/tpu/decode_profile.json.

Usage (tunnel alive): python scripts/tpu_decode_profile.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dynamo_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

BATCHES = (16, 128)  # small-batch latency vs large-batch throughput regime
K_STEPS = 16
ISL = 128  # resident context per sequence when decode is measured
MODEL = os.environ.get("PROFILE_MODEL", "llama3-1b")


def build_engine(attention_impl: str, batch: int):
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    cfg = EngineConfig(
        model=MODEL,
        num_pages=batch * 4 + 64,
        page_size=64,
        max_pages_per_seq=8,
        decode_buckets=(batch,),
        prefill_chunk=128,
        prefill_token_budget=batch * 128,
        decode_steps=K_STEPS,
        max_seqs=batch,
        dtype="bfloat16",
        enable_prefix_caching=False,
        attention_impl=attention_impl,
    )
    return JaxEngine(cfg)


def time_full(eng, batch: int) -> dict:
    """Steady-state per-token time of the engine's own fused decode. Run
    the real serving loop with max_tokens large enough that the timed
    region is pure decode_multi dispatches."""
    import numpy as np

    from dynamo_tpu.engine.request import SamplingParams

    rng = np.random.default_rng(0)
    vocab = int(getattr(eng.adapter.config, "vocab_size", 32000))
    hi = min(32000, vocab - 1)
    prompts = [
        [int(x) for x in rng.integers(1, hi, ISL)] for _ in range(batch)
    ]
    for i, p in enumerate(prompts):
        eng.add_request(
            f"w{i}", p, SamplingParams(temperature=0.0, max_tokens=K_STEPS * 5)
        )
    # prefill + first fused decode dispatch (compiles) — untimed
    while eng.has_work:
        outs = eng.step()
        if outs and not outs[0].is_first:
            break
    t0 = time.perf_counter()
    tokens = 0
    dispatches = 0
    while eng.has_work:
        outs = eng.step()
        tokens += sum(len(o.new_token_ids) for o in outs)
        dispatches += 1
    dt = time.perf_counter() - t0
    return {
        "tokens": tokens,
        "dispatches": dispatches,
        "wall_s": round(dt, 3),
        "ms_per_token_row": round(1000 * dt / max(1, tokens / batch), 3),
        "tok_s": round(tokens / dt, 1),
    }


def time_dense_floor(batch: int) -> dict:
    """Weight-streaming floor: the same parameter stack driven as pure
    dense matmuls (one token per sequence, attention output zeroed via a
    no-op context of length 1 is still paged — instead we time the lm
    head + mlp/qkv matmuls directly)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.registry import get_model

    adapter = get_model(MODEL, dtype="bfloat16", attention_impl="xla")
    params = adapter.init_params(jax.random.key(0))

    leaves = [x for x in jax.tree.leaves(params) if x.ndim >= 2]
    x0 = jnp.ones((batch, max(l.shape[0] for l in leaves)), jnp.bfloat16)

    @jax.jit
    def stream_all(x):
        # touch every >=2D parameter with a matmul shaped [B, in] @ [in, out]
        acc = jnp.zeros((batch,), jnp.float32)
        for leaf in leaves:
            w = leaf.reshape(leaf.shape[0], -1)
            y = jax.lax.dot_general(
                x[:, : w.shape[0]], w,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc = acc + y.sum(axis=-1)
        return acc

    stream_all(x0).block_until_ready()
    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        stream_all(x0).block_until_ready()
    dt = (time.perf_counter() - t0) / n
    total_bytes = sum(l.size * l.dtype.itemsize for l in leaves)
    return {
        "ms": round(1000 * dt, 3),
        "weight_bytes": int(total_bytes),
        "implied_gb_s": round(total_bytes / dt / 1e9, 1),
    }


def main() -> None:
    import jax

    out = {
        "platform": jax.devices()[0].platform,
        "k_steps": K_STEPS,
        "model": MODEL,
        "batches": {},
    }
    for batch in BATCHES:
        row = {"dense_floor": time_dense_floor(batch)}
        for impl in ("pallas", "xla"):
            eng = build_engine(impl, batch)
            row[f"full_{impl}"] = time_full(eng, batch)
            del eng
        out["batches"][str(batch)] = row
    path = Path(__file__).resolve().parent.parent / "artifacts" / "tpu"
    path.mkdir(parents=True, exist_ok=True)
    (path / "decode_profile.json").write_text(json.dumps(out, indent=1))
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()

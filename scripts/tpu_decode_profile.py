"""Dissect the decode-step time on the real chip.

bench_1b measures ~13 ms/token at batch 128 for llama3-1b, vs a ~4 ms
memory roofline (2.5 GB bf16 weights + ~0.7 GB KV reads per fused step at
819 GB/s v5e HBM). This script times the SAME jitted fused-decode program
the engine serves with, under both attention impls, plus a dense-only
floor, to locate the gap:

  full_pallas       — engine's decode_multi program, attention_impl=pallas
  full_xla          — same, attention_impl=xla
  full_pallas_kvq   — pallas with kv_quantize=int8 (halved KV traffic)
  dense_floor       — model forward with attention replaced by identity
                      (weight-streaming floor for the dense stack)
  kstep_sweep       — the decode_kstep program (on-device sampling, stop
                      checks, paged-KV writes) ms/step + roofline
                      attainment vs K in {1,2,4,8,16}

For each impl the SAME program is also timed WITHOUT the host loop
(`pure_*`): fixed device inputs, one block per dispatch. That DIRECT
split — pure program ms/dispatch vs serve ms/dispatch, difference =
host-loop overhead — is what the 13 ms → 3.7 ms roofline argument rests
on (VERDICT r06 item #9; previously inferred from the 3.1× serve ratio).
A computed `roofline` block (weight + actual-dtype KV bytes / HBM BW)
rides in the artifact so program time and its floor sit side by side.

Times are per-token (per fused inner step), steady state, K=16 fused
steps per dispatch so the ~65 ms tunnel RTT amortizes to <1 ms/step.
Writes artifacts/tpu/decode_profile.json.

Usage (tunnel alive): python scripts/tpu_decode_profile.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dynamo_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

BATCHES = (16, 128)  # small-batch latency vs large-batch throughput regime
K_STEPS = 16
ISL = 128  # resident context per sequence when decode is measured
MODEL = os.environ.get("PROFILE_MODEL", "llama3-1b")
#: v5e HBM bandwidth for the computed roofline (override per generation)
HBM_GB_S = float(os.environ.get("PROFILE_HBM_GB_S", "819"))


def build_engine(attention_impl: str, batch: int, kv_quantize=None):
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    cfg = EngineConfig(
        model=MODEL,
        num_pages=batch * 4 + 64,
        page_size=64,
        max_pages_per_seq=8,
        decode_buckets=(batch,),
        prefill_chunk=128,
        prefill_token_budget=batch * 128,
        decode_steps=K_STEPS,
        max_seqs=batch,
        dtype="bfloat16",
        enable_prefix_caching=False,
        attention_impl=attention_impl,
        kv_quantize=kv_quantize,
    )
    return JaxEngine(cfg)


def roofline(eng, batch: int) -> dict:
    """Computed per-fused-step HBM floor for THIS engine's dtypes: the
    whole weight stack streams once per fused step; each step reads every
    resident sequence's KV history once (the flash walk's contract) and
    writes one token row per layer. Quantized pools count narrow pages +
    their f32 scale planes — the measured program time should close
    toward this number, and the fp-vs-int8 delta IS the KV-traffic
    saving."""
    import jax

    weight_bytes = sum(
        int(x.size) * x.dtype.itemsize for x in jax.tree.leaves(eng.params)
    )
    kv = eng.kv
    s = eng.config.page_size
    pages_per_seq = -(-ISL // s)
    # bytes of one (layer, page) k+v slice incl. scale planes
    per_page = sum(
        int(x.shape[2] * (x.shape[3] if x.ndim > 3 else 1)
            * (x.shape[4] if x.ndim > 4 else 1))
        * x.dtype.itemsize
        for x in (kv.k, kv.v, kv.k_scale, kv.v_scale)
        if x is not None
    )
    n_layers = kv.k.shape[0]
    kv_read = batch * pages_per_seq * per_page * n_layers
    kv_write = kv_read // (pages_per_seq * s)  # one row/seq/layer
    total = weight_bytes + kv_read + kv_write
    return {
        "weight_bytes": weight_bytes,
        "kv_read_bytes_per_step": int(kv_read),
        "roofline_ms_per_step": round(1000 * total / (HBM_GB_S * 1e9), 3),
    }


def time_full(eng, batch: int) -> dict:
    """Steady-state per-token time of the engine's own fused decode. Run
    the real serving loop with max_tokens large enough that the timed
    region is pure decode_multi dispatches."""
    import numpy as np

    from dynamo_tpu.engine.request import SamplingParams

    rng = np.random.default_rng(0)
    vocab = int(getattr(eng.adapter.config, "vocab_size", 32000))
    hi = min(32000, vocab - 1)
    prompts = [
        [int(x) for x in rng.integers(1, hi, ISL)] for _ in range(batch)
    ]
    for i, p in enumerate(prompts):
        eng.add_request(
            f"w{i}", p, SamplingParams(temperature=0.0, max_tokens=K_STEPS * 5)
        )
    # prefill + first fused decode dispatch (compiles) — untimed
    while eng.has_work:
        outs = eng.step()
        if outs and not outs[0].is_first:
            break
    t0 = time.perf_counter()
    tokens = 0
    dispatches = 0
    while eng.has_work:
        outs = eng.step()
        tokens += sum(len(o.new_token_ids) for o in outs)
        dispatches += 1
    dt = time.perf_counter() - t0
    return {
        "tokens": tokens,
        "dispatches": dispatches,
        "wall_s": round(dt, 3),
        "ms_per_token_row": round(1000 * dt / max(1, tokens / batch), 3),
        "tok_s": round(tokens / dt, 1),
    }


def time_pure_program(eng, batch: int) -> dict:
    """The same fused decode_multi program timed WITHOUT the engine's host
    loop: fixed device-resident inputs, kv threaded through (it may be
    donated), one block per call mirroring the per-dispatch sync. The gap
    serve_ms_per_dispatch - pure_ms_per_dispatch is the host overhead
    (scheduling, input staging, detokenize feedback) — the number that
    says whether further host-loop work (input packing) pays."""
    import jax
    import numpy as np

    fn = eng._get_step_fn(
        "decode_multi", batch, K_STEPS, greedy=True, lp=-1, pen=0,
        bias=False,
    )
    mp = eng.config.max_pages_per_seq
    tokens = np.ones((batch, 1), np.int32)
    positions = np.full((batch, 1), ISL - 1, np.int32)
    valid = np.ones((batch, 1), bool)
    pt = np.zeros((batch, mp), np.int32)
    for i in range(batch):
        pt[i, :4] = 1 + 4 * i + np.arange(4)
    samp, _ = eng._sampling_arrays([], pad_to=batch)
    dev = eng._dev_tree({"base": (tokens, positions, valid, pt),
                         "samp": samp})
    d_tokens, d_positions, d_valid, d_pt = dev["base"]
    kv = eng.kv
    ids, kv = fn(eng.params, d_tokens, d_positions, d_valid, kv, d_pt,
                 *dev["samp"])
    jax.block_until_ready(ids)
    n = 5
    t0 = time.perf_counter()
    for _ in range(n):
        ids, kv = fn(eng.params, d_tokens, d_positions, d_valid, kv, d_pt,
                     *dev["samp"])
        jax.block_until_ready(ids)
    dt = (time.perf_counter() - t0) / n
    eng.kv = kv
    return {
        "ms_per_dispatch": round(1000 * dt, 3),
        "ms_per_token_row": round(1000 * dt / K_STEPS, 3),
    }


def time_kstep_sweep(eng, batch: int, roof: dict) -> dict:
    """ISSUE 16 leg: the decode_kstep program (on-device sampling, stop
    checks, paged-KV writes — ONE host sync per K tokens) timed pure for
    K in {1,2,4,8,16}. ms/step should be ~flat across K while ms/dispatch
    grows ~linearly; `attainment` is roofline_ms_per_step / measured
    ms/step, the number /v1/debug/programs reports live for the
    decode_kstep family. Read next to host_overhead_ms_*: the K-window
    pays the host overhead once per K steps instead of every step."""
    import jax
    import numpy as np

    from dynamo_tpu.engine.sampling import STOP_SLOTS

    mp = eng.config.max_pages_per_seq
    tokens = np.ones((batch, 1), np.int32)
    positions = np.full((batch, 1), ISL - 1, np.int32)
    valid = np.ones((batch, 1), bool)
    pt = np.zeros((batch, mp), np.int32)
    for i in range(batch):
        pt[i, :4] = 1 + 4 * i + np.arange(4)
    # no stop tokens, unbounded budgets: every row stays alive the whole
    # window, so the timed program does the full K steps of work
    stops = np.full((batch, STOP_SLOTS), -1, np.int32)
    budgets = np.full((batch,), 1 << 30, np.int32)
    samp, _ = eng._sampling_arrays([], pad_to=batch)
    dev = eng._dev_tree({"base": (tokens, positions, valid, pt),
                         "ctl": (stops, budgets), "samp": samp})
    d_tokens, d_positions, d_valid, d_pt = dev["base"]
    d_stops, d_budgets = dev["ctl"]
    out = {}
    for k in (1, 2, 4, 8, 16):
        fn = eng._get_step_fn(
            "decode_kstep", batch, k, greedy=True, lp=-1, pen=0,
            bias=False,
        )
        kv = eng.kv
        ids, _n, kv = fn(eng.params, d_tokens, d_positions, d_valid, kv,
                         d_pt, d_stops, d_budgets, *dev["samp"])
        jax.block_until_ready(ids)
        n = 5
        t0 = time.perf_counter()
        for _ in range(n):
            ids, _n, kv = fn(eng.params, d_tokens, d_positions, d_valid,
                             kv, d_pt, d_stops, d_budgets, *dev["samp"])
            jax.block_until_ready(ids)
        dt = (time.perf_counter() - t0) / n
        eng.kv = kv
        ms_step = 1000 * dt / k
        out[str(k)] = {
            "ms_per_dispatch": round(1000 * dt, 3),
            "ms_per_step": round(ms_step, 3),
            "attainment": round(
                roof["roofline_ms_per_step"] / ms_step, 3
            ) if ms_step > 0 else None,
        }
    return out


def time_dense_floor(batch: int) -> dict:
    """Weight-streaming floor: the same parameter stack driven as pure
    dense matmuls (one token per sequence, attention output zeroed via a
    no-op context of length 1 is still paged — instead we time the lm
    head + mlp/qkv matmuls directly)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.registry import get_model

    adapter = get_model(MODEL, dtype="bfloat16", attention_impl="xla")
    params = adapter.init_params(jax.random.key(0))

    leaves = [x for x in jax.tree.leaves(params) if x.ndim >= 2]
    x0 = jnp.ones((batch, max(l.shape[0] for l in leaves)), jnp.bfloat16)

    @jax.jit
    def stream_all(x, ws):
        # touch every >=2D parameter with a matmul shaped [B, in] @ [in, out]
        # (ws passed as an ARGUMENT — closing over the params bakes 2.5GB
        # of constants into the lowered program and stalls tunnel compiles)
        acc = jnp.zeros((batch,), jnp.float32)
        for leaf in ws:
            w = leaf.reshape(leaf.shape[0], -1)
            y = jax.lax.dot_general(
                x[:, : w.shape[0]], w,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc = acc + y.sum(axis=-1)
        return acc

    stream_all(x0, leaves).block_until_ready()
    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        stream_all(x0, leaves).block_until_ready()
    dt = (time.perf_counter() - t0) / n
    total_bytes = sum(l.size * l.dtype.itemsize for l in leaves)
    return {
        "ms": round(1000 * dt, 3),
        "weight_bytes": int(total_bytes),
        "implied_gb_s": round(total_bytes / dt / 1e9, 1),
    }


def main() -> None:
    import jax

    out = {
        "platform": jax.devices()[0].platform,
        "k_steps": K_STEPS,
        "model": MODEL,
        "batches": {},
    }
    for batch in BATCHES:
        row = {"dense_floor": time_dense_floor(batch)}
        for tag, impl, kvq in (
            ("pallas", "pallas", None),
            ("xla", "xla", None),
            ("pallas_kvq", "pallas", "int8"),
        ):
            eng = build_engine(impl, batch, kv_quantize=kvq)
            row[f"full_{tag}"] = time_full(eng, batch)
            row[f"pure_{tag}"] = time_pure_program(eng, batch)
            row[f"roofline_{tag}"] = roofline(eng, batch)
            if tag == "pallas":
                # K-step window sweep on the serving-default impl only
                row["kstep_sweep"] = time_kstep_sweep(
                    eng, batch, row[f"roofline_{tag}"]
                )
            full = row[f"full_{tag}"]
            if full["dispatches"]:
                # the DIRECT program-vs-host split: serve ms/dispatch −
                # pure program ms/dispatch = host-loop overhead
                serve_ms = 1000 * full["wall_s"] / full["dispatches"]
                row[f"serve_ms_per_dispatch_{tag}"] = round(serve_ms, 3)
                row[f"host_overhead_ms_{tag}"] = round(
                    serve_ms - row[f"pure_{tag}"]["ms_per_dispatch"], 3
                )
            del eng
        out["batches"][str(batch)] = row
    path = Path(__file__).resolve().parent.parent / "artifacts" / "tpu"
    path.mkdir(parents=True, exist_ok=True)
    (path / "decode_profile.json").write_text(json.dumps(out, indent=1))
    print(json.dumps(out, indent=1))
    # perf-regression ledger row (scripts/perf_diff.py): headline
    # tok_s / ms_per_dispatch of this profile, best-effort — a ledger
    # problem never fails the profile run
    try:
        from dynamo_tpu.telemetry import perf_ledger

        row = perf_ledger.row_from_decode_profile(
            out, os.environ.get("DYNTPU_ROUND", "adhoc")
        )
        ledger = os.environ.get("DYNTPU_PERF_LEDGER")
        if ledger != "":
            perf_ledger.append_row(
                row,
                ledger
                or str(
                    Path(__file__).resolve().parent.parent
                    / perf_ledger.DEFAULT_LEDGER
                ),
            )
    except Exception as e:
        print(f"decode_profile: perf_ledger append failed: {e}",
              file=sys.stderr)


if __name__ == "__main__":
    main()

"""Pre-warm the persistent XLA compile cache for the disagg A/B shapes.

The round-3 TPU disagg A/B died in bring-up: the decode worker sat in
cold compiles behind a flaky tunnel until the 600 s readiness window
expired (artifacts/tpu/disagg_ab.err). Compiles are content-addressed in
the persistent cache (DYN_COMPILE_CACHE, enabled at engine boot), so one
in-process run with the A/B's exact engine shapes makes every later
worker boot warm — compile once here, then the A/B's four processes all
hit the cache.

Shapes mirror scripts/tpu_watch_queue.sh disagg_ab: llama3-1b bf16,
page 64 x 1024 pages, max-context 4096 (max_pages_per_seq 64), CLI
defaults prefill_chunk=512 / max_seqs=32, ISL 1024, concurrency 8,
decode fusion 64 (the A/B passes --decode-steps 64 — the k=64
decode_multi programs are the expensive compiles).

Usage (tunnel alive): python scripts/tpu_prewarm.py
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dynamo_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

ISL, OSL, CONC = 1024, 80, 8


def main() -> None:
    import jax
    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    t0 = time.perf_counter()
    cfg = EngineConfig(
        model="llama3-1b",
        num_pages=1024,
        page_size=64,
        max_pages_per_seq=4096 // 64,
        prefill_chunk=512,
        max_seqs=32,
        dtype="bfloat16",
        decode_steps=64,
    )
    eng = JaxEngine(cfg)
    boot_s = time.perf_counter() - t0

    rng = np.random.default_rng(0)
    vocab = int(getattr(eng.adapter.config, "vocab_size", 32000))
    hi = min(32000, vocab - 1)
    # the A/B ramps through decode buckets 1..8 as requests arrive/finish;
    # submit all 8 so prefill (512-chunk) and every bucket <= 8 compile
    for i in range(CONC):
        toks = [int(x) for x in rng.integers(1, hi, ISL)]
        eng.add_request(
            f"warm{i}", toks, SamplingParams(temperature=0.0, max_tokens=OSL)
        )
    steps = 0
    t1 = time.perf_counter()
    while eng.has_work:
        eng.step()
        steps += 1
    out = {
        "platform": jax.devices()[0].platform,
        "boot_s": round(boot_s, 1),
        "serve_s": round(time.perf_counter() - t1, 1),
        "steps": steps,
        "requests": CONC,
        "isl": ISL,
        "osl": OSL,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# DeepSeek-R1-Distill-Llama-8B (BASELINE config 1) on the chip: int8
# weight-only so the 8B fits one v5e with KV headroom. Run after
# scripts/tpu_watch_queue.sh drains. Artifact: artifacts/tpu/bench_dsr1.json
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/tpu
mkdir -p "$OUT"

if ! timeout 120 python -c \
  "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
  >/dev/null 2>&1; then
  echo "tunnel down; not running" >&2
  exit 1
fi

BENCH_MODEL=deepseek-r1-distill-llama-8b BENCH_QUANTIZE=int8 \
  BENCH_REQUESTS=32 BENCH_ATTENTION=auto \
  timeout 3600 python bench.py > "$OUT/bench_dsr1.json" 2> "$OUT/bench_dsr1.err"
rc=$?
tail -c 300 "$OUT/bench_dsr1.json"
exit $rc

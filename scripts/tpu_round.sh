#!/usr/bin/env bash
# One-command TPU measurement plan: run when the axon tunnel is ALIVE.
# Probes first; each stage writes a JSON artifact under artifacts/tpu/.
# Stages are independent — a failure records the error and moves on, but
# a stage TIMEOUT (the SIGTERM-mid-RPC wedge trigger) forces a re-probe
# and aborts the run if the tunnel no longer answers. Bench artifacts
# whose extras.platform isn't "tpu" are marked CPU-FALLBACK, never to be
# folded into TPU rows.
#
#   bash scripts/tpu_round.sh            # everything
#   bash scripts/tpu_round.sh bench_1b   # one stage
#
# Fills the TPU rows of docs/PERF.md (see that file for the table).
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/tpu
mkdir -p "$OUT"
# perf-regression ledger (docs/observability.md "Reading the perf
# plane"): bench-family stages self-append one row per run to
# artifacts/perf_ledger.jsonl, named "<tag>/<stage>" so stages of one
# round don't clobber each other's latest-row slot. Diff rounds with
#   python scripts/perf_diff.py 20260801/bench_1b 20260807/bench_1b
ROUND_TAG="${DYNTPU_ROUND_TAG:-$(date +%Y%m%d)}"

probe() {
  echo "== probing TPU tunnel (120s timeout)"
  if ! timeout 120 python -c "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)"; then
    echo "TUNNEL DOWN — do CPU work instead, re-probe later (memory: tpu-tunnel-fragility)"
    exit 1
  fi
  echo "tunnel OK"
}

check_platform() { # artifact file: flag CPU fallbacks loudly
  if grep -q '"platform": "cpu"' "$1" 2>/dev/null; then
    mv "$1" "${1%.json}.CPU-FALLBACK.json"
    echo "CPU-FALLBACK artifact (tunnel died mid-run?) — NOT a TPU number"
    return 1
  fi
  return 0
}

run_stage() { # name, command...
  local name=$1; shift
  echo "== $name"
  DYNTPU_ROUND="${ROUND_TAG}/${name}" \
    timeout 3600 "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
  local rc=$?
  if [ $rc -eq 124 ]; then
    # SIGTERM mid-TPU-RPC is the documented wedge trigger: re-verify the
    # tunnel before burning hours on stages that would hang or fall back.
    echo "STAGE TIMED OUT — re-probing tunnel before continuing"
    probe
    return
  fi
  if [ $rc -eq 0 ]; then
    check_platform "$OUT/$name.json" && { tail -c 400 "$OUT/$name.json"; echo; }
  else
    echo "STAGE FAILED (see $OUT/$name.err)"; tail -5 "$OUT/$name.err"
  fi
}

bench_1b()   { run_stage bench_1b python bench.py; }
bench_1b_kvq() { # kv-quant A/B arm: same workload, int8 KV pages — read
                 # against bench_1b for the on-chip traffic win (BENCH_r06)
               BENCH_KV_QUANTIZE=int8 run_stage bench_1b_kvq python bench.py; }
bench_1b_mixed() { # mixed-steps chip arm (ISSUE 5): the c=32 saturation
                   # A/B (mixed_ab extras) measured on the chip with the
                   # headline model — burst-drain ITL p95 vs XOR
               BENCH_MIXED_AB=1 run_stage bench_1b_mixed python bench.py; }
bench_1b_spec() { # draft-model speculation chip arm (ISSUE 9): spec_ab
                  # extras at batch<=8 with llama3-draft (random-init —
                  # read modeled_at_accept_rate; point BENCH_SPEC_DRAFT
                  # at a distilled draft, or =llama3-1b for the
                  # self-draft upper bound, target >=2x)
               BENCH_SPEC=1 run_stage bench_1b_spec python bench.py; }
bench_1b_kstep() { # on-device K-step decode window chip arm (ISSUE 16):
                   # kstep_ab extras — ms/token A/B, fused K=8 window
                   # (one host sync per 8 tokens) vs per-token stepping
                   # with the headline model; read against the 13ms-vs-
                   # 3.7ms roofline gap in docs/PERF.md
               BENCH_KSTEP=8 run_stage bench_1b_kstep python bench.py; }
bench_1b_tp() { # pod-scale sharding chip arm (ISSUE 20): the headline
                # model over a tp=4,dp=2 logical-axis mesh with the
                # multi-host decode pipeline live — multihost_pipeline_ab
                # extras carry the modeled ms/token win vs the old
                # multi-host auto-off (the CPU contract pins >=1.5x;
                # read the on-chip ratio here)
               BENCH_MULTIHOST=1 BENCH_MULTIHOST_TOPOLOGY=tp=4,dp=2 \
               BENCH_TOPOLOGY=tp=4,dp=2 \
               run_stage bench_1b_tp python bench.py; }
bench_1b_prefixmig() { # per-prefix KV migration chip arm (ISSUE 18):
                   # prefix_migration_ab extras — turn-2 TTFT with the
                   # session's hot prefix chain migrated vs cold
                   # prefill, priced by the shared kv_economy CostModel
                   # (read flops_saved_per_byte + should_migrate +
                   # modeled_ttft_ratio on the chip wire format)
               BENCH_PREFIXMIG=1 run_stage bench_1b_prefixmig python bench.py; }
bench_8b()   { BENCH_MODEL=llama3-8b BENCH_QUANTIZE=int8 BENCH_REQUESTS=64 \
               run_stage bench_8b python bench.py; }
transfer()   { run_stage transfer python -m benchmarks.transfer_bench --mb 64; }
sweep()      { run_stage perf_sweep python -m benchmarks.perf --mode engine \
                 --model llama3-1b --distribution sharegpt \
                 --num-requests 64 --isl 512 --osl 128 --concurrency 1,4,16,64; }
sweep_8b()   { run_stage perf_sweep_8b python -m benchmarks.perf --mode engine \
                 --model llama3-8b --quantize int8 --distribution sharegpt \
                 --num-pages 512 \
                 --num-requests 32 --isl 512 --osl 128 --concurrency 1,4,16; }
                 # 512 pages: the 2048 default is 17GB of 8B-shape KV —
                 # with int8 weights that exceeds v5e HBM (measured 24.5G)
sla()        { run_stage profile_sla python -m benchmarks.profile_sla \
                 --model llama3-1b --isl 512 --osl 128 --concurrency 1,2,4,8; }
disagg_ab()  { run_stage disagg_ab python -m benchmarks.disagg_bench \
                 --model llama3-1b --dtype bfloat16 --page-size 64 \
                 --num-pages 1024 --max-context 4096 --max-local-prefill 256 \
                 --requests 32 --isl 1024 --osl 64 --concurrency 8; }

STAGES_ALL=(bench_1b bench_1b_kvq bench_1b_mixed bench_1b_spec bench_1b_kstep bench_1b_tp bench_1b_prefixmig bench_8b transfer sweep sweep_8b sla disagg_ab)
# disagg A/B last: two engine processes timeshare the one chip — expect
# contention; honest multi-chip runs need dp mesh halves or two hosts

probe
if [ $# -gt 0 ]; then
  for s in "$@"; do
    declare -f "$s" >/dev/null || { echo "unknown stage $s (have: ${STAGES_ALL[*]})"; exit 1; }
    "$s"
  done
else
  for s in "${STAGES_ALL[@]}"; do "$s"; done
fi
echo "== artifacts in $OUT/ — fold TPU numbers (never *.CPU-FALLBACK.json) into docs/PERF.md and BASELINE.json published{}"

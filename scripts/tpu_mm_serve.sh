#!/usr/bin/env bash
# BASELINE config 5 on the chip: the Qwen2-VL multimodal graph (encode
# worker -> prefill/decode worker) serving an image chat on the TPU.
# Random tiny weights (no checkpoints in the image) — the evidence is
# the full pipeline (ViT tower + m-RoPE splice + paged serving)
# compiling and serving on hardware. Artifact: artifacts/tpu/mm_serve.json
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/tpu
mkdir -p "$OUT"

if ! timeout 120 python -c \
  "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
  >/dev/null 2>&1; then
  echo "tunnel down; not running" >&2
  exit 1
fi

python - << 'PY' > "$OUT/mm_serve.json" 2> "$OUT/mm_serve.err"
import base64
import json
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np

try:
    PLATFORM = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.default_backend())"],
        capture_output=True, text=True, timeout=120,
    ).stdout.strip() or "unknown"
except (subprocess.SubprocessError, OSError):
    # best-effort provenance: a tunnel wedge between the wrapper's gate
    # and this probe must not kill the stage
    PLATFORM = "unknown"

PORT = 8931
cfg = open("examples/multimodal/config_qwen2vl.yaml").read()
cfg = cfg.replace("port: 8080", f"port: {PORT}")
cfg_path = "/tmp/mm_serve_chip.yaml"
open(cfg_path, "w").write(cfg)
proc = subprocess.Popen(
    [sys.executable, "-m", "dynamo_tpu.cli.run", "serve",
     "examples.multimodal.graph:MultimodalFrontend", "-f", cfg_path],
    stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
)
try:
    pixels = np.random.default_rng(0).random((16, 16, 3), np.float32)
    body = json.dumps({
        "model": "qwen2-vl-tiny",
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": "describe"},
                {"type": "image_pixels",
                 "data": base64.b64encode(pixels.tobytes()).decode(),
                 "shape": [16, 16, 3]},
            ],
        }],
        "max_tokens": 8,
    }).encode()
    deadline = time.time() + 1500  # tunnel compiles are minutes each
    last_err = None
    t0 = None
    while time.time() < deadline:
        try:
            t0 = time.time()
            req = urllib.request.Request(
                f"http://127.0.0.1:{PORT}/v1/chat/completions", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=600) as r:
                out = json.load(r)
            print(json.dumps({
                "ok": True,
                "platform": PLATFORM,
                "model": "qwen2-vl-tiny (random weights)",
                "completion_tokens": out["usage"]["completion_tokens"],
                "request_s": round(time.time() - t0, 2),
                "note": "full multimodal pipeline (ViT tower + m-RoPE "
                        "splice + paged decode) served end-to-end; "
                        "BASELINE config 5's topology",
            }, indent=1))
            break
        except Exception as e:  # noqa: BLE001 - boot races are expected
            last_err = repr(e)
            time.sleep(10)
    else:
        print(json.dumps({"ok": False, "error": last_err}, indent=1))
finally:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=20)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
rc=$?
tail -c 300 "$OUT/mm_serve.json"
exit $rc

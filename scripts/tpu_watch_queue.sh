#!/usr/bin/env bash
# Tunnel watcher: probe the axon TPU tunnel on a patient loop and, the
# moment it answers, run the round's outstanding TPU stages back to back
# (zero idle chip time after recovery). Probes are spaced 10 min apart —
# killed-mid-RPC probe clients are suspected of worsening a wedge, so we
# probe rarely and with a generous timeout.
#
#   bash scripts/tpu_watch_queue.sh           # default queue
#   bash scripts/tpu_watch_queue.sh stage...  # explicit stages
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/tpu
mkdir -p "$OUT"

probe_once() {
  timeout 120 python -c \
    "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
    >/dev/null 2>&1
}

wait_for_tunnel() {
  local n=0
  while ! probe_once; do
    n=$((n + 1))
    echo "$(date -u +%H:%M:%S) tunnel down (probe $n); retry in 10 min"
    sleep 600
  done
  echo "$(date -u +%H:%M:%S) tunnel OK after $n failed probes"
}

run_stage() { # name, command...
  local name=$1; shift
  echo "== $name"
  timeout 3600 "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  local rc=$?
  echo "$name rc=$rc"
  if [ $rc -ne 0 ]; then
    tail -5 "$OUT/$name.err"
    # a stage wedging usually means the tunnel died again — re-wait
    wait_for_tunnel
  else
    tail -c 300 "$OUT/$name.json"; echo
  fi
}

pallas_kernels() {
  # FIRST: Mosaic compile + numerics proof for all four kernels
  # (interpret=False). The round-4 decode kernel is a rewrite (flattened
  # page walk); if Mosaic rejects it this stage says so immediately and
  # explains any later pallas-path stage failures. Operational fallback:
  # attention_impl=xla everywhere.
  run_stage pallas_kernels python scripts/tpu_pallas_check.py
}
prewarm() {
  # populate the persistent compile cache with the disagg A/B's exact
  # shapes so the A/B's worker processes boot warm (round-3 failure mode:
  # decode worker cold-compiling past its readiness window)
  run_stage prewarm python scripts/tpu_prewarm.py
}
disagg_ab() {
  # burst mode: per-request timeout (a tunnel wedge costs requests, not
  # the stage), incremental partial artifact, decode fusion 64 (the
  # tunnel sync RTT dominates an un-fused decode step)
  run_stage disagg_ab python -m benchmarks.disagg_bench \
    --model llama3-1b --dtype bfloat16 --page-size 64 --num-pages 1024 \
    --max-context 4096 --max-local-prefill 256 --requests 24 --isl 1024 \
    --osl 64 --concurrency 8 --warmup 8 --decode-steps 64 \
    --request-timeout 120 --out "$OUT/disagg_ab_partial.json"
}
sla_8b() {
  run_stage profile_sla_8b python -m benchmarks.profile_sla \
    --model llama3-8b --quantize int8 --num-pages 448 \
    --num-requests 24 --isl 512 --osl 96 --concurrency 1,4,8,16 \
    --ttft-target 400 --itl-target 40 --decode-steps 64
}
sweep_8b() {
  run_stage perf_sweep_8b python -m benchmarks.perf --mode engine \
    --model llama3-8b --quantize int8 --distribution sharegpt \
    --num-pages 512 --num-requests 32 --isl 512 --osl 128 \
    --concurrency 1,4,16 --decode-steps 64
}
ft_kill() {
  run_stage ft_device_kill python scripts/tpu_ft_device_kill.py
}
routing() {
  run_stage routing_engine python -m benchmarks.routing_engine_bench \
    --model llama3-1b --dtype bfloat16 --page 16 --pages 512 \
    --max-context 2048 --depth 6 --branching 2 --suffix 64 \
    --requests 64 --osl 16 --concurrency 8 --warmup 8 --decode-steps 16
}
decode_profile() {
  # stage name differs from the script's own artifact
  # (decode_profile.json) — run_stage's stdout redirect opens its file at
  # offset 0, so a shared name would clobber the clean write_text JSON
  run_stage decode_prof python scripts/tpu_decode_profile.py
}
offload() {
  run_stage offload_ab python -m benchmarks.offload_bench \
    --model llama3-1b --dtype bfloat16 --page-size 16 --num-pages 192 \
    --max-context 2048 --users 8 --turns 4 --turn-chars 400 --osl 16 \
    --decode-steps 16
}
bench_dsv2() {
  # DeepSeek-V2-Lite (15.7B MLA+MoE) int8 on ONE v5e chip: the compressed
  # latent cache + weight-only int8 make it fit; random weights (no
  # checkpoints in the image), so tok/s+MFU are the story, not quality.
  BENCH_MODEL=deepseek-v2-lite BENCH_QUANTIZE=int8 BENCH_REQUESTS=32 \
    BENCH_ATTENTION=auto \
    run_stage bench_dsv2 python bench.py
}

bench_1b_sweep() {
  # re-capture the headline with the attention-impl sweep (auto vs
  # hybrid); bench.py reports the best with both in extras
  run_stage bench_1b python bench.py
}
bench_1b_kvq() {
  # kv-quant bench A/B arm (ISSUE 2): identical workload with int8 KV
  # pages; compare tok/s + pool-byte gauges against bench_1b
  BENCH_KV_QUANTIZE=int8 run_stage bench_1b_kvq python bench.py
}
bench_1b_mixed() {
  # mixed-steps chip arm (ISSUE 5): the c=32 saturation A/B on the chip
  # with the headline model — mixed_ab extras carry burst-drain ITL p95
  # and TTFT p50 ratios vs fixed-budget XOR scheduling
  BENCH_MIXED_AB=1 run_stage bench_1b_mixed python bench.py
}
bench_1b_spec() {
  # draft-model speculation chip arm (ISSUE 9): spec_ab extras — decode
  # tok/s A/B at batch<=8, fused draft+verify vs plain. Default draft is
  # llama3-draft (random-init: acceptance ~chance, read the
  # modeled_at_accept_rate curve); BENCH_SPEC_DRAFT=llama3-1b runs the
  # self-draft upper bound (acceptance ~1, target >=2x modeled)
  BENCH_SPEC=1 run_stage bench_1b_spec python bench.py
}
bench_1b_kstep() {
  # on-device K-step decode window chip arm (ISSUE 16): kstep_ab extras
  # — ms/token with the fused K=8 window (sampling, stop checks, and
  # paged-KV writes on device; one host sync per 8 tokens) vs the
  # per-token host loop, headline model. The number that re-measures
  # docs/PERF.md's 13ms-vs-3.7ms host-loop argument.
  BENCH_KSTEP=8 run_stage bench_1b_kstep python bench.py
}
bench_1b_tp() {
  # pod-scale sharding chip arm (ISSUE 20): headline model over a
  # tp=4,dp=2 logical-axis mesh with the multi-host decode pipeline
  # live — multihost_pipeline_ab extras carry the modeled ms/token win
  # vs the old multi-host auto-off (CPU contract pins >=1.5x)
  BENCH_MULTIHOST=1 BENCH_MULTIHOST_TOPOLOGY=tp=4,dp=2 \
    BENCH_TOPOLOGY=tp=4,dp=2 \
    run_stage bench_1b_tp python bench.py
}
bench_1b_prefixmig() {
  # per-prefix KV migration chip arm (ISSUE 18): prefix_migration_ab
  # extras — turn-2 TTFT with the session's hot prefix chain migrated
  # to a fresh engine vs cold prefill, priced by the shared kv_economy
  # CostModel (flops_saved_per_byte, should_migrate, modeled ratio on
  # the chip wire format)
  BENCH_PREFIXMIG=1 run_stage bench_1b_prefixmig python bench.py
}
pallas_gate() {
  # numerics GATE: prefill logit diff + 32-step teacher-forced drift
  # (budget 0.25 / >=90% argmax agreement); exit 2 = gate failed.
  # Stage name != the script's own pallas_serve_check.json artifact (see
  # decode_profile note).
  run_stage pallas_gate python scripts/tpu_pallas_serve_check.py
}
transfer() {
  # re-measure the transfer planes on the chip (host path now rides the
  # same-host shm plane; device pull needs the PJRT transfer server)
  run_stage transfer python -m benchmarks.transfer_bench --mb 64 --iters 4
}

STAGES=("$@")
[ ${#STAGES[@]} -eq 0 ] && STAGES=(pallas_kernels prewarm disagg_ab sweep_8b sla_8b ft_kill routing offload bench_dsv2 decode_profile bench_1b_sweep bench_1b_kvq bench_1b_mixed bench_1b_spec bench_1b_kstep bench_1b_prefixmig pallas_gate transfer)

wait_for_tunnel
for s in "${STAGES[@]}"; do
  "$s"
done
echo "queue complete"

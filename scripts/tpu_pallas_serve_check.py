"""On-device pallas-vs-xla serving agreement (VERDICT r2 item 2).

Three comparisons on the live TPU, llama3-1b shapes (seeded random
weights — no trained checkpoint exists in this zero-egress image):

1. model-forward logits: one 128-token prefill through forward() under
   attention_impl="xla" vs "pallas"; gate on max |Δlogit| < 0.25 (the
   measured value is ~0.07 on a ±5 logit range — bf16 accumulation-order
   noise across 16 layers, amplified by random near-uniform weights).
2. engine greedy agreement: same requests through two JaxEngines. With
   random weights argmax gaps are smaller than (1)'s noise, so token
   flips are EXPECTED; recorded as stats, not gated. (With a trained
   checkpoint the gap is orders of magnitude larger and greedy is
   stable; tests/test_checkpoint_e2e.py covers that on CPU.)
3. steady-state timing: a second, fully-warmed run of the same workload
   per impl (first run pays Mosaic remote-compile).

Writes artifacts/tpu/pallas_serve_check.json.
Run: python scripts/tpu_pallas_serve_check.py        (requires live TPU)
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

LOGIT_TOL = 0.25


def logits_check():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models import LlamaConfig, forward, init_params
    from dynamo_tpu.models.llama import init_kv_pages

    cfg_x = dataclasses.replace(
        LlamaConfig.llama3_1b(), attention_impl="xla"
    )
    cfg_p = dataclasses.replace(
        LlamaConfig.llama3_1b(), attention_impl="pallas"
    )
    params = init_params(jax.random.key(0), cfg_x)
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    rng = np.random.default_rng(7)
    T = 128
    toks = jnp.asarray(rng.integers(1, 32000, (1, T)), jnp.int32)
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (1, 1))
    valid = jnp.ones((1, T), bool)
    pt = jnp.asarray(np.arange(1, 9, dtype=np.int32)[None])
    outs = {}
    for name, cfg in (("xla", cfg_x), ("pallas", cfg_p)):
        kv = init_kv_pages(cfg, num_pages=64, page_size=64)
        logits, _ = forward(params, cfg, toks, positions, valid, kv, pt)
        outs[name] = np.asarray(logits[0, -1].astype(jnp.float32))
    diff = float(np.abs(outs["xla"] - outs["pallas"]).max())
    return {
        "max_abs_logit_diff": diff,
        "logit_range": [
            float(outs["xla"].min()), float(outs["xla"].max())
        ],
        "argmax_agree": bool(
            outs["xla"].argmax() == outs["pallas"].argmax()
        ),
        "ok": diff < LOGIT_TOL,
    }


def run_engine(impl: str, prompts, osl: int):
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    cfg = EngineConfig(
        model="llama3-1b",
        num_pages=256,
        page_size=64,
        max_pages_per_seq=8,
        decode_buckets=(4, 8),
        prefill_chunk=128,
        prefill_token_budget=1024,
        decode_steps=8,
        max_seqs=8,
        dtype="bfloat16",
        enable_prefix_caching=False,
        attention_impl=impl,
    )
    eng = JaxEngine(cfg)

    def one_run(tag):
        t0 = time.time()
        for i, p in enumerate(prompts):
            eng.add_request(
                f"{tag}{i}", p, SamplingParams(temperature=0.0, max_tokens=osl)
            )
        outs: dict[str, list[int]] = {}
        n = 0
        while eng.has_work:
            for out in eng.step():
                outs.setdefault(out.request_id, []).extend(out.new_token_ids)
                n += len(out.new_token_ids)
        return outs, n / (time.time() - t0)

    outs, _ = one_run("w")  # warm: compiles every program
    eng.allocator.clear_cache()
    outs, tok_s = one_run("r")
    return outs, tok_s


def main():
    import jax

    plat = jax.devices()[0].platform
    print(f"platform: {plat}")
    if plat == "cpu":
        print("refusing: this check must run on TPU")
        sys.exit(1)

    logits = logits_check()
    print("logits:", json.dumps(logits))

    rng = np.random.default_rng(7)
    prompts = [
        [int(x) for x in rng.integers(1, 32000, n)]
        for n in (40, 130, 200, 64)
    ]
    osl = 32
    xla, tok_s_xla = run_engine("xla", prompts, osl)
    pallas, tok_s_pallas = run_engine("pallas", prompts, osl)

    greedy = []
    for rid in sorted(xla):
        a, b = xla[rid], pallas.get(rid, [])
        agree = next(
            (i for i, (x, y) in enumerate(zip(a, b)) if x != y),
            min(len(a), len(b)),
        )
        greedy.append(
            {"request": rid, "agree_prefix": agree, "len": len(a)}
        )

    out = {
        "platform": plat,
        "model": "llama3-1b (seeded random weights)",
        "logits": logits,
        "greedy_prefix_agreement": greedy,
        "steady_state_tok_s": {
            "xla": round(tok_s_xla, 1),
            "pallas": round(tok_s_pallas, 1),
        },
        "ok": logits["ok"],
    }
    path = Path(__file__).resolve().parent.parent / "artifacts/tpu"
    path.mkdir(parents=True, exist_ok=True)
    (path / "pallas_serve_check.json").write_text(json.dumps(out, indent=2))
    print(json.dumps(out))
    sys.exit(0 if out["ok"] else 2)


if __name__ == "__main__":
    main()

"""On-device pallas-vs-xla serving agreement + numerics GATE.

Five comparisons on the live TPU, llama3-1b shapes (seeded random
weights — no trained checkpoint exists in this zero-egress image);
2b. is the kv-quant leg: the SAME teacher-forced drift with int8 KV
pages (pallas+kv_quantize vs fp xla), gated on the same <0.25 /
>=90%-argmax budget:

1. model-forward logits: one 128-token prefill through forward() under
   attention_impl="xla" vs "pallas"; GATED on max |Δlogit| < 0.25 (the
   measured value is ~0.07 on a ±5 logit range — bf16 accumulation-order
   noise across 16 layers, amplified by random near-uniform weights).
2. TEACHER-FORCED per-step drift (the round-3 verdict's numerics gate):
   32 decode steps where BOTH impls consume the same token stream (the
   xla path's greedy choices), measuring per-step max |Δlogit| and
   argmax agreement. Teacher forcing isolates kernel numerics from
   compounding divergence — a free-running rollout forks forever after
   ONE bf16-noise flip, which with random near-uniform weights says
   nothing about the kernels. GATED: every step's drift < 0.25 AND
   argmax agreement >= 90%.
3. engine greedy FREE-RUNNING agreement: same requests through two
   JaxEngines. Flips are expected with random weights (see above);
   recorded as stats, not gated — the documented waiver. (With a
   trained checkpoint greedy is stable; tests/test_checkpoint_e2e.py
   covers byte-identity on CPU.)
4. steady-state timing: a second, fully-warmed run of the same workload
   per impl (first run pays Mosaic remote-compile).

Writes artifacts/tpu/pallas_serve_check.json; exit 2 = gate FAILED.
Run: python scripts/tpu_pallas_serve_check.py        (requires live TPU)
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

LOGIT_TOL = 0.25
STEPS = 32
MIN_AGREE = 0.90
#: one preset drives EVERY check + the artifact label
MODEL_PRESET = os.environ.get("PALLAS_CHECK_MODEL", "llama3_1b")


def _impl_cfgs():
    from dynamo_tpu.models import LlamaConfig

    base = getattr(LlamaConfig, MODEL_PRESET.replace("-", "_"))()
    return (
        ("xla", dataclasses.replace(base, attention_impl="xla")),
        ("pallas", dataclasses.replace(base, attention_impl="pallas")),
    )


def _prefill_setup():
    """The one definition of the shared prefill workload (seed, T, page
    table) — logits_check and teacher_forced_drift must compare the SAME
    setup or their numbers stop being comparable."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models import init_params

    cfgs = _impl_cfgs()
    params = init_params(jax.random.key(0), cfgs[0][1])
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    rng = np.random.default_rng(7)
    T = 128
    toks = jnp.asarray(rng.integers(1, 32000, (1, T)), jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)[None]
    valid = jnp.ones((1, T), bool)
    pt = jnp.asarray(np.arange(1, 9, dtype=np.int32)[None])
    return cfgs, params, T, toks, positions, valid, pt


def teacher_forced_drift():
    """Per-step decode numerics: both impls consume the SAME tokens (the
    xla path's greedy stream), so step i's drift measures the kernels at
    step i — not 16 layers of compounded earlier divergence."""
    import jax.numpy as jnp

    from dynamo_tpu.models import forward
    from dynamo_tpu.models.llama import init_kv_pages

    cfgs, params, T, toks, positions, valid, pt = _prefill_setup()

    state = {}
    for name, cfg in cfgs:
        kv = init_kv_pages(cfg, num_pages=64, page_size=64)
        logits, kv = forward(params, cfg, toks, positions, valid, kv, pt)
        state[name] = (
            np.asarray(logits[0, -1].astype(jnp.float32)), cfg, kv
        )
    drift, agree = [], 0
    cur = int(state["xla"][0].argmax())
    for i in range(STEPS):
        step = {}
        for name in ("xla", "pallas"):
            _, cfg, kv = state[name]
            logits, kv = forward(
                params, cfg,
                jnp.asarray([[cur]], jnp.int32),
                jnp.asarray([[T + i]], jnp.int32),
                jnp.ones((1, 1), bool), kv, pt,
            )
            step[name] = np.asarray(logits[0, -1].astype(jnp.float32))
            state[name] = (step[name], cfg, kv)
        drift.append(
            round(float(np.abs(step["xla"] - step["pallas"]).max()), 4)
        )
        agree += int(step["xla"].argmax() == step["pallas"].argmax())
        cur = int(step["xla"].argmax())
    agreement = agree / STEPS
    return {
        "steps": STEPS,
        "per_step_max_abs_logit_diff": drift,
        "max_drift": max(drift),
        "teacher_forced_argmax_agreement": agreement,
        "budget": {"max_drift": LOGIT_TOL, "min_agreement": MIN_AGREE},
        "ok": max(drift) < LOGIT_TOL and agreement >= MIN_AGREE,
    }


def kv_quant_drift():
    """The kv-quant numerics leg (ISSUE 2 CI gate): teacher-forced decode
    where the int8-KV pallas path consumes the fp xla path's greedy
    stream. Budget: per-step max |Δlogit| < 0.25 (the fp pallas leg's
    budget — int8 row-scale quantization noise lands well inside it at
    these logit ranges) and ≥90% per-step argmax agreement."""
    import dataclasses

    import jax.numpy as jnp

    from dynamo_tpu.models import forward
    from dynamo_tpu.models.llama import init_kv_pages

    cfgs, params, T, toks, positions, valid, pt = _prefill_setup()
    arms = {
        "xla": (dict(cfgs)["xla"], None),
        "pallas_int8": (
            dataclasses.replace(dict(cfgs)["pallas"]),
            "int8",
        ),
    }
    state = {}
    for name, (cfg, kvq) in arms.items():
        kv = init_kv_pages(cfg, num_pages=64, page_size=64, kv_quantize=kvq)
        logits, kv = forward(params, cfg, toks, positions, valid, kv, pt)
        state[name] = (
            np.asarray(logits[0, -1].astype(jnp.float32)), cfg, kv
        )
    drift, agree = [], 0
    cur = int(state["xla"][0].argmax())
    for i in range(STEPS):
        step = {}
        for name in arms:
            _, cfg, kv = state[name]
            logits, kv = forward(
                params, cfg,
                jnp.asarray([[cur]], jnp.int32),
                jnp.asarray([[T + i]], jnp.int32),
                jnp.ones((1, 1), bool), kv, pt,
            )
            step[name] = np.asarray(logits[0, -1].astype(jnp.float32))
            state[name] = (step[name], cfg, kv)
        drift.append(
            round(float(np.abs(step["xla"] - step["pallas_int8"]).max()), 4)
        )
        agree += int(step["xla"].argmax() == step["pallas_int8"].argmax())
        cur = int(step["xla"].argmax())
    agreement = agree / STEPS
    return {
        "steps": STEPS,
        "per_step_max_abs_logit_diff": drift,
        "max_drift": max(drift),
        "teacher_forced_argmax_agreement": agreement,
        "budget": {"max_drift": LOGIT_TOL, "min_agreement": MIN_AGREE},
        "ok": max(drift) < LOGIT_TOL and agreement >= MIN_AGREE,
    }


def logits_check():
    import jax.numpy as jnp

    from dynamo_tpu.models import forward
    from dynamo_tpu.models.llama import init_kv_pages

    cfgs, params, _T, toks, positions, valid, pt = _prefill_setup()
    outs = {}
    for name, cfg in cfgs:
        kv = init_kv_pages(cfg, num_pages=64, page_size=64)
        logits, _ = forward(params, cfg, toks, positions, valid, kv, pt)
        outs[name] = np.asarray(logits[0, -1].astype(jnp.float32))
    diff = float(np.abs(outs["xla"] - outs["pallas"]).max())
    return {
        "max_abs_logit_diff": diff,
        "logit_range": [
            float(outs["xla"].min()), float(outs["xla"].max())
        ],
        "argmax_agree": bool(
            outs["xla"].argmax() == outs["pallas"].argmax()
        ),
        "ok": diff < LOGIT_TOL,
    }


def run_engine(impl: str, prompts, osl: int):
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    cfg = EngineConfig(
        model=MODEL_PRESET.replace("_", "-"),
        num_pages=256,
        page_size=64,
        max_pages_per_seq=8,
        decode_buckets=(4, 8),
        prefill_chunk=128,
        prefill_token_budget=1024,
        decode_steps=8,
        max_seqs=8,
        dtype="bfloat16",
        enable_prefix_caching=False,
        attention_impl=impl,
    )
    eng = JaxEngine(cfg)

    def one_run(tag):
        t0 = time.time()
        for i, p in enumerate(prompts):
            eng.add_request(
                f"{tag}{i}", p, SamplingParams(temperature=0.0, max_tokens=osl)
            )
        outs: dict[str, list[int]] = {}
        n = 0
        while eng.has_work:
            for out in eng.step():
                outs.setdefault(out.request_id, []).extend(out.new_token_ids)
                n += len(out.new_token_ids)
        return outs, n / (time.time() - t0)

    outs, _ = one_run("w")  # warm: compiles every program
    eng.allocator.clear_cache()
    outs, tok_s = one_run("r")
    return outs, tok_s


def main():
    import jax

    plat = jax.devices()[0].platform
    print(f"platform: {plat}")
    if plat == "cpu":
        print("refusing: this check must run on TPU")
        sys.exit(1)

    logits = logits_check()
    print("logits:", json.dumps(logits))
    drift = teacher_forced_drift()
    print("teacher-forced drift:", json.dumps(drift))
    kvq = kv_quant_drift()
    print("kv-quant drift (int8 pages):", json.dumps(kvq))

    rng = np.random.default_rng(7)
    prompts = [
        [int(x) for x in rng.integers(1, 32000, n)]
        for n in (40, 130, 200, 64)
    ]
    osl = 32
    xla, tok_s_xla = run_engine("xla", prompts, osl)
    pallas, tok_s_pallas = run_engine("pallas", prompts, osl)

    greedy = []
    for rid in sorted(xla):
        a, b = xla[rid], pallas.get(rid, [])
        agree = next(
            (i for i, (x, y) in enumerate(zip(a, b)) if x != y),
            min(len(a), len(b)),
        )
        greedy.append(
            {"request": rid, "agree_prefix": agree, "len": len(a)}
        )

    out = {
        "platform": plat,
        "model": f"{MODEL_PRESET} (seeded random weights)",
        "logits": logits,
        "teacher_forced_drift": drift,
        "kv_quant_drift": kvq,
        # free-running agreement: stats only (documented waiver — random
        # near-uniform weights fork on bf16 noise; see module docstring)
        "greedy_prefix_agreement": greedy,
        "steady_state_tok_s": {
            "xla": round(tok_s_xla, 1),
            "pallas": round(tok_s_pallas, 1),
        },
        "ok": logits["ok"] and drift["ok"] and kvq["ok"],
    }
    path = Path(__file__).resolve().parent.parent / "artifacts/tpu"
    path.mkdir(parents=True, exist_ok=True)
    (path / "pallas_serve_check.json").write_text(json.dumps(out, indent=2))
    print(json.dumps(out))
    sys.exit(0 if out["ok"] else 2)


if __name__ == "__main__":
    main()

"""Benchmark: serving throughput of the JaxEngine on one TPU chip.

Workload (genai-perf-inspired, scaled to one chip — BASELINE.md): N
concurrent requests, random prompts, fixed output length, continuous
batching with paged KV + prefix caching off (worst case). Reports output
tokens/sec/chip, p50 TTFT, p50 ITL, and approximate MFU.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...,
   "extras": {...}}

Robustness contract (the axon TPU tunnel is known to wedge): the backend is
probed in a SUBPROCESS with a timeout before any in-process jax import
commits to a platform. On probe failure the bench retries, then falls back
to CPU with extras.platform="cpu" (vs_baseline compared against the CPU
record, not the TPU one). Any unexpected crash still emits one structured
JSON line instead of a bare traceback.

vs_baseline compares against `published.output_tok_s_per_chip` (TPU) or
`published.cpu_output_tok_s` (CPU fallback) in BASELINE.json; 1.0 until a
prior round has published.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

PROBE_SRC = "import jax; d=jax.devices(); print(d[0].platform)"


def probe_backend(retries: int = 5, timeout_s: int = 120) -> str:
    """Return the usable platform ('tpu' or 'cpu') via subprocess probes.

    A wedged tunnel hangs rather than erroring, so the probe must be a
    killable child process — never the bench process itself. Patience
    matters: this bench is the round's headline TPU artifact, and a CPU
    fallback caused by a TRANSIENT wedge wastes the whole round's
    hardware evidence (round 2 post-mortem) — so by default we probe for
    ~12 min (5 x 120s probe + 30s gaps) before giving up."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if want == "cpu":
        return "cpu"
    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", PROBE_SRC],
                capture_output=True, text=True, timeout=timeout_s,
                env=dict(os.environ),
            )
            if out.returncode == 0:
                plat = out.stdout.strip().splitlines()[-1].strip().lower()
                return "tpu" if plat not in ("cpu",) else "cpu"
        except subprocess.TimeoutExpired:
            pass
        if attempt < retries - 1:
            time.sleep(30)
    return "cpu"


def emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)
    _ledger_append(obj)


def _ledger_append(payload: dict) -> None:
    """Append this emission to artifacts/perf_ledger.jsonl (the
    perf-regression ledger — scripts/perf_diff.py diffs rounds from
    it). Best-effort: a ledger problem must never fail the bench run
    itself. DYNTPU_ROUND names the row's round (driver rounds export
    it); DYNTPU_PERF_LEDGER overrides the path, empty string disables."""
    path = os.environ.get("DYNTPU_PERF_LEDGER")
    if path == "":
        return
    try:
        from dynamo_tpu.telemetry import perf_ledger

        row = perf_ledger.row_from_bench(
            payload, os.environ.get("DYNTPU_ROUND", "adhoc")
        )
        perf_ledger.append_row(row, path or perf_ledger.DEFAULT_LEDGER)
    except Exception as e:
        print(f"bench: perf_ledger append failed: {e}", file=sys.stderr)


def _make_echo_driver(num_requests: int, tokens: int):
    """`drive(engine, tag) -> (tokens, seconds)`: the shared concurrent
    echo workload of the harness/tracing A/Bs."""
    import asyncio

    from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
    from dynamo_tpu.runtime.context import Context

    prompt = list(range(1, tokens + 1))

    async def drive(engine, tag):
        async def one(i):
            req = PreprocessedRequest(
                request_id=f"{tag}{i}", token_ids=prompt, max_tokens=tokens
            )
            n = 0
            ctx = Context(request_id=req.request_id)
            async for item in engine.generate(ctx, req):
                n += len(item["token_ids"])
            return n

        t0 = time.time()
        counts = await asyncio.gather(*[one(i) for i in range(num_requests)])
        return sum(counts), time.time() - t0

    return drive


def _ext_harness_ab(num_requests: int = 8, tokens: int = 64) -> dict:
    """Per-token overhead of the subprocess external-engine harness: the
    SAME echo workload through an in-process EchoEngine vs the torch-free
    reference worker behind the wire protocol (spawn + frames + msgpack +
    checksums). The delta prices the isolation boundary a foreign engine
    pays per token (docs/external_engines.md 'Level 2')."""
    import asyncio

    from dynamo_tpu.engine.async_engine import EchoEngine
    from dynamo_tpu.external.client import SubprocessEngine

    drive = _make_echo_driver(num_requests, tokens)

    async def run():
        n_in, t_in = await drive(EchoEngine(), "warm-in")
        n_in, t_in = await drive(EchoEngine(), "in")
        ext = SubprocessEngine(
            [sys.executable, "-m", "dynamo_tpu.external.reference_worker",
             "--model", "bench-ext", "--metrics-interval", "60"],
            name="bench-ext",
        )
        await ext.start()
        try:
            await drive(ext, "warm-ext")
            n_ext, t_ext = await drive(ext, "ext")
        finally:
            await ext.stop()
        return {
            "requests": num_requests,
            "tokens_per_arm": n_in,
            "inproc_tok_s": round(n_in / t_in, 1) if t_in else None,
            "subprocess_tok_s": round(n_ext / t_ext, 1) if t_ext else None,
            "wire_overhead_us_per_token": round(
                (t_ext / n_ext - t_in / n_in) * 1e6, 2
            ),
        }

    return asyncio.run(run())


def _spec_ab(
    model: str = "tiny", draft: str = None, pairs: int = 3,
    num_requests: int = 8, osl: int = 48, spec_tokens: int = 4,
) -> dict:
    """Draft-model speculative decoding A/B (ISSUE 9): the decode-bound
    workload (tiny prompts, batch <= 8, long outputs) with the fused
    draft+verify path on vs off. BOTH arms run in ONE warm engine — the
    draft stays loaded, `eng._spec_draft` toggles the routing — and the
    arms interleave per pair so box-load drift cancels.

    The ASSERTED number is the deterministic dispatch-level model, not
    the wall ratio: modeled_decode_tok_s_ratio =
    (tokens/dispatch spec-on / tokens/dispatch spec-off) x
    (ms/dispatch spec-off / ms/dispatch spec-on), medians over pairs.
    tokens/dispatch on the spec arm is B x (1 + accept_rate x S) — the
    microbench priced at the MEASURED acceptance rate — and ms/dispatch
    is each arm's engine-measured decode phase time over many
    dispatches. A `modeled_at` curve extrapolates the ratio to other
    acceptance rates (what a distilled draft would buy), since the
    default draft here is SELF-draft (draft == target params, greedy
    acceptance ~1): the upper-bound harness that exercises the whole
    fused pipeline without needing a distilled checkpoint."""
    import gc

    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    draft = draft or model
    base = EngineConfig.for_tests() if model == "tiny" else None
    over = {
        "model": model,
        "spec_draft_model": draft,
        "spec_draft_tokens": spec_tokens,
        "num_pages": max(256, num_requests * 8),
        "page_size": 16,
        "max_pages_per_seq": 16,
        "prefill_chunk": 64,
        "decode_buckets": (1, 2, 4, 8),
        "max_seqs": max(8, num_requests),
        "decode_steps": 1,  # spec competes with classic stepping; the
        # fused-K path is a different lever (it can't beat the roofline
        # per REQUEST, only amortize syncs)
        "enable_prefix_caching": False,
    }
    if base is not None:
        cfg = EngineConfig(**{**base.__dict__, **over})
    else:
        cfg = EngineConfig(**over)
    eng = JaxEngine(cfg)
    rng = np.random.default_rng(0)

    def drive(tag: str) -> dict:
        m = eng.metrics
        keys = (
            "time_decode_ms", "decode_dispatches", "generated_tokens",
            "spec_drafted", "spec_accepted",
        )
        before = {k: getattr(m, k) for k in keys}
        t0 = time.perf_counter()
        for i in range(num_requests):
            eng.add_request(
                f"{tag}{i}",
                [int(x) for x in rng.integers(1, 200, 12)],
                SamplingParams(temperature=0.0, max_tokens=osl),
            )
        gen = 0
        while eng.has_work:
            for out in eng.step():
                gen += len(out.new_token_ids)
        elapsed = time.perf_counter() - t0
        eng.drain_overlap()
        d = {k: getattr(m, k) - v for k, v in before.items()}
        disp = max(1, d["decode_dispatches"])
        return {
            "tok_s": round(gen / elapsed, 1),
            "ms_per_dispatch": round(d["time_decode_ms"] / disp, 4),
            "tok_per_dispatch": round(d["generated_tokens"] / disp, 3),
            "accept_rate": round(
                d["spec_accepted"] / max(1, d["spec_drafted"]), 4
            ),
            "decode_dispatches": d["decode_dispatches"],
        }

    # warm both arms (compiles + caches)
    eng._spec_draft = True
    drive("warm_on")
    eng._spec_draft = False
    drive("warm_off")
    on_runs, off_runs = [], []
    for p in range(pairs):
        eng._spec_draft = True
        on_runs.append(drive(f"on{p}"))
        eng._spec_draft = False
        off_runs.append(drive(f"off{p}"))
    del eng
    gc.collect()

    import statistics

    def med(runs, k):
        return statistics.median(r[k] for r in runs)

    rate = med(on_runs, "accept_rate")
    ms_on, ms_off = med(on_runs, "ms_per_dispatch"), med(
        off_runs, "ms_per_dispatch"
    )
    tpd_on, tpd_off = med(on_runs, "tok_per_dispatch"), med(
        off_runs, "tok_per_dispatch"
    )
    modeled = (
        (tpd_on / tpd_off) * (ms_off / ms_on)
        if tpd_off and ms_on
        else None
    )
    # extrapolation: at acceptance r the spec arm lands B*(1 + r*S)
    # tokens per dispatch at the measured spec-dispatch cost
    modeled_at = {}
    if modeled is not None and rate > 0:
        per_accept = tpd_on / (1.0 + rate * spec_tokens)
        for r in (0.5, 0.7, 0.9):
            modeled_at[str(r)] = round(
                (per_accept * (1.0 + r * spec_tokens) / tpd_off)
                * (ms_off / ms_on),
                3,
            )
    return {
        "model": model,
        "draft": draft,
        "spec_tokens": spec_tokens,
        "batch": num_requests,
        "pairs": pairs,
        "spec_on": {
            "tok_s": med(on_runs, "tok_s"),
            "ms_per_dispatch": ms_on,
            "tok_per_dispatch": tpd_on,
            "accept_rate": rate,
        },
        "spec_off": {
            "tok_s": med(off_runs, "tok_s"),
            "ms_per_dispatch": ms_off,
            "tok_per_dispatch": tpd_off,
        },
        "wall_tok_s_ratio": round(
            med(on_runs, "tok_s") / max(1e-9, med(off_runs, "tok_s")), 3
        ),
        "modeled_decode_tok_s_ratio": (
            round(modeled, 3) if modeled is not None else None
        ),
        "modeled_at_accept_rate": modeled_at,
    }


def _kstep_ab(
    model: str = "tiny", pairs: int = 3, num_requests: int = 8,
    osl: int = 64, kstep: int = 8,
) -> dict:
    """On-device K-step decode window A/B (ISSUE 16): the decode-bound
    workload (tiny prompts, long outputs) with the fused decode_kstep
    window on (K=kstep) vs classic per-token stepping (K=1). BOTH arms
    run in ONE warm engine — `eng._decode_kstep` toggles the live window
    target, the engine stays built with decode_kstep=kstep so the policy
    gate is open — and the arms interleave per pair so box-load drift
    cancels. overlap_decode is off in both arms (the CPU backend
    serializes the speculative dispatch, which would bill the K=1 arm
    for pipelining the chip gets free) and decode_steps is pinned to 1
    so the K=1 arm is the true host-per-token loop docs/PERF.md prices.

    The ASSERTED number is the deterministic dispatch-level model:
    modeled_ms_per_token_ratio =
    (ms/dispatch K=1 / tokens/dispatch K=1) /
    (ms/dispatch K / tokens/dispatch K), medians over pairs — the K arm
    lands ~K tokens per host visit at well under K x the dispatch cost,
    so the ratio is the host-loop tax the window removes. Wall tok/s
    rides along unasserted."""
    import gc

    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    base = EngineConfig.for_tests() if model == "tiny" else None
    over = {
        "model": model,
        "decode_kstep": kstep,
        "num_pages": max(256, num_requests * 8),
        "page_size": 16,
        "max_pages_per_seq": 16,
        "prefill_chunk": 64,
        "decode_buckets": (1, 2, 4, 8),
        "max_seqs": max(8, num_requests),
        "decode_steps": 1,
        "overlap_decode": False,
        "enable_prefix_caching": False,
    }
    if base is not None:
        cfg = EngineConfig(**{**base.__dict__, **over})
    else:
        cfg = EngineConfig(**over)
    eng = JaxEngine(cfg)
    rng = np.random.default_rng(0)

    def drive(tag: str) -> dict:
        m = eng.metrics
        keys = (
            "time_decode_ms", "decode_dispatches", "generated_tokens",
            "kstep_windows", "kstep_steps",
        )
        before = {k: getattr(m, k) for k in keys}
        t0 = time.perf_counter()
        for i in range(num_requests):
            eng.add_request(
                f"{tag}{i}",
                [int(x) for x in rng.integers(1, 200, 12)],
                SamplingParams(temperature=0.0, max_tokens=osl),
            )
        gen = 0
        while eng.has_work:
            for out in eng.step():
                gen += len(out.new_token_ids)
        elapsed = time.perf_counter() - t0
        eng.drain_overlap()
        d = {k: getattr(m, k) - v for k, v in before.items()}
        disp = max(1, d["decode_dispatches"])
        return {
            "tok_s": round(gen / elapsed, 1),
            "ms_per_dispatch": round(d["time_decode_ms"] / disp, 4),
            "tok_per_dispatch": round(d["generated_tokens"] / disp, 3),
            "decode_dispatches": d["decode_dispatches"],
            "kstep_windows": d["kstep_windows"],
            "kstep_steps": d["kstep_steps"],
        }

    # warm both arms (compiles + caches)
    eng._decode_kstep = kstep
    drive("warm_on")
    eng._decode_kstep = 1
    drive("warm_off")
    on_runs, off_runs = [], []
    for p in range(pairs):
        eng._decode_kstep = kstep
        on_runs.append(drive(f"on{p}"))
        eng._decode_kstep = 1
        off_runs.append(drive(f"off{p}"))
    del eng
    gc.collect()

    import statistics

    def med(runs, k):
        return statistics.median(r[k] for r in runs)

    ms_on, ms_off = med(on_runs, "ms_per_dispatch"), med(
        off_runs, "ms_per_dispatch"
    )
    tpd_on, tpd_off = med(on_runs, "tok_per_dispatch"), med(
        off_runs, "tok_per_dispatch"
    )
    modeled = (
        (ms_off / tpd_off) / (ms_on / tpd_on)
        if tpd_off and tpd_on and ms_on
        else None
    )
    return {
        "model": model,
        "kstep": kstep,
        "batch": num_requests,
        "pairs": pairs,
        "kstep_on": {
            "tok_s": med(on_runs, "tok_s"),
            "ms_per_dispatch": ms_on,
            "tok_per_dispatch": tpd_on,
            "windows": med(on_runs, "kstep_windows"),
            "steps": med(on_runs, "kstep_steps"),
        },
        "kstep_off": {
            "tok_s": med(off_runs, "tok_s"),
            "ms_per_dispatch": ms_off,
            "tok_per_dispatch": tpd_off,
        },
        "wall_tok_s_ratio": round(
            med(on_runs, "tok_s") / max(1e-9, med(off_runs, "tok_s")), 3
        ),
        "modeled_ms_per_token_ratio": (
            round(modeled, 3) if modeled is not None else None
        ),
    }


def _multihost_pipeline_ab(
    model: str = "tiny", pairs: int = 3, num_requests: int = 8,
    osl: int = 64, kstep: int = 8, topology: str = "tp=2,dp=2",
) -> dict:
    """The fast decode pipeline carried across hosts (ISSUE 20): under a
    FORCED multi-host mesh (EngineConfig.force_multihost over the CPU
    device grid — the engine takes the multi-controller code paths
    without a fabric), the K-step pipeline ON vs the old multi-host
    behavior (the pre-lift auto-off: synchronous per-token stepping).
    ONE warm engine; the arms toggle `eng._decode_kstep` live and
    interleave per pair so box-load drift cancels. Like _kstep_ab, the
    TIMED arms keep overlap off (the CPU backend serializes the
    speculative dispatch, billing the ON arm for pipelining the chip
    gets free); a separate UN-timed probe drive then runs with overlap
    re-enabled and reports its engagement (`overlap_probe`) — proof the
    multi-host overlap path works, without letting its CPU artifact
    pollute the model.

    The ASSERTED number is the deterministic dispatch-level model (same
    construction as _kstep_ab): modeled_ms_per_token_ratio =
    (ms/dispatch / tok/dispatch, pipeline off) / (same, pipeline on) —
    the per-window host sync the lift removes from every replica's
    lockstep loop. Wall tok/s rides along unasserted."""
    import gc

    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    base = EngineConfig.for_tests() if model == "tiny" else None
    over = {
        "model": model,
        "topology": topology,
        "force_multihost": True,
        "decode_kstep": kstep,
        "num_pages": max(256, num_requests * 8),
        "page_size": 16,
        "max_pages_per_seq": 16,
        "prefill_chunk": 64,
        "decode_buckets": (1, 2, 4, 8),
        "max_seqs": max(8, num_requests),
        "decode_steps": 1,
        "overlap_decode": False,
        "enable_prefix_caching": False,
    }
    if base is not None:
        cfg = EngineConfig(**{**base.__dict__, **over})
    else:
        cfg = EngineConfig(**over)
    eng = JaxEngine(cfg)
    assert eng._multiproc, "force_multihost must engage the SPMD paths"
    rng = np.random.default_rng(0)

    def drive(tag: str) -> dict:
        m = eng.metrics
        keys = (
            "time_decode_ms", "decode_dispatches", "generated_tokens",
            "kstep_windows", "overlap_hits",
        )
        before = {k: getattr(m, k) for k in keys}
        t0 = time.perf_counter()
        for i in range(num_requests):
            eng.add_request(
                f"{tag}{i}",
                [int(x) for x in rng.integers(1, 200, 12)],
                SamplingParams(temperature=0.0, max_tokens=osl),
            )
        gen = 0
        while eng.has_work:
            for out in eng.step():
                gen += len(out.new_token_ids)
        elapsed = time.perf_counter() - t0
        eng.drain_overlap()
        d = {k: getattr(m, k) - v for k, v in before.items()}
        disp = max(1, d["decode_dispatches"])
        return {
            "tok_s": round(gen / elapsed, 1),
            "ms_per_dispatch": round(d["time_decode_ms"] / disp, 4),
            "tok_per_dispatch": round(d["generated_tokens"] / disp, 3),
            "decode_dispatches": d["decode_dispatches"],
            "kstep_windows": d["kstep_windows"],
            "overlap_hits": d["overlap_hits"],
        }

    eng._decode_kstep = kstep
    drive("warm_on")
    eng._decode_kstep = 1
    drive("warm_off")
    on_runs, off_runs = [], []
    for p in range(pairs):
        eng._decode_kstep = kstep
        on_runs.append(drive(f"on{p}"))
        eng._decode_kstep = 1
        off_runs.append(drive(f"off{p}"))
    # un-timed probe: the overlap path itself, live on the forced
    # multi-host mesh (its timing is a CPU serialization artifact)
    eng._decode_kstep = kstep
    eng._overlap_enabled = True
    probe = drive("probe")
    del eng
    gc.collect()

    import statistics

    def med(runs, k):
        return statistics.median(r[k] for r in runs)

    ms_on, ms_off = med(on_runs, "ms_per_dispatch"), med(
        off_runs, "ms_per_dispatch"
    )
    tpd_on, tpd_off = med(on_runs, "tok_per_dispatch"), med(
        off_runs, "tok_per_dispatch"
    )
    modeled = (
        (ms_off / tpd_off) / (ms_on / tpd_on)
        if tpd_off and tpd_on and ms_on
        else None
    )
    return {
        "model": model,
        "topology": topology,
        "kstep": kstep,
        "batch": num_requests,
        "pairs": pairs,
        "pipeline_on": {
            "tok_s": med(on_runs, "tok_s"),
            "ms_per_dispatch": ms_on,
            "tok_per_dispatch": tpd_on,
            "kstep_windows": med(on_runs, "kstep_windows"),
        },
        "pipeline_off": {
            "tok_s": med(off_runs, "tok_s"),
            "ms_per_dispatch": ms_off,
            "tok_per_dispatch": tpd_off,
        },
        "overlap_probe": {
            "overlap_hits": probe["overlap_hits"],
            "kstep_windows": probe["kstep_windows"],
        },
        "wall_tok_s_ratio": round(
            med(on_runs, "tok_s") / max(1e-9, med(off_runs, "tok_s")), 3
        ),
        "modeled_ms_per_token_ratio": (
            round(modeled, 3) if modeled is not None else None
        ),
    }


def _mixed_ab(model: str = "tiny", pairs: int = 1) -> dict:
    """Stall-free mixed prefill+decode steps A/B (ISSUE 5): the c=32
    saturation workload — a few long-running decodes with a steady
    arrival stream of chunked prompts against a FIXED prefill budget —
    with `mixed_steps` on vs off. The XOR scheduler stalls every running
    decode for each arrival's whole prefill drain, so pooled ITL p95
    sits at several step times; mixed steps carry the decode batch
    inside every prefill dispatch, collapsing ITL p95 toward one step
    while TTFT p50 (arrival -> first token, still one prefill chunk per
    step either way) stays within a few percent.

    Noise control on a shared box: BOTH arms run in ONE engine (the
    scheduler's `mixed_enabled` flag toggles per step or per drive), so
    they share a warm jit cache. Workload-level wall numbers here carry
    per-run correlated bias of ±10% (a load burst hits the two program
    working sets asymmetrically), so — exactly like the trace_overhead
    A/B — the ASSERTED ratios are deterministic: the TTFT ratio comes
    from a back-to-back per-chunk-stratum program microbench, and the
    ITL ratio prices each arm's deterministic step schedule with
    stratified step-cost medians from randomized-interleaved drives
    (policy coin-tossed per step). Raw wall ratios ride along
    unasserted. Prompts are long and chunks big (1536/512) so one
    chunk's quadratic attention dominates the decode rider, as it does
    on chip with 512–2048-token chunks; overlap_decode is off in both
    arms (the CPU backend serializes the speculative dispatch, which
    would bill the mixed arm for pipelining the chip gets free)."""
    import statistics

    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    early, osl_early = 2, 112
    #: arrivals take their first token and finish (osl 1): the decode
    #: batch stays the 2 long-running rows, so the rider the TTFT ratio
    #: prices is the steady decode batch, not a backlog-inflated one
    isl_late, osl_late = 1536, 1
    #: saturation: 3 drain steps per prompt, arrivals every 3/4 steps
    #: (avg 3.5) — just under XOR capacity, so the backlog stays alive
    #: and strict prefill priority starves decodes for whole cycles
    late_gaps = (4, 3)
    num_late = 30  # c=32: 2 long decodes + 30 arrivals
    rng = np.random.default_rng(11)
    late_prompts = [
        [int(x) for x in rng.integers(1, 200, isl_late)]
        for _ in range(num_late)
    ]

    eng = JaxEngine(
        EngineConfig(
            model=model,
            num_pages=448,
            page_size=16,
            max_pages_per_seq=97,
            decode_buckets=(1, 2, 4, 8, 16, 32, 64),
            prefill_chunk=512,
            prefill_token_budget=512,  # fixed budget: 3 steps/prompt
            max_seqs=64,
            decode_steps=1,
            dtype="float32",
            enable_prefix_caching=False,
            mixed_steps=True,
            overlap_decode=False,
        )
    )

    def drive(tag: str, coin=None, n_late: int = num_late) -> dict:
        """Step-driven arrivals: every `late_every` engine steps another
        chunked prompt lands while the early requests decode through —
        identical arrival pattern (in steps) for both arms. Per-step
        durations are collected BY BATCH KIND; with `coin` set, the
        scheduling policy flips randomly every step, so mixed and
        prefill step costs sample the identical machine load."""
        m = eng.metrics
        submit_t, submit_step, first_t, first_step = {}, {}, {}, {}
        emits: dict = {}
        emit_steps: dict = {}
        step_ms: dict = {"mixed": [], "prefill": [], "decode": []}
        #: per-step (kind, chunk-index) labels. Step costs are
        #: MULTI-MODAL by chunk index (the first chunk skips the history
        #: gather; later chunks attend over more history), so medians
        #: must stratify by chunk or they hop between modes.
        labels: list = []
        samples: dict = {}
        prefill_reqs: dict = {}
        prev_computed: dict = {}
        step_i, sent = 0, 0
        chunk_sz = eng.config.prefill_chunk

        def add(rid, prompt, osl):
            submit_t[rid] = time.perf_counter()
            submit_step[rid] = step_i
            req = eng.add_request(
                rid, prompt,
                SamplingParams(max_tokens=osl, ignore_eos=True),
            )
            if len(prompt) > chunk_sz:
                prefill_reqs[rid] = req
                prev_computed[rid] = 0

        for i in range(early):
            add(f"{tag}e{i}", [i + 1, i + 2, i + 3], osl_early)
        next_at = late_gaps[0]
        while eng.has_work or sent < n_late:
            if sent < n_late and step_i >= next_at:
                add(f"{tag}l{sent}", late_prompts[sent], osl_late)
                sent += 1
                next_at += late_gaps[sent % len(late_gaps)]
            if coin is not None:
                eng.scheduler.mixed_enabled = bool(coin.integers(0, 2))
            kinds0 = (
                m.mixed_dispatches, m.prefill_dispatches,
                m.decode_dispatches,
            )
            t0 = time.perf_counter()
            outs = eng.step()
            dt = time.perf_counter() - t0
            if m.mixed_dispatches > kinds0[0]:
                kind = "mixed"
            elif m.prefill_dispatches > kinds0[1]:
                kind = "prefill"
            elif m.decode_dispatches > kinds0[2]:
                kind = "decode"
            else:
                kind = None
            chunk_idx = None
            for rid, req in list(prefill_reqs.items()):
                done = min(req.num_computed_tokens, len(req.prompt_tokens))
                if done > prev_computed[rid]:
                    chunk_idx = prev_computed[rid] // chunk_sz
                    prev_computed[rid] = done
                if req.is_finished or done >= len(req.prompt_tokens):
                    prefill_reqs.pop(rid)
                    prev_computed.pop(rid, None)
            labels.append((kind, chunk_idx))
            if kind is not None:
                step_ms[kind].append(dt * 1000.0)
                samples.setdefault((kind, chunk_idx), []).append(
                    (step_i, dt * 1000.0)
                )
            for out in outs:
                now = time.perf_counter()
                if out.is_first and out.request_id not in first_t:
                    first_t[out.request_id] = now
                    first_step[out.request_id] = step_i
                if out.new_token_ids:
                    emits.setdefault(out.request_id, []).append(now)
                    emit_steps.setdefault(out.request_id, []).append(step_i)
            step_i += 1
        itls = []
        for times in emits.values():
            itls.extend(b - a for a, b in zip(times, times[1:]))
        itls.sort()
        ttfts = sorted(first_t[r] - submit_t[r] for r in first_t)
        ttft_steps = sorted(
            first_step[r] - submit_step[r] + 1 for r in first_t
        )
        return {
            "itl_p95_wall_ms": itls[int(len(itls) * 0.95)] * 1000.0,
            "ttft_p50_wall_ms": ttfts[len(ttfts) // 2] * 1000.0,
            "ttft_p50_steps": ttft_steps[len(ttft_steps) // 2],
            "step_ms": step_ms,
            "samples": samples,
            "labels": labels,
            "emit_steps": emit_steps,
            "mixed_dispatches": m.mixed_dispatches,
        }

    def arm(on: bool, tag: str) -> dict:
        eng.scheduler.mixed_enabled = on
        return drive(tag)

    # warmup with random interleaving: compiles every program variant of
    # BOTH policies in one (shortened) pass
    drive("warm", coin=np.random.default_rng(7), n_late=8)
    # randomized interleaved phase: the per-step-kind cost medians that
    # feed the TTFT comparison — mixed and prefill steps alternate by
    # coin toss, so any load burst hits both kinds alike
    rnds = [drive("rnd", coin=np.random.default_rng(97))]

    def microbench(reps: int = 16) -> tuple[dict, dict]:
        """Deterministic per-chunk-stratum cost ratio of the MIXED
        program vs the pure prefill program it replaces: identical
        synthetic inputs, the two programs alternating back-to-back in
        a tight loop, per-iteration pair ratios, median over reps.
        Workload-level wall numbers on this shared box carry per-run
        correlated bias of ±10% (a load burst hits the two program
        working sets asymmetrically) — this is the same reasoning as
        the trace_overhead A/B's deterministic span microbench."""
        import jax

        mp = eng.config.max_pages_per_seq
        chunk = eng.config.prefill_chunk
        n_chunks = isl_late // chunk
        b_dec = eng.config.decode_bucket_for(early)
        p_pages = eng.allocator.allocate(isl_late // 16 + 1)
        d_pages = [eng.allocator.allocate(10) for _ in range(b_dec)]
        rngl = np.random.default_rng(5)
        ratios, prefill_ms = {}, {}
        try:
            for c in range(n_chunks):
                first_chunk, psamp = c == 0, c == n_chunks - 1
                rows = b_dec + (1 if psamp else 0)
                host = {
                    "p": (
                        rngl.integers(1, 200, (1, chunk)).astype(np.int32),
                        (np.arange(chunk, dtype=np.int32) + c * chunk)[
                            None
                        ],
                        np.ones((1, chunk), bool),
                        np.zeros((1, mp), np.int32),
                    ),
                    "d": (
                        np.full((b_dec, 1), 7, np.int32),
                        np.full((b_dec, 1), 80, np.int32),
                        np.ones((b_dec, 1), bool),
                        np.zeros((b_dec, mp), np.int32),
                    ),
                    "last": np.full(1, chunk - 1, np.int32),
                    "samp": (
                        np.zeros(rows, np.float32),
                        np.ones(rows, np.float32),
                        np.zeros(rows, np.int32),
                        np.zeros(rows, np.uint32),
                        np.zeros(rows, np.int32),
                    ),
                    "samp1": (
                        np.zeros(1, np.float32), np.ones(1, np.float32),
                        np.zeros(1, np.int32), np.zeros(1, np.uint32),
                        np.zeros(1, np.int32),
                    ),
                    "last1": np.full(1, chunk - 1, np.int32),
                }
                host["p"][3][0, : len(p_pages)] = p_pages
                for i, pg in enumerate(d_pages):
                    host["d"][3][i, : len(pg)] = pg
                dev = jax.device_put(host)
                mixed_fn = eng._get_step_fn(
                    "mixed", b_dec, chunk, greedy=True,
                    first_chunk=first_chunk, b_pre=1, psamp=psamp,
                )
                if psamp:
                    pre_fn = eng._get_step_fn(
                        "prefill", 1, chunk, greedy=True,
                        first_chunk=first_chunk,
                    )
                else:
                    pre_fn = eng._get_step_fn(
                        "prefill_nosample", 1, chunk,
                        first_chunk=first_chunk,
                    )

                def run_mixed():
                    out = mixed_fn(
                        eng.params, *dev["d"][:3], eng.kv, dev["d"][3],
                        *dev["p"], dev["last"], *dev["samp"],
                    )
                    eng.kv = out[-1]
                    jax.block_until_ready(out[0])

                def run_pre():
                    if psamp:
                        out = pre_fn(
                            eng.params, *dev["p"][:3], eng.kv,
                            dev["p"][3], dev["last1"], *dev["samp1"],
                        )
                        eng.kv = out[-1]
                        jax.block_until_ready(out[0])
                    else:
                        eng.kv = pre_fn(
                            eng.params, *dev["p"][:3], eng.kv,
                            dev["p"][3],
                        )
                        jax.block_until_ready(eng.kv.k)

                run_mixed()
                run_pre()  # warm both
                ms_ms, ps_ms = [], []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    run_mixed()
                    t1 = time.perf_counter()
                    run_pre()
                    t2 = time.perf_counter()
                    ms_ms.append((t1 - t0) * 1000.0)
                    ps_ms.append((t2 - t1) * 1000.0)
                # min-of-mins, not median-of-pair-ratios: timing noise on
                # a shared box is strictly ADDITIVE (preemption, cache
                # pollution), so the minimum over reps converges on the
                # true program cost while a load burst that lands inside
                # one pair's window skews its ratio arbitrarily — the
                # estimator that let ttft_p50_ratio flake to 1.17 on a
                # clean tree under box load
                ratios[c] = min(ms_ms) / min(ps_ms)
                prefill_ms[c] = min(ps_ms)
        finally:
            eng.allocator.free(p_pages)
            for pg in d_pages:
                eng.allocator.free(pg)
        return ratios, prefill_ms

    stratum_ratio, stratum_prefill_ms = microbench()
    n_pairs = 16 * len(stratum_ratio)

    #: absolute per-stratum prices for gap modeling — taken from the
    #: PREFILL samples only and shared by BOTH arms (mixed steps price
    #: as prefill x step_ratio), so their own noise mostly cancels in
    #: the ITL ratio.
    by_stratum: dict = {}
    decode_samples, prefill_samples = [], []
    for rnd in rnds:
        for (kind, c), v in rnd["samples"].items():
            if kind == "prefill" and c is not None:
                by_stratum.setdefault(c, []).extend(x for _, x in v)
        prefill_samples.extend(rnd["step_ms"]["prefill"])
        decode_samples.extend(rnd["step_ms"]["decode"])
    med_prefill = {
        c: statistics.median(v) for c, v in by_stratum.items() if v
    }
    med_prefill_all = statistics.median(prefill_samples)
    med_decode = (
        statistics.median(decode_samples) if decode_samples else 0.0
    )
    #: drain-cost-weighted combination: what carrying the decode batch
    #: costs one prompt's WHOLE drain (= its TTFT, queue wait aside —
    #: and under saturation the mixed queue drains no slower: mixed
    #: steps move one chunk per step too, without spending steps on
    #: pure decode). Weights are the microbench's own per-stratum
    #: prefill times, keeping the asserted number fully deterministic.
    weight_total = sum(stratum_prefill_ms.values())
    step_ratio = (
        sum(
            stratum_prefill_ms[c] * r for c, r in stratum_ratio.items()
        )
        / weight_total
    )

    def price(kind, chunk_idx) -> float:
        if kind is None:
            return 0.0
        if kind == "decode":
            return med_decode
        base = med_prefill.get(chunk_idx, med_prefill_all)
        if kind == "mixed":
            return base * stratum_ratio.get(chunk_idx, step_ratio)
        return base

    def modeled_itl_p95(drv) -> float:
        """Gap cost from the arm drive's DETERMINISTIC step schedule:
        each inter-token gap spans a known sequence of (step kind,
        chunk) labels; price them with the shared stratified medians.
        Load bursts cannot move this — only the scheduling policy can."""
        gaps = []
        for steps in drv["emit_steps"].values():
            for a, b in zip(steps, steps[1:]):
                gaps.append(
                    sum(
                        price(*drv["labels"][s])
                        for s in range(a + 1, b + 1)
                    )
                )
        gaps.sort()
        return gaps[int(len(gaps) * 0.95)]

    itl_ratios, itl_wall_ratios = [], []
    res = {}
    disp0 = 0
    for rep in range(pairs):
        arms = [(True, "mixed_on"), (False, "mixed_off")]
        if rep % 2:
            arms.reverse()  # cancel any first-arm bias
        for on, tag in arms:
            res[tag] = arm(on, f"p{rep}{tag}")
        assert res["mixed_on"]["mixed_dispatches"] > disp0
        disp0 = res["mixed_on"]["mixed_dispatches"]
        itl_ratios.append(
            modeled_itl_p95(res["mixed_off"])
            / modeled_itl_p95(res["mixed_on"])
        )
        itl_wall_ratios.append(
            res["mixed_off"]["itl_p95_wall_ms"]
            / res["mixed_on"]["itl_p95_wall_ms"]
        )
    # TTFT p50 ratio: a prompt's first token needs its chunks drained —
    # the same number of chunk steps in both arms, each costing
    # step_ratio more under mixed (and under saturation the mixed queue
    # drains no slower: mixed steps move one chunk per step too, without
    # spending steps on pure decode). The paired per-step cost ratio IS
    # the TTFT p50 ratio; wall TTFTs per arm ride along for reference.
    ttft_ratio = step_ratio

    def strip(r):  # step lists are bulky; keep the medians
        return {
            **{
                k: v
                for k, v in r.items()
                if k not in ("step_ms", "samples", "labels", "emit_steps")
            },
            "step_ms_p50": {
                k: round(statistics.median(v), 2) if v else None
                for k, v in r["step_ms"].items()
            },
        }

    return {
        "workload": (
            f"c={early + num_late} saturation: {early} long decodes + "
            f"steady {isl_late}-token arrivals, fixed budget 512"
        ),
        "pairs": pairs,
        "mixed_on": strip(res["mixed_on"]),
        "mixed_off": strip(res["mixed_off"]),
        #: chunk-stratified prefill step medians (randomized interleaved
        #: drives) + the microbench's per-stratum mixed/prefill program
        #: ratios — the deterministic basis of both asserted numbers
        "prefill_step_ms_p50": {
            f"c{c}": round(v, 2) for c, v in sorted(med_prefill.items())
        },
        "decode_step_ms_p50": round(med_decode, 2),
        "microbench_step_ratio": round(step_ratio, 3),
        "microbench_pairs": n_pairs,
        "stratum_ratios": {
            f"c{c}": round(r, 3) for c, r in sorted(stratum_ratio.items())
        },
        #: XOR itl_p95 / mixed itl_p95, each priced over the arm's
        #: deterministic step schedule with the stratified medians —
        #: >= 2 is the acceptance bar; the raw wall ratio rides along
        "itl_p95_ratio": round(statistics.median(itl_ratios), 3),
        "itl_p95_wall_ratio": round(
            statistics.median(itl_wall_ratios), 3
        ),
        #: mixed ttft_p50 / XOR ttft_p50 (one prompt's drain cost, from
        #: the back-to-back program microbench) — within 15% is the bar
        #: (noise-robust min-based estimator; the DETERMINISTIC part of
        #: the claim is the step-schedule equality below, asserted tight)
        "ttft_p50_ratio": round(ttft_ratio, 3),
        #: steps from arrival to first token, per arm — fully determined
        #: by the scheduling policy (one chunk per step either way), so
        #: the contract asserts exact equality: mixed steps do not delay
        #: a prompt's drain by even one step
        "ttft_p50_steps_on": res["mixed_on"]["ttft_p50_steps"],
        "ttft_p50_steps_off": res["mixed_off"]["ttft_p50_steps"],
    }


def _trace_overhead_ab(num_requests: int = 8, tokens: int = 64) -> dict:
    """Distributed-tracing overhead A/B (ISSUE 4 acceptance): the SAME
    echo workload through the subprocess harness — where every traced hop
    fires (engine span, trace context on the generate frame, child span
    shipped back as a `span` frame) — with tracing off vs on.

    This box's background load swings short echo runs by tens of percent
    — far above the span layer's true cost — so the empirical A/B runs
    INTERLEAVED (alternating-order off/on pairs, median per-pair ratio:
    a slow window hits both arms and cancels) and is reported as a
    sanity band, while the <3% claim is pinned by `modeled_overhead_pct`:
    a deterministic microbench of the per-request span work (parent span
    + event + adopted child span) divided by the measured per-request
    serving time. The model is conservative — it charges the whole span
    fan to the critical path."""
    import asyncio
    import statistics

    from dynamo_tpu import telemetry
    from dynamo_tpu.external.client import SubprocessEngine

    drive = _make_echo_driver(num_requests, tokens)

    def span_layer_us_per_request(iters: int = 4000) -> float:
        """Deterministic cost of one traced request's span work in THIS
        process: the engine span contextmanager, a first_token event, and
        adopting the child's shipped span into the ring."""
        telemetry.configure(enabled=True, ring_size=8)
        child = {
            "trace_id": "0" * 32, "span_id": "1" * 16,
            "parent_id": None, "name": "child.generate",
            "service": "ext-child", "start_ts": 0.0, "duration_ms": 1.0,
            "status": "ok", "attrs": {}, "events": [],
        }
        t0 = time.perf_counter()
        for _ in range(iters):
            with telemetry.span(
                "engine.generate", service="engine",
                attrs={"request_id": "bench"},
            ) as sp:
                sp.add_event("first_token")
                child["trace_id"] = sp.trace_id
                telemetry.record_span_dict(dict(child))
        us = (time.perf_counter() - t0) / iters * 1e6
        telemetry.configure(enabled=False)
        return us

    async def run(pairs: int = 6):
        ext = SubprocessEngine(
            [sys.executable, "-m", "dynamo_tpu.external.reference_worker",
             "--model", "bench-trace", "--metrics-interval", "60"],
            name="bench-trace",
        )
        await ext.start()
        ratios = []
        offs, ons = [], []
        try:
            await drive(ext, "warm-trace")
            for rep in range(pairs):
                arms = [(False, "off"), (True, "on")]
                if rep % 2:
                    arms.reverse()  # cancel any first-arm bias
                rate = {}
                for on, tag in arms:
                    telemetry.configure(
                        enabled=on, ring_size=64 if on else None
                    )
                    n, t = await drive(ext, f"{tag}-{rep}-")
                    rate[tag] = n / t if t else 0.0
                if rate["off"] and rate["on"]:
                    ratios.append(rate["on"] / rate["off"])
                    offs.append(rate["off"])
                    ons.append(rate["on"])
        finally:
            telemetry.configure(enabled=False)
            await ext.stop()
        ratio = statistics.median(ratios) if ratios else None
        off_med = statistics.median(offs) if offs else None
        span_us = span_layer_us_per_request()
        modeled = None
        if off_med:
            request_us = tokens / off_med * 1e6  # wall us per request
            modeled = round(span_us / request_us * 100.0, 3)
        return {
            "requests": num_requests,
            "pairs": len(ratios),
            "trace_off_tok_s": round(off_med, 1) if off_med else None,
            "trace_on_tok_s": (
                round(statistics.median(ons), 1) if ons else None
            ),
            "measured_overhead_pct": (
                round((1.0 - ratio) * 100.0, 2) if ratio else None
            ),
            "span_layer_us_per_request": round(span_us, 2),
            "modeled_overhead_pct": modeled,
        }

    return asyncio.run(run())


def _slo_overhead_ab(pairs: int = 3, osl: int = 32, n_req: int = 8) -> dict:
    """Fleet-telemetry overhead A/B (ISSUE 6 acceptance): the SLO
    sketches + SLA accounting + fleet-frame serialization must cost <1%
    of token throughput. Like trace_overhead, this box's load noise on a
    short tiny-engine run dwarfs the true cost, so the <1% claim is
    pinned by `modeled_overhead_pct` — a deterministic microbench of the
    per-token SLO work (one sketch observe per token + the finish-time
    SLA judgement amortized over the request) against the measured
    per-token serving time — while the interleaved wall A/B (one warm
    engine, `fleet_telemetry` toggled per drive, alternating-order
    pairs) rides along as a sanity band. to_wire() (the per-publish
    fleet frame, ~1/s per worker) is priced separately."""
    import statistics

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.telemetry.slo import SloTracker

    tr = SloTracker()
    iters = 20_000
    t0 = time.perf_counter()
    for i in range(iters):
        tr.observe("itl_ms", 10.0 + (i & 15))
    observe_us = (time.perf_counter() - t0) / iters * 1e6
    t0 = time.perf_counter()
    for _ in range(2_000):
        tr.finish_request(
            ttft_ms=100.0, itl_ms=10.0, e2e_ms=500.0, tokens=osl
        )
    finish_us = (time.perf_counter() - t0) / 2_000 * 1e6
    t0 = time.perf_counter()
    for _ in range(200):
        tr.to_wire()
    wire_us = (time.perf_counter() - t0) / 200 * 1e6

    eng = JaxEngine(EngineConfig.for_tests())
    slo_tracker = eng.slo

    def drive(tag: str) -> tuple[float, int]:
        for i in range(n_req):
            eng.add_request(
                f"{tag}-{i}", [1 + i, 2, 3, 4],
                SamplingParams(temperature=0.0, max_tokens=osl),
            )
        t0 = time.perf_counter()
        done = eng.run_to_completion()
        dt = time.perf_counter() - t0
        eng.allocator.clear_cache()
        toks = sum(len(v) for v in done.values())
        return (toks / dt if dt else 0.0), toks

    drive("warm")  # compile every program before the timed arms
    rates: dict = {"on": [], "off": []}
    on_tokens = on_observes = on_finishes = 0
    for rep in range(pairs):
        arms = [("on", True), ("off", False)]
        if rep % 2:
            arms.reverse()  # cancel any first-arm bias
        for tag, on in arms:
            eng.slo = slo_tracker if on else None
            eng._fleet_telemetry = on
            if on:
                obs0 = sum(
                    sk.count for sk in slo_tracker.sketches.values()
                )
                fin0 = slo_tracker.requests_total
            rate, toks = drive(f"{tag}{rep}")
            rates[tag].append(rate)
            if on:
                on_tokens += toks
                on_observes += (
                    sum(sk.count for sk in slo_tracker.sketches.values())
                    - obs0
                )
                on_finishes += slo_tracker.requests_total - fin0
    eng.slo = slo_tracker
    eng._fleet_telemetry = True
    on_med = statistics.median(rates["on"])
    off_med = statistics.median(rates["off"])
    modeled = measured = None
    # the engine observes once per EMISSION (a fused K-step emission
    # spreads one observe over its K tokens): price the MEASURED call
    # pattern, not a one-observe-per-token worst case
    obs_per_token = on_observes / on_tokens if on_tokens else 1.0
    fin_per_token = on_finishes / on_tokens if on_tokens else 1.0 / osl
    if off_med:
        serving_us_per_token = 1e6 / off_med
        modeled = round(
            (observe_us * obs_per_token + finish_us * fin_per_token)
            / serving_us_per_token * 100.0,
            3,
        )
        measured = round((1.0 - on_med / off_med) * 100.0, 2)
    return {
        "pairs": pairs,
        "telemetry_on_tok_s": round(on_med, 1),
        "telemetry_off_tok_s": round(off_med, 1),
        "observe_us": round(observe_us, 3),
        "finish_request_us": round(finish_us, 3),
        "frame_to_wire_us": round(wire_us, 2),
        "observes_per_token": round(obs_per_token, 4),
        "modeled_overhead_pct": modeled,
        "measured_overhead_pct": measured,
    }


def _handover_ab() -> dict:
    """Worker-handover A/B (ISSUE 12 acceptance): TTFT of a CONTINUED
    stream when its prompt blocks arrived warm via handover vs
    replay-by-recompute, plus the bytes-moved vs prefill-flops-saved
    accounting. The headline numbers are DETERMINISTIC by construction:
    blocks/bytes moved follow exactly from the workload shape and the
    canonical wire format, flops saved is the standard 2·P·T over the
    cached tokens, and `modeled_ttft_ratio` counts prefill-chunk
    dispatches (uncached/chunk vs total/chunk) — the wall-clock TTFT
    pair rides along as a sanity band only (box noise).

    Engine-level: the same export/adopt calls the Worker handover op
    drives (engine.handover_metas / export_blocks_by_hash /
    prepare+commit_handover_adopt); the transfer-plane hop is covered by
    tests/test_handover.py. The accounting itself (2·P·T, wire bytes,
    chunk-counted modeled ratio) lives in kv_economy.CostModel — the
    ONE pricing function the router, the planner and this bench share
    (ISSUE 18)."""
    from dataclasses import replace

    import jax
    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.kv_economy import CostModel
    from dynamo_tpu.tokens import hash_token_blocks

    cfg = replace(EngineConfig.for_tests(), max_pages_per_seq=32)
    prompt = [((i * 37) % 211) + 1 for i in range(48)]
    n_emit = 8

    # retiring side: serve once (registers prompt + generated blocks),
    # then export the whole registered set in the canonical wire format
    a = JaxEngine(cfg)
    a.add_request(
        "warm", prompt,
        SamplingParams(temperature=0.0, max_tokens=n_emit, ignore_eos=True),
    )
    emitted = a.run_to_completion()["warm"]
    metas = a.handover_metas()
    t0 = time.perf_counter()
    emetas, k, v = a.export_blocks_by_hash([h for h, _, _ in metas])
    export_s = time.perf_counter() - t0
    bytes_moved = int(k.nbytes + v.nbytes)
    blocks_moved = len(emetas)
    block_bytes = bytes_moved // blocks_moved

    # successor: compile-warm its programs on a DISJOINT prompt so the
    # cold/warm TTFT pair measures prefill work, not XLA compiles
    b = JaxEngine(cfg)
    b.add_request(
        "jit", [7] * len(prompt),
        SamplingParams(temperature=0.0, max_tokens=n_emit, ignore_eos=True),
    )
    b.run_to_completion()
    b.allocator.clear_cache()

    continuation = list(prompt) + [int(t) for t in emitted]

    def ttft(tag: str) -> float:
        b.add_request(
            tag, continuation,
            SamplingParams(temperature=0.0, max_tokens=2, ignore_eos=True),
        )
        t0 = time.perf_counter()
        for _ in range(10_000):
            outs = b.step()
            if any(o.request_id == tag and o.new_token_ids for o in outs):
                dt = time.perf_counter() - t0
                b.run_to_completion()  # drain the tail
                return dt
        raise RuntimeError("no first token")

    # replay-by-recompute: the continuation prefills from scratch
    ttft_cold_s = ttft("cold")
    b.allocator.clear_cache()

    # warm handover: adopt the exported blocks, then the SAME
    # continuation prefix-hits them
    t0 = time.perf_counter()
    pages, kept, want = b.prepare_handover_adopt(emetas)
    b.inject_pages(
        pages,
        np.ascontiguousarray(k[:, :, want]),
        np.ascontiguousarray(v[:, :, want]),
    )
    adopted = b.commit_handover_adopt(pages, kept)
    adopt_s = time.perf_counter() - t0
    hashes = hash_token_blocks(
        continuation, block_size=cfg.page_size, salt=cfg.model
    )
    cached_tokens = b.allocator.match_length(hashes) * cfg.page_size
    ttft_warm_s = ttft("warmc")

    n_params = sum(int(x.size) for x in jax.tree.leaves(b.params))
    cm = CostModel(
        params=n_params, block_bytes=block_bytes, page_size=cfg.page_size
    )
    flops_saved = cm.flops_saved(cached_tokens)
    assert cm.bytes_moved(blocks_moved) == bytes_moved
    return {
        "prompt_tokens": len(prompt),
        "emitted_tokens": len(emitted),
        "page_size": cfg.page_size,
        "params": n_params,
        "blocks_moved": blocks_moved,
        "block_bytes": block_bytes,
        "bytes_moved": bytes_moved,
        "blocks_adopted": adopted,
        "cached_tokens": cached_tokens,
        "prefill_flops_saved": flops_saved,
        "flops_saved_per_byte": round(flops_saved / bytes_moved, 2),
        "export_s": round(export_s, 4),
        "adopt_s": round(adopt_s, 4),
        "ttft_cold_s": round(ttft_cold_s, 4),
        "ttft_warm_s": round(ttft_warm_s, 4),
        "measured_ttft_ratio": round(ttft_warm_s / ttft_cold_s, 3)
        if ttft_cold_s
        else None,
        # deterministic: prefill-chunk dispatches the warm continuation
        # skips vs the cold one — the pinned contract number
        "modeled_ttft_ratio": round(
            cm.modeled_ttft_ratio(
                len(continuation), cached_tokens, cfg.prefill_chunk
            ),
            4,
        ),
    }


def _prefix_migration_ab() -> dict:
    """Per-prefix KV migration A/B (ISSUE 18 acceptance): a multi-turn
    chat session's turn-2 TTFT when only the session's HOT PREFIX CHAIN
    migrated to a fresh worker vs cold prefill, priced by the shared
    kv_economy CostModel. Unlike `_handover_ab` (the whole registered
    set moves with its worker), this moves exactly the chain the next
    request will hit — the router's migrate_prefix shape: export the
    matched hashes, adopt on the destination, re-serve.

    Deterministic headline: turn 1 is 32 tokens (8 full blocks at
    page_size=4 — the source's registered chain covers the prompt;
    decode tokens ride uncached), 8 emitted; turn 2 re-sends the
    history plus 8 user tokens → 48 total, 16 uncached → 1 warm prefill
    chunk vs 3 cold at chunk=16 (modeled_ttft_ratio 1/3).
    should_migrate must hold at this shape — the bench run re-checks
    the same pricing fn the router gates on."""
    from dataclasses import replace

    import jax
    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.kv_economy import CostModel
    from dynamo_tpu.tokens import hash_token_blocks

    cfg = replace(EngineConfig.for_tests(), max_pages_per_seq=32)
    turn1 = [((i * 37) % 211) + 1 for i in range(32)]
    n_emit = 8

    # the session's home worker: serve turn 1 (prompt + generated blocks
    # register as they fill), then export ONLY the chain turn 2 needs
    a = JaxEngine(cfg)
    a.add_request(
        "turn1", turn1,
        SamplingParams(temperature=0.0, max_tokens=n_emit, ignore_eos=True),
    )
    emitted = a.run_to_completion()["turn1"]
    history = list(turn1) + [int(t) for t in emitted]
    turn2 = history + [((i * 53) % 211) + 1 for i in range(8)]
    chain = hash_token_blocks(
        history, block_size=cfg.page_size, salt=cfg.model
    )
    t0 = time.perf_counter()
    exported = a.export_blocks_by_hash([int(h) for h in chain])
    export_s = time.perf_counter() - t0
    if exported is None:
        raise RuntimeError("hot prefix chain not resident on the source")
    emetas, k, v = exported
    bytes_moved = int(k.nbytes + v.nbytes)
    blocks_moved = len(emetas)
    block_bytes = bytes_moved // blocks_moved

    # the fresh worker the router redirected to: compile-warm on a
    # disjoint prompt so the TTFT pair measures prefill work only
    b = JaxEngine(cfg)
    b.add_request(
        "jit", [7] * len(turn2),
        SamplingParams(temperature=0.0, max_tokens=n_emit, ignore_eos=True),
    )
    b.run_to_completion()
    b.allocator.clear_cache()

    def ttft(tag: str) -> float:
        b.add_request(
            tag, turn2,
            SamplingParams(temperature=0.0, max_tokens=2, ignore_eos=True),
        )
        t0 = time.perf_counter()
        for _ in range(10_000):
            outs = b.step()
            if any(o.request_id == tag and o.new_token_ids for o in outs):
                dt = time.perf_counter() - t0
                b.run_to_completion()  # drain the tail
                return dt
        raise RuntimeError("no first token")

    # cold: the suppressed-migration path — turn 2 prefills from scratch
    ttft_cold_s = ttft("cold")
    b.allocator.clear_cache()

    # warm: adopt the migrated chain, then the SAME turn 2 prefix-hits
    t0 = time.perf_counter()
    pages, kept, want = b.prepare_handover_adopt(emetas)
    b.inject_pages(
        pages,
        np.ascontiguousarray(k[:, :, want]),
        np.ascontiguousarray(v[:, :, want]),
    )
    adopted = b.commit_handover_adopt(pages, kept)
    adopt_s = time.perf_counter() - t0
    hashes = hash_token_blocks(
        turn2, block_size=cfg.page_size, salt=cfg.model
    )
    cached_tokens = b.allocator.match_length(hashes) * cfg.page_size
    ttft_warm_s = ttft("warmc")

    n_params = sum(int(x.size) for x in jax.tree.leaves(b.params))
    cm = CostModel(
        params=n_params, block_bytes=block_bytes, page_size=cfg.page_size
    )
    price = cm.price(blocks_moved)
    return {
        "turn1_tokens": len(turn1),
        "turn2_tokens": len(turn2),
        "emitted_tokens": len(emitted),
        "page_size": cfg.page_size,
        "params": n_params,
        "blocks_moved": blocks_moved,
        "block_bytes": block_bytes,
        "bytes_moved": bytes_moved,
        "blocks_adopted": adopted,
        "cached_tokens": cached_tokens,
        "prefill_flops_saved": cm.flops_saved(cached_tokens),
        "flops_saved_per_byte": round(price.flops_saved_per_byte, 2),
        # the router's gate, re-evaluated on the bench shape: this move
        # must clear the break-even threshold
        "should_migrate": cm.should_migrate(blocks_moved),
        "export_s": round(export_s, 4),
        "adopt_s": round(adopt_s, 4),
        "ttft_cold_s": round(ttft_cold_s, 4),
        "ttft_warm_s": round(ttft_warm_s, 4),
        "measured_ttft_ratio": round(ttft_warm_s / ttft_cold_s, 3)
        if ttft_cold_s
        else None,
        # deterministic: 1 warm prefill chunk vs 3 cold (16 uncached vs
        # 48 total at chunk=16) — the pinned contract number
        "modeled_ttft_ratio": round(
            cm.modeled_ttft_ratio(
                len(turn2), cached_tokens, cfg.prefill_chunk
            ),
            4,
        ),
    }


def _flight_overhead_ab(pairs: int = 4, osl: int = 32, n_req: int = 8) -> dict:
    """Flight-recorder overhead A/B (ISSUE 7 acceptance): the per-step
    record — one small dict build + deque append, ONCE per engine step
    regardless of batch size — must cost <1% of token throughput. Like
    trace/slo_overhead, this box's load noise dwarfs the true cost on a
    short tiny-engine run, so the <1% claim is pinned by
    `modeled_overhead_pct`: a deterministic microbench of record_step()
    priced at the MEASURED records-per-token rate of the same drive (a
    decode step amortizes one record over its whole batch), while the
    interleaved wall A/B (one warm engine, `flight` nulled per arm,
    alternating-order pairs) rides along as a sanity band."""
    import statistics

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import EngineMetrics, JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.telemetry.flight import FlightRecorder

    # deterministic microbench: one per-step record against live-ish
    # counters (the delta loop is the dominant cost)
    fl = FlightRecorder(512)
    fm = EngineMetrics()
    iters = 20_000
    t0 = time.perf_counter()
    for i in range(iters):
        fm.generated_tokens += 8
        fm.time_decode_dispatch_ms += 0.5
        fl.record_step(
            fm, kind="decode", step_ms=1.0, n_decode=8, b_decode=8,
            waiting=0, running=8, free_pages=100, active_pages=28,
            watermark=28,
        )
    record_us = (time.perf_counter() - t0) / iters * 1e6

    eng = JaxEngine(EngineConfig.for_tests())
    recorder = eng.flight

    def drive(tag: str) -> tuple[float, int]:
        for i in range(n_req):
            eng.add_request(
                f"{tag}-{i}", [1 + i, 2, 3, 4],
                SamplingParams(temperature=0.0, max_tokens=osl),
            )
        t0 = time.perf_counter()
        done = eng.run_to_completion()
        dt = time.perf_counter() - t0
        eng.allocator.clear_cache()
        toks = sum(len(v) for v in done.values())
        return (toks / dt if dt else 0.0), toks

    drive("warm")  # compile every program before the timed arms
    rates: dict = {"on": [], "off": []}
    on_records = on_tokens = 0
    for rep in range(pairs):
        arms = [("on", True), ("off", False)]
        if rep % 2:
            arms.reverse()  # cancel any first-arm bias
        for tag, on in arms:
            eng.flight = recorder if on else None
            if on:
                rec0 = recorder._seq
            rate, toks = drive(f"{tag}{rep}")
            rates[tag].append(rate)
            if on:
                on_records += recorder._seq - rec0
                on_tokens += toks
    eng.flight = recorder
    on_med = statistics.median(rates["on"])
    off_med = statistics.median(rates["off"])
    records_per_token = on_records / on_tokens if on_tokens else 1.0
    modeled = measured = None
    if off_med:
        serving_us_per_token = 1e6 / off_med
        modeled = round(
            record_us * records_per_token / serving_us_per_token * 100.0, 3
        )
        measured = round((1.0 - on_med / off_med) * 100.0, 2)
    return {
        "pairs": pairs,
        "flight_on_tok_s": round(on_med, 1),
        "flight_off_tok_s": round(off_med, 1),
        "record_us": round(record_us, 3),
        "records_per_token": round(records_per_token, 4),
        "modeled_overhead_pct": modeled,
        "measured_overhead_pct": measured,
    }


def _kv_index_overhead_ab(pairs: int = 4, osl: int = 32, n_req: int = 8) -> dict:
    """KV index sequencing overhead A/B (ISSUE 13 acceptance): the
    sequence stamp + rolling-digest fold added to the KV event publish
    path must cost <1% of token throughput. The stamp runs in the
    worker's async publish loop — off the token path entirely — and KV
    events are RARE relative to tokens (one stored event per full page
    = 1/page_size per generated token, plus evictions), so the honest
    claim is the DETERMINISTIC model: a microbench of the REAL
    Worker._stamp_kv_events hot path priced at the measured
    events-per-token rate of a live drive. The interleaved wall A/B
    (same engine, publish-tick simulation stamping on/off per arm)
    rides along as a sanity band."""
    import statistics

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.model_card import ModelDeploymentCard
    from dynamo_tpu.worker import Worker

    card = ModelDeploymentCard(name="tiny", kv_page_size=4)
    w = Worker(None, card, engine_kind="echo")

    # deterministic microbench: the real stamping path over realistic
    # single-hash stored/removed batches (what the allocator emits)
    batch = [
        {
            "kind": "stored" if i % 3 else "removed",
            "block_hashes": [(i * 2654435761) & ((1 << 64) - 1)],
            "parent_hash": None,
            "token_blocks": [[1, 2, 3, 4]],
        }
        for i in range(64)
    ]
    iters = 2_000
    t0 = time.perf_counter()
    for _ in range(iters):
        for ev in batch:
            ev.pop("seq", None)
        w._stamp_kv_events(batch)
    stamp_us = (time.perf_counter() - t0) / (iters * len(batch)) * 1e6

    events = []
    eng = JaxEngine(
        EngineConfig.for_tests(), on_kv_event=lambda e: events.append(e)
    )
    wire = Worker._kv_event_wire

    def drive(tag: str, stamp: bool) -> tuple[float, int, int]:
        del events[:]
        for i in range(n_req):
            eng.add_request(
                f"{tag}-{i}", [1 + i, 2, 3, 4],
                SamplingParams(temperature=0.0, max_tokens=osl),
            )
        t0 = time.perf_counter()
        done = eng.run_to_completion()
        # the publish-tick work the sequencing adds, in-line so the arm
        # pays it inside the timed window
        batch = [wire(e) for e in events]
        if stamp:
            w._stamp_kv_events(batch)
        dt = time.perf_counter() - t0
        eng.allocator.clear_cache()
        toks = sum(len(v) for v in done.values())
        return (toks / dt if dt else 0.0), toks, len(batch)

    drive("warm", False)
    rates: dict = {"on": [], "off": []}
    ev_total = tok_total = 0
    for rep in range(pairs):
        arms = [("on", True), ("off", False)]
        if rep % 2:
            arms.reverse()
        for tag, stamp in arms:
            rate, toks, nev = drive(f"{tag}{rep}", stamp)
            rates[tag].append(rate)
            if stamp:
                ev_total += nev
                tok_total += toks
    on_med = statistics.median(rates["on"])
    off_med = statistics.median(rates["off"])
    events_per_token = ev_total / tok_total if tok_total else 1.0
    modeled = measured = None
    if off_med:
        serving_us_per_token = 1e6 / off_med
        modeled = round(
            stamp_us * events_per_token / serving_us_per_token * 100.0, 4
        )
        measured = round((1.0 - on_med / off_med) * 100.0, 2)
    return {
        "pairs": pairs,
        "seq_on_tok_s": round(on_med, 1),
        "seq_off_tok_s": round(off_med, 1),
        "stamp_us": round(stamp_us, 4),
        "events_per_token": round(events_per_token, 4),
        "modeled_overhead_pct": modeled,
        "measured_overhead_pct": measured,
    }


def _trace_plane_overhead_ab(
    pairs: int = 3, osl: int = 32, n_req: int = 8
) -> dict:
    """Fleet trace plane overhead A/B (ISSUE 14 acceptance): span
    SHIPPING (sink append + msgpack batch pack) + phase-histogram
    EXEMPLAR stamping on a warm engine must cost <1% of token
    throughput. Like the sibling telemetry A/Bs, the <1% claim is the
    DETERMINISTIC model — a microbench of the per-span ship work and
    the per-observe exemplar delta priced at the MEASURED
    spans/request and observes/token of a live traced drive — while
    the interleaved wall A/B rides along as a sanity band. The model
    is conservative twice over: the batch pack actually runs in the
    async publish loop (off the token path), and every engine-thread
    observe is charged the exemplar-stamped price."""
    import statistics

    import msgpack

    from dynamo_tpu import telemetry
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.telemetry import phases as _phases
    from dynamo_tpu.telemetry import traceplane

    # -- microbench 1: one shipped span (open+close through the sink)
    # plus its amortized share of a 64-span msgpack batch pack
    telemetry.configure(enabled=True, ring_size=8)
    traceplane.ensure_shipping()
    iters = 3_000
    t0 = time.perf_counter()
    for _ in range(iters):
        with telemetry.span("engine.generate", service="engine") as sp:
            sp.add_event("first_token")
    span_us = (time.perf_counter() - t0) / iters * 1e6
    batch = traceplane.drain_spans()[:64]
    t0 = time.perf_counter()
    for _ in range(200):
        msgpack.packb(batch, use_bin_type=True, default=repr)
    pack_us_per_span = (
        (time.perf_counter() - t0) / (200 * max(1, len(batch))) * 1e6
    )
    ship_us_per_span = span_us + pack_us_per_span

    # -- microbench 2: exemplar-stamped observe vs plain observe
    tid = "ab" * 16
    t0 = time.perf_counter()
    for i in range(20_000):
        _phases.observe("decode_step_ms", 1.0 + (i & 7), trace_id=tid)
    stamped_us = (time.perf_counter() - t0) / 20_000 * 1e6
    telemetry.configure(enabled=False)
    _phases.phase_histograms.reset()
    t0 = time.perf_counter()
    for i in range(20_000):
        _phases.observe("decode_step_ms", 1.0 + (i & 7))
    plain_us = (time.perf_counter() - t0) / 20_000 * 1e6
    exemplar_us = max(0.0, stamped_us - plain_us)

    # -- the interleaved wall A/B on one warm engine, measuring the
    # live spans/request + observes/token rates for the model
    eng = JaxEngine(EngineConfig.for_tests())

    def drive(tag: str, on: bool) -> tuple[float, int, int, int]:
        if on:
            telemetry.configure(enabled=True, ring_size=64)
            traceplane.ensure_shipping()
            traceplane.drain_spans()
        obs0 = sum(
            sum(c) for c in _phases.phase_histograms._counts.values()
        )
        t0 = time.perf_counter()
        reqs = []
        for i in range(n_req):
            # the traced path exactly as AsyncEngineRunner drives it:
            # one engine span per request, trace id stamped on the
            # engine-side Request (exemplars + breakdown enrichment)
            if on:
                with telemetry.span(
                    "engine.generate", service="engine"
                ) as sp:
                    req = eng.add_request(
                        f"{tag}-{i}", [1 + i, 2, 3, 4],
                        SamplingParams(temperature=0.0, max_tokens=osl),
                    )
                    req.trace_id = sp.trace_id
            else:
                req = eng.add_request(
                    f"{tag}-{i}", [1 + i, 2, 3, 4],
                    SamplingParams(temperature=0.0, max_tokens=osl),
                )
            reqs.append(req)
        done = eng.run_to_completion()
        shipped = 0
        if on:
            spans = traceplane.drain_spans()
            msgpack.packb(spans, use_bin_type=True, default=repr)
            shipped = len(spans)
        dt = time.perf_counter() - t0
        if on:
            telemetry.configure(enabled=False)
        eng.allocator.clear_cache()
        toks = sum(len(v) for v in done.values())
        obs = (
            sum(sum(c) for c in _phases.phase_histograms._counts.values())
            - obs0
        )
        return (toks / dt if dt else 0.0), toks, shipped, obs

    drive("warm", False)
    rates: dict = {"on": [], "off": []}
    span_total = tok_total = obs_total = 0
    for rep in range(pairs):
        arms = [("on", True), ("off", False)]
        if rep % 2:
            arms.reverse()
        for tag, on in arms:
            rate, toks, shipped, obs = drive(f"{tag}{rep}", on)
            rates[tag].append(rate)
            if on:
                span_total += shipped
                tok_total += toks
                obs_total += obs
    telemetry.configure(enabled=False)
    traceplane.disable_shipping()
    _phases.phase_histograms.reset()
    on_med = statistics.median(rates["on"])
    off_med = statistics.median(rates["off"])
    spans_per_token = span_total / tok_total if tok_total else 1.0
    observes_per_token = obs_total / tok_total if tok_total else 1.0
    modeled = measured = None
    if off_med:
        serving_us_per_token = 1e6 / off_med
        modeled = round(
            (
                ship_us_per_span * spans_per_token
                + exemplar_us * observes_per_token
            )
            / serving_us_per_token
            * 100.0,
            4,
        )
        measured = round((1.0 - on_med / off_med) * 100.0, 2)
    return {
        "pairs": pairs,
        "trace_plane_on_tok_s": round(on_med, 1),
        "trace_plane_off_tok_s": round(off_med, 1),
        "ship_us_per_span": round(ship_us_per_span, 3),
        "exemplar_us_per_observe": round(exemplar_us, 4),
        "spans_per_token": round(spans_per_token, 4),
        "observes_per_token": round(observes_per_token, 4),
        "modeled_overhead_pct": modeled,
        "measured_overhead_pct": measured,
    }


def _failover_blackout() -> dict:
    """Control-plane failover blackout (ISSUE 15 acceptance): primary +
    warm standby in-process, a steady ringed publisher, SIGKILL-
    equivalent primary death. `blackout_ms` spans the last successful
    publish before the kill to the FIRST successful publish after the
    standby promoted (detector budget 0.3s here). Plus the replication-
    overhead A/B: the journal tap is the only cost replication adds to
    the publish path, measured by interleaved tap-on/tap-off batches on
    the fabric publish path and MODELED against the measured wire
    publish round-trip (<2% target, asserted in test_bench_contract —
    wall ratios on this box swing with load, so the deterministic model
    is the claim)."""
    import asyncio
    import statistics
    import time as _time

    from dynamo_tpu.runtime.fabric import (
        FabricNode,
        FabricServer,
        RemoteFabric,
    )
    from dynamo_tpu.runtime.fabric.local import LocalFabric

    async def drive() -> dict:
        primary = FabricServer(port=0)
        await primary.start()
        node = FabricNode(
            port=0, standby_of=primary.address, detector_budget_s=0.3,
            orphan_grace=10.0,
        )
        await node.start()
        client = await RemoteFabric.connect(
            f"{primary.address},{node.address}"
        )
        try:
            # steady-state wire publish cost (standby attached — the
            # deployed configuration)
            for _ in range(20):  # warm
                await client.publish("kv_events.bench", {"i": -1}, b"x" * 64)
            t0 = _time.perf_counter()
            n_wire = 200
            for i in range(n_wire):
                await client.publish("kv_events.bench", {"i": i}, b"x" * 64)
            wire_us = (_time.perf_counter() - t0) / n_wire * 1e6

            # blackout: publish at a tight cadence, kill, time to the
            # first success on the promoted standby
            before = after = 0
            last_ok = _time.perf_counter()
            for i in range(50):
                await client.publish("kv_events.bench", {"b": i}, b"x")
                before += 1
                last_ok = _time.perf_counter()
            primary.kill()
            first_ok = None
            deadline = _time.perf_counter() + 30.0
            while first_ok is None and _time.perf_counter() < deadline:
                try:
                    await client.publish("kv_events.bench", {"a": after}, b"x")
                    first_ok = _time.perf_counter()
                except (ConnectionError, RuntimeError, OSError):
                    await asyncio.sleep(0.005)
            if first_ok is None:
                return {"error": "no publish succeeded after the kill"}
            for i in range(20):
                await client.publish("kv_events.bench", {"a": i}, b"x")
                after += 1
            return {
                "blackout_ms": round((first_ok - last_ok) * 1000.0, 1),
                "detector_budget_ms": 300.0,
                "publishes_before": before,
                "publishes_after": after + 1,
                "promoted_fence": node.fabric.fence,
                "wire_publish_us": round(wire_us, 1),
            }
        finally:
            await client.close()
            await node.stop()
            await primary.stop()

    async def tap_ab(wire_us: float) -> dict:
        """Interleaved journal-tap on/off batches on the publish path."""
        f = LocalFabric()
        n, reps = 400, 6
        base_runs, tap_runs = [], []
        q = None
        for r in range(2 * reps):
            tap = r % 2 == 1
            if tap and q is None:
                q = f.repl_attach()
            if not tap and q is not None:
                f.repl_detach(q)
                q = None
            t0 = _time.perf_counter()
            for i in range(n):
                await f.publish("kv_events.ab", {"i": i}, b"x" * 64)
            us = (_time.perf_counter() - t0) / n * 1e6
            (tap_runs if tap else base_runs).append(us)
            if q is not None:
                while not q.empty():  # drain like a live standby would
                    q.get_nowait()
        base_us = statistics.median(base_runs)
        tap_us = statistics.median(tap_runs)
        tap_cost = max(0.0, tap_us - base_us)
        return {
            "publish_path_base_us": round(base_us, 3),
            "publish_path_tap_us": round(tap_us, 3),
            "tap_cost_us": round(tap_cost, 3),
            # the model: replication adds tap_cost to every wire publish
            # that costs wire_us end to end — THIS is the deployment
            # overhead claim (<2%)
            "modeled_repl_overhead_pct": round(
                tap_cost / wire_us * 100.0, 4
            ) if wire_us else None,
            # the raw in-process path ratio (microseconds on
            # microseconds) — NOT a deployment overhead; reported so the
            # tap cost itself is visible
            "tap_path_ratio_pct": round(
                (tap_us / base_us - 1.0) * 100.0, 2
            ) if base_us else None,
        }

    async def run():
        doc = await drive()
        if "error" in doc:
            return doc
        doc.update(await tap_ab(doc["wire_publish_us"]))
        return doc

    return asyncio.run(run())


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    platform = probe_backend(
        retries=int(os.environ.get("BENCH_PROBE_RETRIES", "3"))
    )
    if platform == "cpu":
        # Commit the fallback before jax initializes in-process. The env var
        # alone is ineffective once sitecustomize has run — re-apply via
        # jax.config (backends init lazily, so this sticks).
        os.environ["JAX_PLATFORMS"] = "cpu"
        honor_jax_platforms_env()

    if platform == "cpu":
        # One CPU core cannot run the TPU workload (llama3-1b x 128
        # requests would take hours); fall back to a CPU-feasible
        # configuration and say so in extras. vs_baseline compares against
        # the CPU record (cpu_output_tok_s), never the TPU one.
        model = os.environ.get("BENCH_MODEL", "tiny")
        num_requests = int(os.environ.get("BENCH_REQUESTS", "16"))
        isl = int(os.environ.get("BENCH_ISL", "64"))
        osl = int(os.environ.get("BENCH_OSL", "32"))
    else:
        model = os.environ.get("BENCH_MODEL", "llama3-1b")
        num_requests = int(os.environ.get("BENCH_REQUESTS", "128"))
        isl = int(os.environ.get("BENCH_ISL", "128"))
        osl = int(os.environ.get("BENCH_OSL", "64"))

    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    chunk = -(-max(128, isl) // 64) * 64  # page-aligned prefill chunk
    # One wave: every request resident at once (weights amortize across
    # the whole batch), pages sized for prompt+output per sequence.
    pages_per_seq = -(-(isl + osl + 1) // 64)

    def make_engine(
        attention_impl: str,
        overlap: bool = True,
        decode_steps: int = None,
        kv_quantize: str = "env",
    ) -> JaxEngine:
        if kv_quantize == "env":
            # chip stage: BENCH_KV_QUANTIZE=int8 runs the headline with
            # quantized pages (queued as a tpu_round.sh A/B stage)
            kv_quantize = os.environ.get("BENCH_KV_QUANTIZE") or None
        cfg = EngineConfig(
            model=model,
            num_pages=max(512, num_requests * (pages_per_seq + 1)),
            page_size=64,
            max_pages_per_seq=max(16, pages_per_seq + 1),
            # Buckets up to and INCLUDING one that fits the whole batch, so
            # decode really runs as one wave (the scheduler caps batches at
            # decode_buckets[-1]).
            decode_buckets=tuple(
                b for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
                if b < num_requests
            ) + (num_requests,),
            prefill_chunk=chunk,
            # Whole-workload dispatches: all prompts prefill in one batched
            # program; decode fuses K steps per host sync (the TPU sits
            # behind a ~65ms tunnel round-trip, so syncs dominate
            # unamortized).
            prefill_token_budget=num_requests * chunk,
            decode_steps=(
                decode_steps
                if decode_steps is not None
                else int(os.environ.get("BENCH_DECODE_STEPS", "64"))
            ),
            max_seqs=max(32, num_requests),
            dtype="bfloat16",
            enable_prefix_caching=False,
            # llama3-8b bf16 (16GB) exceeds a v5e chip's HBM; int8
            # weight-only (BENCH_QUANTIZE=int8) fits it alongside the KV
            # pages.
            quantize=os.environ.get("BENCH_QUANTIZE") or None,
            kv_quantize=kv_quantize,
            attention_impl=attention_impl,
            overlap_decode=overlap,
            # chip stage bench_1b_tp: BENCH_TOPOLOGY=tp=4,dp=2 runs the
            # headline on the combined mesh layout; params place through
            # the logical-axis rule table (ISSUE 20)
            topology=os.environ.get("BENCH_TOPOLOGY", ""),
        )
        return JaxEngine(cfg)

    # Serving-config sweep: the pallas page-walk decode is latency-optimal
    # at small batch but issues O(B x pages) DMA descriptors per layer;
    # "hybrid" gates large decode buckets onto the XLA gather. The bench
    # measures both on TPU and reports the BEST (per-impl numbers in
    # extras) — picking a serving config is legitimate tuning, hiding the
    # loser would not be.
    default_impls = "auto,hybrid" if platform == "tpu" else "auto"
    impls = [
        i.strip()
        for i in os.environ.get("BENCH_ATTENTION", default_impls).split(",")
        if i.strip()
    ]

    eng = make_engine(impls[0])

    import jax

    n_params = sum(int(x.size) for x in jax.tree.leaves(eng.params))
    # MoE: FLOPs/token follow the ACTIVE parameters (top_k of E experts),
    # not the resident total — MFU from total params would overstate ~8x
    # for deepseek-v2-lite. Routed expert leaves are named we_*.
    acfg = eng.adapter.config
    n_experts = getattr(acfg, "n_routed_experts", 0) or getattr(
        acfg, "num_experts", 0
    )
    top_k = getattr(acfg, "num_experts_per_tok", None) or getattr(
        acfg, "top_k", 0
    )
    n_active = n_params
    if n_experts and top_k:
        expert_elems = sum(
            int(leaf.size)
            for path, leaf in jax.tree_util.tree_leaves_with_path(eng.params)
            if any(
                getattr(k, "key", "").startswith("we_")
                and not getattr(k, "key", "").endswith("_scale")
                for k in path
            )
        )
        n_active = n_params - expert_elems + expert_elems * top_k // n_experts

    rng = np.random.default_rng(0)
    prompts = [
        [int(x) for x in rng.integers(1, 32000, isl)] for _ in range(num_requests)
    ]

    def run_timed(eng) -> dict:
        # Warmup with the SAME workload (all requests, same osl) so every
        # decode bucket, fused-step count, and prefill program the timed
        # run uses is compiled before the timer starts — otherwise tok/s
        # and TTFT measure XLA (the fused decode K adapts to remaining
        # max_tokens, so a short warmup osl would compile the wrong K).
        for i, p in enumerate(prompts):
            eng.add_request(
                f"warm{i}", p,
                SamplingParams(temperature=0.0, max_tokens=osl),
            )
        eng.run_to_completion()
        eng.allocator.clear_cache()

        # decode phase split (dispatch/sync/postprocess + overlap
        # counters) is reported as deltas over the TIMED section only
        phase0 = {
            k: getattr(eng.metrics, k)
            for k in (
                "time_decode_dispatch_ms", "time_decode_sync_ms",
                "time_decode_host_ms", "overlap_dispatches",
                "overlap_hits", "overlap_rollbacks",
            )
        }
        t0 = time.time()
        submit = {}
        first_token = {}
        last_token = {}
        tokens_of = {}
        for i, p in enumerate(prompts):
            rid = f"r{i}"
            submit[rid] = time.time()
            eng.add_request(
                rid, p, SamplingParams(temperature=0.0, max_tokens=osl)
            )
        generated = 0
        while eng.has_work:
            for out in eng.step():
                now = time.time()
                generated += len(out.new_token_ids)
                tokens_of[out.request_id] = tokens_of.get(
                    out.request_id, 0
                ) + len(out.new_token_ids)
                if out.is_first and out.request_id not in first_token:
                    first_token[out.request_id] = now
                last_token[out.request_id] = now
        elapsed = time.time() - t0
        ttfts = sorted(first_token[r] - submit[r] for r in first_token)
        itls = sorted(
            (last_token[r] - first_token[r]) / (tokens_of[r] - 1)
            for r in first_token
            if tokens_of.get(r, 0) > 1
        )
        return {
            "tok_s": generated / elapsed,
            "p50_ttft": ttfts[len(ttfts) // 2] if ttfts else float("nan"),
            "p50_itl": itls[len(itls) // 2] if itls else float("nan"),
            "elapsed": elapsed,
            "generated": generated,
            "decode_phases": {
                k: round(getattr(eng.metrics, k) - v, 2)
                for k, v in phase0.items()
            },
        }

    per_impl = {impls[0]: run_timed(eng)}
    for impl in impls[1:]:
        import gc

        del eng
        gc.collect()
        eng = make_engine(impl)
        per_impl[impl] = run_timed(eng)
    best_impl = max(per_impl, key=lambda k: per_impl[k]["tok_s"])
    best = per_impl[best_impl]

    # Overlap on/off A/B (CPU fallback only): the overlapped decode
    # loop's win lives where per-step syncs dominate, so the A/B runs
    # the same workload at decode_steps=1 (classic stepping) with
    # overlap_decode on vs off — BENCH_r06 carries the evidence even
    # when the TPU tunnel is down. The TPU headline number already runs
    # with overlap on (fused K amortizes most of what's left).
    overlap_ab = None
    if platform != "tpu" and os.environ.get("BENCH_OVERLAP_AB", "1") != "0":
        import gc

        ab_steps = int(os.environ.get("BENCH_OVERLAP_AB_STEPS", "1"))
        overlap_ab = {"decode_steps": ab_steps}
        for tag, ov in (("overlap_on", True), ("overlap_off", False)):
            del eng
            gc.collect()
            eng = make_engine(best_impl, overlap=ov, decode_steps=ab_steps)
            r = run_timed(eng)
            ph = r["decode_phases"]
            overlap_ab[tag] = {
                "tok_s": round(r["tok_s"], 2),
                "decode_dispatch_ms": ph["time_decode_dispatch_ms"],
                "decode_sync_ms": ph["time_decode_sync_ms"],
                "decode_host_ms": ph["time_decode_host_ms"],
            }
        off_tok_s = overlap_ab["overlap_off"]["tok_s"]
        overlap_ab["speedup"] = (
            round(overlap_ab["overlap_on"]["tok_s"] / off_tok_s, 3)
            if off_tok_s
            else None
        )

    # KV-quant on/off A/B (CPU fallback now; the chip stage is queued in
    # tpu_round.sh as bench_1b_kvq for BENCH_r06): same workload with
    # int8 pages vs model-dtype pages, plus the pool-byte gauges so the
    # ~2x effective-capacity claim rides the record next to the tok/s.
    kvquant_ab = None
    if platform != "tpu" and os.environ.get("BENCH_KVQUANT_AB", "1") != "0":
        import gc

        kvquant_ab = {}
        for tag, kvq in (("kv_fp", None), ("kv_int8", "int8")):
            del eng
            gc.collect()
            eng = make_engine(best_impl, kv_quantize=kvq)
            r = run_timed(eng)
            kvquant_ab[tag] = {
                "tok_s": round(r["tok_s"], 2),
                "kv_pool_bytes": eng.metrics.kv_pool_bytes,
                "kv_pool_bytes_dense_equiv": (
                    eng.metrics.kv_pool_bytes_dense_equiv
                ),
            }
        fp_tok_s = kvquant_ab["kv_fp"]["tok_s"]
        kvquant_ab["speedup"] = (
            round(kvquant_ab["kv_int8"]["tok_s"] / fp_tok_s, 3)
            if fp_tok_s
            else None
        )
        kvquant_ab["capacity_ratio"] = round(
            kvquant_ab["kv_int8"]["kv_pool_bytes_dense_equiv"]
            / max(kvquant_ab["kv_int8"]["kv_pool_bytes"], 1),
            3,
        )

    # Subprocess external-engine harness A/B (CPU only: the harness is
    # engine-agnostic plumbing; its cost doesn't depend on the chip): the
    # per-token price of the wire hop, reported next to the headline.
    ext_ab = None
    if platform != "tpu" and os.environ.get("BENCH_EXT_AB", "1") != "0":
        try:
            ext_ab = _ext_harness_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            ext_ab = {"error": f"{type(e).__name__}: {e}"}

    # Mixed prefill+decode steps A/B (ISSUE 5): burst-drain ITL p95 with
    # the decode batch riding every prefill dispatch vs XOR scheduling.
    # Runs by default on the CPU fallback (tiny); the chip arm is queued
    # as bench_1b_mixed in tpu_round.sh (BENCH_MIXED_AB=1 forces it on
    # TPU with the headline model).
    mixed_ab = None
    default_mixed = "1" if platform != "tpu" else "0"
    if os.environ.get("BENCH_MIXED_AB", default_mixed) != "0":
        try:
            mixed_ab = _mixed_ab(
                model=os.environ.get(
                    "BENCH_MIXED_MODEL",
                    "tiny" if platform != "tpu" else model,
                ),
                pairs=int(
                    os.environ.get(
                        "BENCH_MIXED_PAIRS",
                        "1",
                    )
                ),
            )
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            mixed_ab = {"error": f"{type(e).__name__}: {e}"}

    # Distributed-tracing on/off A/B (ISSUE 4): tracing must be free when
    # off and near-free when on; the per-request span fan (frontend ->
    # router -> engine -> child) rides the same echo workload.
    trace_ab = None
    if platform != "tpu" and os.environ.get("BENCH_TRACE_AB", "1") != "0":
        try:
            trace_ab = _trace_overhead_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            trace_ab = {"error": f"{type(e).__name__}: {e}"}

    # Fleet-telemetry on/off A/B (ISSUE 6): the SLO sketch + fleet
    # publishing layer must stay under 1% of token throughput.
    slo_ab = None
    if platform != "tpu" and os.environ.get("BENCH_SLO_AB", "1") != "0":
        try:
            slo_ab = _slo_overhead_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            slo_ab = {"error": f"{type(e).__name__}: {e}"}

    # Flight-recorder on/off A/B (ISSUE 7): the per-step record append
    # must stay under 1% of token throughput.
    flight_ab = None
    if platform != "tpu" and os.environ.get("BENCH_FLIGHT_AB", "1") != "0":
        try:
            flight_ab = _flight_overhead_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            flight_ab = {"error": f"{type(e).__name__}: {e}"}

    # Worker-handover A/B (ISSUE 12): warm-handover continuation TTFT vs
    # replay-by-recompute + bytes-moved vs prefill-flops-saved.
    handover_ab = None
    if platform != "tpu" and os.environ.get("BENCH_HANDOVER_AB", "1") != "0":
        try:
            handover_ab = _handover_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            handover_ab = {"error": f"{type(e).__name__}: {e}"}

    # Per-prefix KV migration A/B (ISSUE 18): turn-2 TTFT after
    # migrating only the session's hot prefix chain vs cold prefill,
    # priced by the shared kv_economy CostModel. Runs by default on the
    # CPU fallback; the chip arm is queued as bench_1b_prefixmig in
    # tpu_round.sh (BENCH_PREFIXMIG=1 forces it on TPU).
    prefixmig_ab = None
    default_prefixmig = "1" if platform != "tpu" else "0"
    if os.environ.get("BENCH_PREFIXMIG", default_prefixmig) != "0":
        try:
            prefixmig_ab = _prefix_migration_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            prefixmig_ab = {"error": f"{type(e).__name__}: {e}"}

    # KV index sequencing A/B (ISSUE 13): the sequence stamp + digest
    # fold on the event publish path must stay under 1% of token
    # throughput.
    kv_index_ab = None
    if platform != "tpu" and os.environ.get("BENCH_KV_INDEX_AB", "1") != "0":
        try:
            kv_index_ab = _kv_index_overhead_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            kv_index_ab = {"error": f"{type(e).__name__}: {e}"}

    # Fleet trace plane A/B (ISSUE 14): span shipping + exemplar
    # stamping on a warm engine must stay under 1% of token throughput.
    trace_plane_ab = None
    if platform != "tpu" and os.environ.get(
        "BENCH_TRACE_PLANE_AB", "1"
    ) != "0":
        try:
            trace_plane_ab = _trace_plane_overhead_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            trace_plane_ab = {"error": f"{type(e).__name__}: {e}"}

    # Control-plane failover blackout + replication overhead (ISSUE 15):
    # warm-standby promotion window under a SIGKILL'd primary, and the
    # journal tap's cost on the publish path (<2% modeled).
    failover_ab = None
    if platform != "tpu" and os.environ.get(
        "BENCH_FAILOVER_AB", "1"
    ) != "0":
        try:
            failover_ab = _failover_blackout()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            failover_ab = {"error": f"{type(e).__name__}: {e}"}

    # Draft-model speculative decoding A/B (ISSUE 9): decode tok/s with
    # the fused draft+verify path on vs off at batch <= 8. Runs by
    # default on the CPU fallback (tiny self-draft — acceptance ~1, the
    # upper-bound harness); the chip arm is queued as bench_1b_spec in
    # tpu_round.sh (BENCH_SPEC=1 forces it on TPU with the headline
    # model + llama3-draft — random-init unless BENCH_SPEC_DRAFT points
    # at a distilled draft, so read modeled_at_accept_rate there).
    # Deliberately LAST among the A/Bs: its engine compiles/gc churn
    # must not sit right before the telemetry wall-overhead sanity
    # bands, which are the load-sensitive ones.
    spec_ab = None
    default_spec = "1" if platform != "tpu" else "0"
    if os.environ.get("BENCH_SPEC", default_spec) != "0":
        try:
            spec_ab = _spec_ab(
                model=os.environ.get(
                    "BENCH_SPEC_MODEL",
                    "tiny" if platform != "tpu" else model,
                ),
                draft=os.environ.get(
                    "BENCH_SPEC_DRAFT",
                    None if platform != "tpu" else "llama3-draft",
                ),
                pairs=int(os.environ.get("BENCH_SPEC_PAIRS", "3")),
            )
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            spec_ab = {"error": f"{type(e).__name__}: {e}"}

    # On-device K-step decode window A/B (ISSUE 16): ms/token with the
    # fused decode_kstep window (K tokens per host visit) vs the classic
    # per-token loop. Runs by default on the CPU fallback (tiny); the
    # chip arm is queued as bench_1b_kstep in tpu_round.sh (BENCH_KSTEP
    # sets K and forces it on TPU with the headline model).
    kstep_ab = None
    default_kstep = "8" if platform != "tpu" else "0"
    kstep_k = int(os.environ.get("BENCH_KSTEP", default_kstep))
    if kstep_k > 1:
        try:
            kstep_ab = _kstep_ab(
                model=os.environ.get(
                    "BENCH_KSTEP_MODEL",
                    "tiny" if platform != "tpu" else model,
                ),
                pairs=int(os.environ.get("BENCH_KSTEP_PAIRS", "3")),
                kstep=kstep_k,
            )
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            kstep_ab = {"error": f"{type(e).__name__}: {e}"}

    # Multi-host pipeline A/B (ISSUE 20): the decode pipeline carried
    # across hosts vs the old multi-host auto-off, under the forced
    # multi-host CPU mesh. Runs by default on the CPU fallback (tiny);
    # the chip arm is queued as bench_1b_tp in tpu_round.sh
    # (BENCH_MULTIHOST forces it with the headline model).
    multihost_ab = None
    default_mh = "1" if platform != "tpu" else "0"
    if os.environ.get("BENCH_MULTIHOST", default_mh) != "0":
        try:
            multihost_ab = _multihost_pipeline_ab(
                model=os.environ.get(
                    "BENCH_MULTIHOST_MODEL",
                    "tiny" if platform != "tpu" else model,
                ),
                pairs=int(os.environ.get("BENCH_MULTIHOST_PAIRS", "3")),
                topology=os.environ.get(
                    "BENCH_MULTIHOST_TOPOLOGY", "tp=2,dp=2"
                ),
            )
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            multihost_ab = {"error": f"{type(e).__name__}: {e}"}

    tok_s = best["tok_s"]
    p50_ttft = best["p50_ttft"]
    p50_itl = best["p50_itl"]
    elapsed = best["elapsed"]
    generated = best["generated"]

    # Approximate MFU: decode is ~2*params FLOPs/token; prefill adds
    # 2*params per prompt token (attention FLOPs are second-order at these
    # sequence lengths). Peak resolved per TPU generation.
    from benchmarks.perf import tpu_bf16_peak_flops

    peak = tpu_bf16_peak_flops()
    total_tokens = generated + num_requests * isl
    mfu = (
        (2.0 * n_active * total_tokens / elapsed) / peak
        if peak is not None
        else float("nan")
    )

    # vs_baseline compares like with like: each (platform, model, quantize)
    # config scores against ITS OWN published record — an 8B number divided
    # by the 1B target would read as a regression (round-3 verdict).
    published = {}
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            published = json.load(f).get("published", {})
    except Exception:
        pass
    if platform != "tpu":
        baseline = float(published.get("cpu_output_tok_s", 0.0) or 0.0)
        baseline_workload = published.get("cpu_note", "cpu fallback")
    elif os.environ.get("BENCH_KV_QUANTIZE"):
        # kv-quant chip stages score against their own records, never the
        # fp-page ones (same like-with-like rule as the int8-weights 8B)
        key = f"{model.replace('-', '_').replace('.', '_')}_kv_" + (
            os.environ["BENCH_KV_QUANTIZE"]
        )
        rec = published.get(key, {})
        baseline = float(rec.get("output_tok_s_per_chip", 0.0) or 0.0)
        baseline_workload = rec.get(
            "workload", f"{model} kv {os.environ['BENCH_KV_QUANTIZE']}"
        )
    elif model == "llama3-8b" and os.environ.get("BENCH_QUANTIZE") == "int8":
        rec = published.get("llama3_8b_int8", {})
        baseline = float(rec.get("output_tok_s_per_chip", 0.0) or 0.0)
        baseline_workload = rec.get("workload", "llama3-8b int8")
    elif model == "llama3-1b" and not os.environ.get("BENCH_QUANTIZE"):
        baseline = float(published.get("output_tok_s_per_chip", 0.0) or 0.0)
        baseline_workload = published.get("workload", "llama3-1b")
    else:
        # no published record for this config yet: first measurement is
        # its own baseline
        baseline, baseline_workload = 0.0, f"none published for {model}"
    vs = tok_s / baseline if baseline > 0 else 1.0

    # A CPU fallback is a degraded measurement of a TPU framework: label
    # it in the metric name and carry the newest chip-measured artifact
    # (payload + age) so the round record holds a TPU number either way.
    metric = "output_tok_s_per_chip"
    tpu_latest = None
    kernel_check = None
    disagg_ab = None

    def _stamp(path: str) -> dict:
        mt = os.path.getmtime(path)
        return {
            "age_hours": round((time.time() - mt) / 3600.0, 1),
            "recorded_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(mt)
            ),
        }

    if platform != "tpu":
        metric = "output_tok_s_cpu_fallback"
        art_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "artifacts", "tpu"
        )
        try:
            candidates = [
                os.path.join(art_dir, f)
                for f in os.listdir(art_dir)
                if f.startswith("bench_") and f.endswith(".json")
            ]
            candidates = [p for p in candidates if os.path.getsize(p) > 0]
            # prefer the headline config's artifact; fall back to newest
            headline = os.path.join(art_dir, "bench_1b.json")
            newest = (
                headline
                if headline in candidates
                else max(candidates, key=os.path.getmtime)
            )
            with open(newest) as f:
                payload = json.load(f)
            tpu_latest = {
                "file": os.path.basename(newest),
                **_stamp(newest),
                "payload": payload,
            }
        except (OSError, ValueError):
            tpu_latest = None
        # also carry the freshest on-chip kernel numerics proof (its own
        # extras key — latest_tpu_artifact keeps its file/payload/age
        # shape) — it can be newer than any bench artifact when a tunnel
        # wedge cut a round's queue short after the kernel stage
        try:
            kp = os.path.join(art_dir, "pallas_check.json")
            with open(kp) as f:
                kdoc = json.load(f)
            if kdoc.get("platform") == "tpu":
                kernel_check = {
                    "all_ok": kdoc.get("all_ok"),
                    **_stamp(kp),
                }
        except (OSError, ValueError):
            pass
        # the round's headline A/B (disagg vs agg on chip) rides along
        # too — it is the reference's own north-star comparison. Same
        # provenance rule as kernel_check: only chip-declared artifacts.
        try:
            ap = os.path.join(art_dir, "disagg_ab.json")
            with open(ap) as f:
                adoc = json.load(f)
            if (
                adoc.get("platform") == "tpu"
                and "disagg_throughput_ratio" in adoc
            ):
                disagg_ab = {
                    "disagg_throughput_ratio": adoc[
                        "disagg_throughput_ratio"
                    ],
                    "disagg_ttft_ratio": adoc.get("disagg_ttft_ratio"),
                    **_stamp(ap),
                }
        except (OSError, ValueError):
            pass

    emit(
        {
            "metric": metric,
            "value": round(tok_s, 2),
            "unit": "tok/s",
            "vs_baseline": round(vs, 3),
            "extras": {
                "platform": platform,
                "model": model,
                "params": n_params,
                "num_requests": num_requests,
                "isl": isl,
                "osl": osl,
                "p50_ttft_s": round(p50_ttft, 4),
                "p50_itl_s": round(p50_itl, 5) if p50_itl == p50_itl else None,
                "mfu": round(mfu, 4) if mfu == mfu else None,
                "elapsed_s": round(elapsed, 2),
                "generated_tokens": generated,
                # decode phase split of the headline run (docs/engine.md
                # "The decode loop"): sync ≈ 0 means the overlapped
                # pipeline has taken the host readback off the critical
                # path
                "decode_dispatch_ms": best["decode_phases"][
                    "time_decode_dispatch_ms"
                ],
                "decode_sync_ms": best["decode_phases"][
                    "time_decode_sync_ms"
                ],
                "decode_host_ms": best["decode_phases"][
                    "time_decode_host_ms"
                ],
                "overlap_hits": best["decode_phases"]["overlap_hits"],
                "overlap_rollbacks": best["decode_phases"][
                    "overlap_rollbacks"
                ],
                **({"overlap_ab": overlap_ab} if overlap_ab else {}),
                **({"mixed_ab": mixed_ab} if mixed_ab else {}),
                **({"spec_ab": spec_ab} if spec_ab else {}),
                **({"kstep_ab": kstep_ab} if kstep_ab else {}),
                **(
                    {"multihost_pipeline_ab": multihost_ab}
                    if multihost_ab
                    else {}
                ),
                **({"kvquant_ab": kvquant_ab} if kvquant_ab else {}),
                **({"ext_harness_ab": ext_ab} if ext_ab else {}),
                **({"trace_overhead": trace_ab} if trace_ab else {}),
                **({"slo_overhead": slo_ab} if slo_ab else {}),
                **({"flight_overhead": flight_ab} if flight_ab else {}),
                **({"handover_ab": handover_ab} if handover_ab else {}),
                **(
                    {"prefix_migration_ab": prefixmig_ab}
                    if prefixmig_ab
                    else {}
                ),
                **(
                    {"kv_index_overhead": kv_index_ab} if kv_index_ab else {}
                ),
                **(
                    {"trace_plane_overhead": trace_plane_ab}
                    if trace_plane_ab
                    else {}
                ),
                **(
                    {"failover_blackout": failover_ab}
                    if failover_ab
                    else {}
                ),
                **(
                    {"kv_quantize": os.environ["BENCH_KV_QUANTIZE"]}
                    if os.environ.get("BENCH_KV_QUANTIZE")
                    else {}
                ),
                "baseline_workload": baseline_workload,
                **({"latest_tpu_artifact": tpu_latest} if tpu_latest else {}),
                **({"kernel_check": kernel_check} if kernel_check else {}),
                **({"disagg_ab_chip": disagg_ab} if disagg_ab else {}),
                "attention_impl": best_impl,
                "attention_impls": {
                    k: {
                        "tok_s": round(v["tok_s"], 2),
                        "p50_ttft_s": round(v["p50_ttft"], 4),
                        "p50_itl_s": (
                            round(v["p50_itl"], 5)
                            if v["p50_itl"] == v["p50_itl"]
                            else None
                        ),
                    }
                    for k, v in per_impl.items()
                },
            },
        }
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # last resort: structured artifact, not a traceback
        emit(
            {
                "metric": "output_tok_s_per_chip",
                "value": 0.0,
                "unit": "tok/s",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}",
            }
        )
        sys.exit(1)

"""Benchmark: serving throughput of the JaxEngine on one TPU chip.

Workload (genai-perf-inspired, scaled to one chip — BASELINE.md): N
concurrent requests, random prompts, fixed output length, continuous
batching with paged KV + prefix caching off (worst case). Reports output
tokens/sec/chip, p50 TTFT, p50 ITL, and approximate MFU.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...,
   "extras": {...}}

Robustness contract (the axon TPU tunnel is known to wedge): the backend is
probed in a SUBPROCESS with a timeout before any in-process jax import
commits to a platform. On probe failure the bench retries, then falls back
to CPU with extras.platform="cpu" (vs_baseline compared against the CPU
record, not the TPU one). Any unexpected crash still emits one structured
JSON line instead of a bare traceback.

vs_baseline compares against `published.output_tok_s_per_chip` (TPU) or
`published.cpu_output_tok_s` (CPU fallback) in BASELINE.json; 1.0 until a
prior round has published.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

PROBE_SRC = "import jax; d=jax.devices(); print(d[0].platform)"


def probe_backend(retries: int = 5, timeout_s: int = 120) -> str:
    """Return the usable platform ('tpu' or 'cpu') via subprocess probes.

    A wedged tunnel hangs rather than erroring, so the probe must be a
    killable child process — never the bench process itself. Patience
    matters: this bench is the round's headline TPU artifact, and a CPU
    fallback caused by a TRANSIENT wedge wastes the whole round's
    hardware evidence (round 2 post-mortem) — so by default we probe for
    ~12 min (5 x 120s probe + 30s gaps) before giving up."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if want == "cpu":
        return "cpu"
    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", PROBE_SRC],
                capture_output=True, text=True, timeout=timeout_s,
                env=dict(os.environ),
            )
            if out.returncode == 0:
                plat = out.stdout.strip().splitlines()[-1].strip().lower()
                return "tpu" if plat not in ("cpu",) else "cpu"
        except subprocess.TimeoutExpired:
            pass
        if attempt < retries - 1:
            time.sleep(30)
    return "cpu"


def emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _make_echo_driver(num_requests: int, tokens: int):
    """`drive(engine, tag) -> (tokens, seconds)`: the shared concurrent
    echo workload of the harness/tracing A/Bs."""
    import asyncio

    from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
    from dynamo_tpu.runtime.context import Context

    prompt = list(range(1, tokens + 1))

    async def drive(engine, tag):
        async def one(i):
            req = PreprocessedRequest(
                request_id=f"{tag}{i}", token_ids=prompt, max_tokens=tokens
            )
            n = 0
            ctx = Context(request_id=req.request_id)
            async for item in engine.generate(ctx, req):
                n += len(item["token_ids"])
            return n

        t0 = time.time()
        counts = await asyncio.gather(*[one(i) for i in range(num_requests)])
        return sum(counts), time.time() - t0

    return drive


def _ext_harness_ab(num_requests: int = 8, tokens: int = 64) -> dict:
    """Per-token overhead of the subprocess external-engine harness: the
    SAME echo workload through an in-process EchoEngine vs the torch-free
    reference worker behind the wire protocol (spawn + frames + msgpack +
    checksums). The delta prices the isolation boundary a foreign engine
    pays per token (docs/external_engines.md 'Level 2')."""
    import asyncio

    from dynamo_tpu.engine.async_engine import EchoEngine
    from dynamo_tpu.external.client import SubprocessEngine

    drive = _make_echo_driver(num_requests, tokens)

    async def run():
        n_in, t_in = await drive(EchoEngine(), "warm-in")
        n_in, t_in = await drive(EchoEngine(), "in")
        ext = SubprocessEngine(
            [sys.executable, "-m", "dynamo_tpu.external.reference_worker",
             "--model", "bench-ext", "--metrics-interval", "60"],
            name="bench-ext",
        )
        await ext.start()
        try:
            await drive(ext, "warm-ext")
            n_ext, t_ext = await drive(ext, "ext")
        finally:
            await ext.stop()
        return {
            "requests": num_requests,
            "tokens_per_arm": n_in,
            "inproc_tok_s": round(n_in / t_in, 1) if t_in else None,
            "subprocess_tok_s": round(n_ext / t_ext, 1) if t_ext else None,
            "wire_overhead_us_per_token": round(
                (t_ext / n_ext - t_in / n_in) * 1e6, 2
            ),
        }

    return asyncio.run(run())


def _trace_overhead_ab(num_requests: int = 8, tokens: int = 64) -> dict:
    """Distributed-tracing overhead A/B (ISSUE 4 acceptance): the SAME
    echo workload through the subprocess harness — where every traced hop
    fires (engine span, trace context on the generate frame, child span
    shipped back as a `span` frame) — with tracing off vs on.

    This box's background load swings short echo runs by tens of percent
    — far above the span layer's true cost — so the empirical A/B runs
    INTERLEAVED (alternating-order off/on pairs, median per-pair ratio:
    a slow window hits both arms and cancels) and is reported as a
    sanity band, while the <3% claim is pinned by `modeled_overhead_pct`:
    a deterministic microbench of the per-request span work (parent span
    + event + adopted child span) divided by the measured per-request
    serving time. The model is conservative — it charges the whole span
    fan to the critical path."""
    import asyncio
    import statistics

    from dynamo_tpu import telemetry
    from dynamo_tpu.external.client import SubprocessEngine

    drive = _make_echo_driver(num_requests, tokens)

    def span_layer_us_per_request(iters: int = 4000) -> float:
        """Deterministic cost of one traced request's span work in THIS
        process: the engine span contextmanager, a first_token event, and
        adopting the child's shipped span into the ring."""
        telemetry.configure(enabled=True, ring_size=8)
        child = {
            "trace_id": "0" * 32, "span_id": "1" * 16,
            "parent_id": None, "name": "child.generate",
            "service": "ext-child", "start_ts": 0.0, "duration_ms": 1.0,
            "status": "ok", "attrs": {}, "events": [],
        }
        t0 = time.perf_counter()
        for _ in range(iters):
            with telemetry.span(
                "engine.generate", service="engine",
                attrs={"request_id": "bench"},
            ) as sp:
                sp.add_event("first_token")
                child["trace_id"] = sp.trace_id
                telemetry.record_span_dict(dict(child))
        us = (time.perf_counter() - t0) / iters * 1e6
        telemetry.configure(enabled=False)
        return us

    async def run(pairs: int = 6):
        ext = SubprocessEngine(
            [sys.executable, "-m", "dynamo_tpu.external.reference_worker",
             "--model", "bench-trace", "--metrics-interval", "60"],
            name="bench-trace",
        )
        await ext.start()
        ratios = []
        offs, ons = [], []
        try:
            await drive(ext, "warm-trace")
            for rep in range(pairs):
                arms = [(False, "off"), (True, "on")]
                if rep % 2:
                    arms.reverse()  # cancel any first-arm bias
                rate = {}
                for on, tag in arms:
                    telemetry.configure(
                        enabled=on, ring_size=64 if on else None
                    )
                    n, t = await drive(ext, f"{tag}-{rep}-")
                    rate[tag] = n / t if t else 0.0
                if rate["off"] and rate["on"]:
                    ratios.append(rate["on"] / rate["off"])
                    offs.append(rate["off"])
                    ons.append(rate["on"])
        finally:
            telemetry.configure(enabled=False)
            await ext.stop()
        ratio = statistics.median(ratios) if ratios else None
        off_med = statistics.median(offs) if offs else None
        span_us = span_layer_us_per_request()
        modeled = None
        if off_med:
            request_us = tokens / off_med * 1e6  # wall us per request
            modeled = round(span_us / request_us * 100.0, 3)
        return {
            "requests": num_requests,
            "pairs": len(ratios),
            "trace_off_tok_s": round(off_med, 1) if off_med else None,
            "trace_on_tok_s": (
                round(statistics.median(ons), 1) if ons else None
            ),
            "measured_overhead_pct": (
                round((1.0 - ratio) * 100.0, 2) if ratio else None
            ),
            "span_layer_us_per_request": round(span_us, 2),
            "modeled_overhead_pct": modeled,
        }

    return asyncio.run(run())


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    platform = probe_backend(
        retries=int(os.environ.get("BENCH_PROBE_RETRIES", "3"))
    )
    if platform == "cpu":
        # Commit the fallback before jax initializes in-process. The env var
        # alone is ineffective once sitecustomize has run — re-apply via
        # jax.config (backends init lazily, so this sticks).
        os.environ["JAX_PLATFORMS"] = "cpu"
        honor_jax_platforms_env()

    if platform == "cpu":
        # One CPU core cannot run the TPU workload (llama3-1b x 128
        # requests would take hours); fall back to a CPU-feasible
        # configuration and say so in extras. vs_baseline compares against
        # the CPU record (cpu_output_tok_s), never the TPU one.
        model = os.environ.get("BENCH_MODEL", "tiny")
        num_requests = int(os.environ.get("BENCH_REQUESTS", "16"))
        isl = int(os.environ.get("BENCH_ISL", "64"))
        osl = int(os.environ.get("BENCH_OSL", "32"))
    else:
        model = os.environ.get("BENCH_MODEL", "llama3-1b")
        num_requests = int(os.environ.get("BENCH_REQUESTS", "128"))
        isl = int(os.environ.get("BENCH_ISL", "128"))
        osl = int(os.environ.get("BENCH_OSL", "64"))

    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    chunk = -(-max(128, isl) // 64) * 64  # page-aligned prefill chunk
    # One wave: every request resident at once (weights amortize across
    # the whole batch), pages sized for prompt+output per sequence.
    pages_per_seq = -(-(isl + osl + 1) // 64)

    def make_engine(
        attention_impl: str,
        overlap: bool = True,
        decode_steps: int = None,
        kv_quantize: str = "env",
    ) -> JaxEngine:
        if kv_quantize == "env":
            # chip stage: BENCH_KV_QUANTIZE=int8 runs the headline with
            # quantized pages (queued as a tpu_round.sh A/B stage)
            kv_quantize = os.environ.get("BENCH_KV_QUANTIZE") or None
        cfg = EngineConfig(
            model=model,
            num_pages=max(512, num_requests * (pages_per_seq + 1)),
            page_size=64,
            max_pages_per_seq=max(16, pages_per_seq + 1),
            # Buckets up to and INCLUDING one that fits the whole batch, so
            # decode really runs as one wave (the scheduler caps batches at
            # decode_buckets[-1]).
            decode_buckets=tuple(
                b for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
                if b < num_requests
            ) + (num_requests,),
            prefill_chunk=chunk,
            # Whole-workload dispatches: all prompts prefill in one batched
            # program; decode fuses K steps per host sync (the TPU sits
            # behind a ~65ms tunnel round-trip, so syncs dominate
            # unamortized).
            prefill_token_budget=num_requests * chunk,
            decode_steps=(
                decode_steps
                if decode_steps is not None
                else int(os.environ.get("BENCH_DECODE_STEPS", "64"))
            ),
            max_seqs=max(32, num_requests),
            dtype="bfloat16",
            enable_prefix_caching=False,
            # llama3-8b bf16 (16GB) exceeds a v5e chip's HBM; int8
            # weight-only (BENCH_QUANTIZE=int8) fits it alongside the KV
            # pages.
            quantize=os.environ.get("BENCH_QUANTIZE") or None,
            kv_quantize=kv_quantize,
            attention_impl=attention_impl,
            overlap_decode=overlap,
        )
        return JaxEngine(cfg)

    # Serving-config sweep: the pallas page-walk decode is latency-optimal
    # at small batch but issues O(B x pages) DMA descriptors per layer;
    # "hybrid" gates large decode buckets onto the XLA gather. The bench
    # measures both on TPU and reports the BEST (per-impl numbers in
    # extras) — picking a serving config is legitimate tuning, hiding the
    # loser would not be.
    default_impls = "auto,hybrid" if platform == "tpu" else "auto"
    impls = [
        i.strip()
        for i in os.environ.get("BENCH_ATTENTION", default_impls).split(",")
        if i.strip()
    ]

    eng = make_engine(impls[0])

    import jax

    n_params = sum(int(x.size) for x in jax.tree.leaves(eng.params))
    # MoE: FLOPs/token follow the ACTIVE parameters (top_k of E experts),
    # not the resident total — MFU from total params would overstate ~8x
    # for deepseek-v2-lite. Routed expert leaves are named we_*.
    acfg = eng.adapter.config
    n_experts = getattr(acfg, "n_routed_experts", 0) or getattr(
        acfg, "num_experts", 0
    )
    top_k = getattr(acfg, "num_experts_per_tok", None) or getattr(
        acfg, "top_k", 0
    )
    n_active = n_params
    if n_experts and top_k:
        expert_elems = sum(
            int(leaf.size)
            for path, leaf in jax.tree_util.tree_leaves_with_path(eng.params)
            if any(
                getattr(k, "key", "").startswith("we_")
                and not getattr(k, "key", "").endswith("_scale")
                for k in path
            )
        )
        n_active = n_params - expert_elems + expert_elems * top_k // n_experts

    rng = np.random.default_rng(0)
    prompts = [
        [int(x) for x in rng.integers(1, 32000, isl)] for _ in range(num_requests)
    ]

    def run_timed(eng) -> dict:
        # Warmup with the SAME workload (all requests, same osl) so every
        # decode bucket, fused-step count, and prefill program the timed
        # run uses is compiled before the timer starts — otherwise tok/s
        # and TTFT measure XLA (the fused decode K adapts to remaining
        # max_tokens, so a short warmup osl would compile the wrong K).
        for i, p in enumerate(prompts):
            eng.add_request(
                f"warm{i}", p,
                SamplingParams(temperature=0.0, max_tokens=osl),
            )
        eng.run_to_completion()
        eng.allocator.clear_cache()

        # decode phase split (dispatch/sync/postprocess + overlap
        # counters) is reported as deltas over the TIMED section only
        phase0 = {
            k: getattr(eng.metrics, k)
            for k in (
                "time_decode_dispatch_ms", "time_decode_sync_ms",
                "time_decode_host_ms", "overlap_dispatches",
                "overlap_hits", "overlap_rollbacks",
            )
        }
        t0 = time.time()
        submit = {}
        first_token = {}
        last_token = {}
        tokens_of = {}
        for i, p in enumerate(prompts):
            rid = f"r{i}"
            submit[rid] = time.time()
            eng.add_request(
                rid, p, SamplingParams(temperature=0.0, max_tokens=osl)
            )
        generated = 0
        while eng.has_work:
            for out in eng.step():
                now = time.time()
                generated += len(out.new_token_ids)
                tokens_of[out.request_id] = tokens_of.get(
                    out.request_id, 0
                ) + len(out.new_token_ids)
                if out.is_first and out.request_id not in first_token:
                    first_token[out.request_id] = now
                last_token[out.request_id] = now
        elapsed = time.time() - t0
        ttfts = sorted(first_token[r] - submit[r] for r in first_token)
        itls = sorted(
            (last_token[r] - first_token[r]) / (tokens_of[r] - 1)
            for r in first_token
            if tokens_of.get(r, 0) > 1
        )
        return {
            "tok_s": generated / elapsed,
            "p50_ttft": ttfts[len(ttfts) // 2] if ttfts else float("nan"),
            "p50_itl": itls[len(itls) // 2] if itls else float("nan"),
            "elapsed": elapsed,
            "generated": generated,
            "decode_phases": {
                k: round(getattr(eng.metrics, k) - v, 2)
                for k, v in phase0.items()
            },
        }

    per_impl = {impls[0]: run_timed(eng)}
    for impl in impls[1:]:
        import gc

        del eng
        gc.collect()
        eng = make_engine(impl)
        per_impl[impl] = run_timed(eng)
    best_impl = max(per_impl, key=lambda k: per_impl[k]["tok_s"])
    best = per_impl[best_impl]

    # Overlap on/off A/B (CPU fallback only): the overlapped decode
    # loop's win lives where per-step syncs dominate, so the A/B runs
    # the same workload at decode_steps=1 (classic stepping) with
    # overlap_decode on vs off — BENCH_r06 carries the evidence even
    # when the TPU tunnel is down. The TPU headline number already runs
    # with overlap on (fused K amortizes most of what's left).
    overlap_ab = None
    if platform != "tpu" and os.environ.get("BENCH_OVERLAP_AB", "1") != "0":
        import gc

        ab_steps = int(os.environ.get("BENCH_OVERLAP_AB_STEPS", "1"))
        overlap_ab = {"decode_steps": ab_steps}
        for tag, ov in (("overlap_on", True), ("overlap_off", False)):
            del eng
            gc.collect()
            eng = make_engine(best_impl, overlap=ov, decode_steps=ab_steps)
            r = run_timed(eng)
            ph = r["decode_phases"]
            overlap_ab[tag] = {
                "tok_s": round(r["tok_s"], 2),
                "decode_dispatch_ms": ph["time_decode_dispatch_ms"],
                "decode_sync_ms": ph["time_decode_sync_ms"],
                "decode_host_ms": ph["time_decode_host_ms"],
            }
        off_tok_s = overlap_ab["overlap_off"]["tok_s"]
        overlap_ab["speedup"] = (
            round(overlap_ab["overlap_on"]["tok_s"] / off_tok_s, 3)
            if off_tok_s
            else None
        )

    # KV-quant on/off A/B (CPU fallback now; the chip stage is queued in
    # tpu_round.sh as bench_1b_kvq for BENCH_r06): same workload with
    # int8 pages vs model-dtype pages, plus the pool-byte gauges so the
    # ~2x effective-capacity claim rides the record next to the tok/s.
    kvquant_ab = None
    if platform != "tpu" and os.environ.get("BENCH_KVQUANT_AB", "1") != "0":
        import gc

        kvquant_ab = {}
        for tag, kvq in (("kv_fp", None), ("kv_int8", "int8")):
            del eng
            gc.collect()
            eng = make_engine(best_impl, kv_quantize=kvq)
            r = run_timed(eng)
            kvquant_ab[tag] = {
                "tok_s": round(r["tok_s"], 2),
                "kv_pool_bytes": eng.metrics.kv_pool_bytes,
                "kv_pool_bytes_dense_equiv": (
                    eng.metrics.kv_pool_bytes_dense_equiv
                ),
            }
        fp_tok_s = kvquant_ab["kv_fp"]["tok_s"]
        kvquant_ab["speedup"] = (
            round(kvquant_ab["kv_int8"]["tok_s"] / fp_tok_s, 3)
            if fp_tok_s
            else None
        )
        kvquant_ab["capacity_ratio"] = round(
            kvquant_ab["kv_int8"]["kv_pool_bytes_dense_equiv"]
            / max(kvquant_ab["kv_int8"]["kv_pool_bytes"], 1),
            3,
        )

    # Subprocess external-engine harness A/B (CPU only: the harness is
    # engine-agnostic plumbing; its cost doesn't depend on the chip): the
    # per-token price of the wire hop, reported next to the headline.
    ext_ab = None
    if platform != "tpu" and os.environ.get("BENCH_EXT_AB", "1") != "0":
        try:
            ext_ab = _ext_harness_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            ext_ab = {"error": f"{type(e).__name__}: {e}"}

    # Distributed-tracing on/off A/B (ISSUE 4): tracing must be free when
    # off and near-free when on; the per-request span fan (frontend ->
    # router -> engine -> child) rides the same echo workload.
    trace_ab = None
    if platform != "tpu" and os.environ.get("BENCH_TRACE_AB", "1") != "0":
        try:
            trace_ab = _trace_overhead_ab()
        except Exception as e:  # noqa: BLE001 — A/B failure must not kill
            # the headline artifact
            trace_ab = {"error": f"{type(e).__name__}: {e}"}

    tok_s = best["tok_s"]
    p50_ttft = best["p50_ttft"]
    p50_itl = best["p50_itl"]
    elapsed = best["elapsed"]
    generated = best["generated"]

    # Approximate MFU: decode is ~2*params FLOPs/token; prefill adds
    # 2*params per prompt token (attention FLOPs are second-order at these
    # sequence lengths). Peak resolved per TPU generation.
    from benchmarks.perf import tpu_bf16_peak_flops

    peak = tpu_bf16_peak_flops()
    total_tokens = generated + num_requests * isl
    mfu = (
        (2.0 * n_active * total_tokens / elapsed) / peak
        if peak is not None
        else float("nan")
    )

    # vs_baseline compares like with like: each (platform, model, quantize)
    # config scores against ITS OWN published record — an 8B number divided
    # by the 1B target would read as a regression (round-3 verdict).
    published = {}
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            published = json.load(f).get("published", {})
    except Exception:
        pass
    if platform != "tpu":
        baseline = float(published.get("cpu_output_tok_s", 0.0) or 0.0)
        baseline_workload = published.get("cpu_note", "cpu fallback")
    elif os.environ.get("BENCH_KV_QUANTIZE"):
        # kv-quant chip stages score against their own records, never the
        # fp-page ones (same like-with-like rule as the int8-weights 8B)
        key = f"{model.replace('-', '_').replace('.', '_')}_kv_" + (
            os.environ["BENCH_KV_QUANTIZE"]
        )
        rec = published.get(key, {})
        baseline = float(rec.get("output_tok_s_per_chip", 0.0) or 0.0)
        baseline_workload = rec.get(
            "workload", f"{model} kv {os.environ['BENCH_KV_QUANTIZE']}"
        )
    elif model == "llama3-8b" and os.environ.get("BENCH_QUANTIZE") == "int8":
        rec = published.get("llama3_8b_int8", {})
        baseline = float(rec.get("output_tok_s_per_chip", 0.0) or 0.0)
        baseline_workload = rec.get("workload", "llama3-8b int8")
    elif model == "llama3-1b" and not os.environ.get("BENCH_QUANTIZE"):
        baseline = float(published.get("output_tok_s_per_chip", 0.0) or 0.0)
        baseline_workload = published.get("workload", "llama3-1b")
    else:
        # no published record for this config yet: first measurement is
        # its own baseline
        baseline, baseline_workload = 0.0, f"none published for {model}"
    vs = tok_s / baseline if baseline > 0 else 1.0

    # A CPU fallback is a degraded measurement of a TPU framework: label
    # it in the metric name and carry the newest chip-measured artifact
    # (payload + age) so the round record holds a TPU number either way.
    metric = "output_tok_s_per_chip"
    tpu_latest = None
    kernel_check = None
    disagg_ab = None

    def _stamp(path: str) -> dict:
        mt = os.path.getmtime(path)
        return {
            "age_hours": round((time.time() - mt) / 3600.0, 1),
            "recorded_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(mt)
            ),
        }

    if platform != "tpu":
        metric = "output_tok_s_cpu_fallback"
        art_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "artifacts", "tpu"
        )
        try:
            candidates = [
                os.path.join(art_dir, f)
                for f in os.listdir(art_dir)
                if f.startswith("bench_") and f.endswith(".json")
            ]
            candidates = [p for p in candidates if os.path.getsize(p) > 0]
            # prefer the headline config's artifact; fall back to newest
            headline = os.path.join(art_dir, "bench_1b.json")
            newest = (
                headline
                if headline in candidates
                else max(candidates, key=os.path.getmtime)
            )
            with open(newest) as f:
                payload = json.load(f)
            tpu_latest = {
                "file": os.path.basename(newest),
                **_stamp(newest),
                "payload": payload,
            }
        except (OSError, ValueError):
            tpu_latest = None
        # also carry the freshest on-chip kernel numerics proof (its own
        # extras key — latest_tpu_artifact keeps its file/payload/age
        # shape) — it can be newer than any bench artifact when a tunnel
        # wedge cut a round's queue short after the kernel stage
        try:
            kp = os.path.join(art_dir, "pallas_check.json")
            with open(kp) as f:
                kdoc = json.load(f)
            if kdoc.get("platform") == "tpu":
                kernel_check = {
                    "all_ok": kdoc.get("all_ok"),
                    **_stamp(kp),
                }
        except (OSError, ValueError):
            pass
        # the round's headline A/B (disagg vs agg on chip) rides along
        # too — it is the reference's own north-star comparison. Same
        # provenance rule as kernel_check: only chip-declared artifacts.
        try:
            ap = os.path.join(art_dir, "disagg_ab.json")
            with open(ap) as f:
                adoc = json.load(f)
            if (
                adoc.get("platform") == "tpu"
                and "disagg_throughput_ratio" in adoc
            ):
                disagg_ab = {
                    "disagg_throughput_ratio": adoc[
                        "disagg_throughput_ratio"
                    ],
                    "disagg_ttft_ratio": adoc.get("disagg_ttft_ratio"),
                    **_stamp(ap),
                }
        except (OSError, ValueError):
            pass

    emit(
        {
            "metric": metric,
            "value": round(tok_s, 2),
            "unit": "tok/s",
            "vs_baseline": round(vs, 3),
            "extras": {
                "platform": platform,
                "model": model,
                "params": n_params,
                "num_requests": num_requests,
                "isl": isl,
                "osl": osl,
                "p50_ttft_s": round(p50_ttft, 4),
                "p50_itl_s": round(p50_itl, 5) if p50_itl == p50_itl else None,
                "mfu": round(mfu, 4) if mfu == mfu else None,
                "elapsed_s": round(elapsed, 2),
                "generated_tokens": generated,
                # decode phase split of the headline run (docs/engine.md
                # "The decode loop"): sync ≈ 0 means the overlapped
                # pipeline has taken the host readback off the critical
                # path
                "decode_dispatch_ms": best["decode_phases"][
                    "time_decode_dispatch_ms"
                ],
                "decode_sync_ms": best["decode_phases"][
                    "time_decode_sync_ms"
                ],
                "decode_host_ms": best["decode_phases"][
                    "time_decode_host_ms"
                ],
                "overlap_hits": best["decode_phases"]["overlap_hits"],
                "overlap_rollbacks": best["decode_phases"][
                    "overlap_rollbacks"
                ],
                **({"overlap_ab": overlap_ab} if overlap_ab else {}),
                **({"kvquant_ab": kvquant_ab} if kvquant_ab else {}),
                **({"ext_harness_ab": ext_ab} if ext_ab else {}),
                **({"trace_overhead": trace_ab} if trace_ab else {}),
                **(
                    {"kv_quantize": os.environ["BENCH_KV_QUANTIZE"]}
                    if os.environ.get("BENCH_KV_QUANTIZE")
                    else {}
                ),
                "baseline_workload": baseline_workload,
                **({"latest_tpu_artifact": tpu_latest} if tpu_latest else {}),
                **({"kernel_check": kernel_check} if kernel_check else {}),
                **({"disagg_ab_chip": disagg_ab} if disagg_ab else {}),
                "attention_impl": best_impl,
                "attention_impls": {
                    k: {
                        "tok_s": round(v["tok_s"], 2),
                        "p50_ttft_s": round(v["p50_ttft"], 4),
                        "p50_itl_s": (
                            round(v["p50_itl"], 5)
                            if v["p50_itl"] == v["p50_itl"]
                            else None
                        ),
                    }
                    for k, v in per_impl.items()
                },
            },
        }
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # last resort: structured artifact, not a traceback
        emit(
            {
                "metric": "output_tok_s_per_chip",
                "value": 0.0,
                "unit": "tok/s",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}",
            }
        )
        sys.exit(1)

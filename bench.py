"""Benchmark: serving throughput of the JaxEngine on one TPU chip.

Workload (genai-perf-inspired, scaled to one chip — BASELINE.md): N
concurrent requests, random prompts, fixed output length, continuous
batching with paged KV + prefix caching off (worst case). Reports output
tokens/sec/chip and p50 TTFT.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...,
   "extras": {...}}

vs_baseline compares against `published.output_tok_s_per_chip` in
BASELINE.json when present (rounds record their numbers there); 1.0 until a
prior round has published.
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    model = os.environ.get("BENCH_MODEL", "llama3-1b")
    num_requests = int(os.environ.get("BENCH_REQUESTS", "128"))
    isl = int(os.environ.get("BENCH_ISL", "128"))
    osl = int(os.environ.get("BENCH_OSL", "64"))

    import numpy as np

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    chunk = -(-max(128, isl) // 64) * 64  # page-aligned prefill chunk
    # One wave: every request resident at once (weights amortize across
    # the whole batch), pages sized for prompt+output per sequence.
    pages_per_seq = -(-(isl + osl + 1) // 64)
    cfg = EngineConfig(
        model=model,
        num_pages=max(512, num_requests * (pages_per_seq + 1)),
        page_size=64,
        max_pages_per_seq=max(16, pages_per_seq + 1),
        # Buckets up to and INCLUDING one that fits the whole batch, so
        # decode really runs as one wave (the scheduler caps batches at
        # decode_buckets[-1]).
        decode_buckets=tuple(
            b for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
            if b < num_requests
        ) + (num_requests,),
        prefill_chunk=chunk,
        # Whole-workload dispatches: all prompts prefill in one batched
        # program; decode fuses K steps per host sync (the TPU sits behind
        # a ~65ms tunnel round-trip, so syncs dominate unamortized).
        prefill_token_budget=num_requests * chunk,
        decode_steps=int(os.environ.get("BENCH_DECODE_STEPS", "64")),
        max_seqs=max(32, num_requests),
        dtype="bfloat16",
        enable_prefix_caching=False,
    )
    eng = JaxEngine(cfg)

    rng = np.random.default_rng(0)
    prompts = [
        [int(x) for x in rng.integers(1, 32000, isl)] for _ in range(num_requests)
    ]

    # Warmup with the SAME workload (all requests, same osl) so every
    # decode bucket, fused-step count, and prefill program the timed run
    # uses is compiled before the timer starts — otherwise tok/s and TTFT
    # measure XLA (the fused decode K adapts to remaining max_tokens, so a
    # short warmup osl would compile the wrong K).
    for i, p in enumerate(prompts):
        eng.add_request(f"warm{i}", p, SamplingParams(temperature=0.0, max_tokens=osl))
    eng.run_to_completion()
    eng.allocator.clear_cache()

    t0 = time.time()
    submit = {}
    first_token = {}
    for i, p in enumerate(prompts):
        rid = f"r{i}"
        submit[rid] = time.time()
        eng.add_request(rid, p, SamplingParams(temperature=0.0, max_tokens=osl))
    generated = 0
    while eng.has_work:
        for out in eng.step():
            generated += len(out.new_token_ids)
            if out.is_first and out.request_id not in first_token:
                first_token[out.request_id] = time.time()
    elapsed = time.time() - t0

    ttfts = sorted(first_token[r] - submit[r] for r in first_token)
    p50_ttft = ttfts[len(ttfts) // 2] if ttfts else float("nan")
    tok_s = generated / elapsed

    baseline = 0.0
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = float(
                json.load(f).get("published", {}).get("output_tok_s_per_chip", 0.0)
            )
    except Exception:
        pass
    vs = tok_s / baseline if baseline > 0 else 1.0

    print(
        json.dumps(
            {
                "metric": "output_tok_s_per_chip",
                "value": round(tok_s, 2),
                "unit": "tok/s",
                "vs_baseline": round(vs, 3),
                "extras": {
                    "model": model,
                    "num_requests": num_requests,
                    "isl": isl,
                    "osl": osl,
                    "p50_ttft_s": round(p50_ttft, 4),
                    "elapsed_s": round(elapsed, 2),
                    "generated_tokens": generated,
                },
            }
        )
    )


if __name__ == "__main__":
    main()

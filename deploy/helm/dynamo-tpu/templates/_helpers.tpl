{{- define "dynamo.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end -}}

{{- define "dynamo.fabricAddr" -}}
{{ .Release.Name }}-fabric:{{ .Values.fabric.port }}
{{- end -}}

"""The fabric interface: control-plane KV + leases + watches, pub/sub
events, durable work queues, and an object store.

One abstraction covers what the reference splits across four transports
(etcd for discovery/lease/watch, NATS core for events, JetStream for the
prefill queue + object store — SURVEY.md L0). Implementations:
LocalFabric (in-process, zero infra — the mem.rs pattern) and RemoteFabric
(TCP client to a FabricServer).

Design rule kept from the reference (§5.8): small control messages ride the
fabric; bulk bytes (token streams, KV pages) ride dedicated direct TCP
planes (runtime/ingress.py, disagg/transfer.py).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, Optional, Protocol

from dynamo_tpu.runtime.store import Watch


@dataclass(frozen=True)
class BusMessage:
    subject: str
    header: Any
    payload: bytes
    #: broker publish sequence for ring-retained subjects (JetStream-style
    #: replay cursor — see local.py's per-subject replay ring); 0 for
    #: subjects outside the ring set (no resume semantics)
    seq: int = 0


class Subscription:
    def __init__(self, subject: str):
        self.subject = subject
        self.queue: asyncio.Queue[Optional[BusMessage]] = asyncio.Queue()
        self._closed = False
        #: replay-resume cursor: the highest broker seq delivered (or the
        #: broker's seq at subscribe time) — RemoteFabric re-subscribes
        #: from here after a reconnect instead of losing the gap
        self.last_seq = 0
        #: broker epoch the cursor belongs to (a broker restart without
        #: persistence invalidates cursors; the WAL preserves the epoch)
        self.epoch: Optional[str] = None
        #: True when the last resume could NOT be made lossless (the ring
        #: trimmed past the cursor, or the broker epoch changed without a
        #: WAL) — consumers with their own sequencing resync off this
        self.resume_gap = False

    def _push(self, msg: Optional[BusMessage]) -> None:
        if not self._closed:
            self.queue.put_nowait(msg)

    async def next(self, timeout: Optional[float] = None) -> Optional[BusMessage]:
        try:
            if timeout is None:
                return await self.queue.get()
            return await asyncio.wait_for(self.queue.get(), timeout)
        except asyncio.TimeoutError:
            return None

    async def __aiter__(self):
        while True:
            m = await self.queue.get()
            if m is None:
                return
            yield m

    def close(self) -> None:
        self._closed = True
        self.queue.put_nowait(None)


@dataclass(frozen=True)
class QueueItem:
    item_id: str
    header: Any
    payload: bytes


def subject_matches(pattern: str, subject: str) -> bool:
    """Exact match, or prefix wildcard: 'events.>' matches 'events.kv.x'."""
    if pattern.endswith(">"):
        return subject.startswith(pattern[:-1])
    return pattern == subject


class AbstractFabric(Protocol):
    # kv + leases + watches (KeyValueStore surface)
    async def put(self, key: str, value: bytes, lease_id: Optional[str] = None) -> None: ...
    async def create(self, key: str, value: bytes, lease_id: Optional[str] = None) -> bool: ...
    async def get(self, key: str) -> Optional[bytes]: ...
    async def get_prefix(self, prefix: str) -> dict[str, bytes]: ...
    async def delete(self, key: str) -> bool: ...
    async def watch_prefix(self, prefix: str) -> Watch: ...
    async def grant_lease(self, ttl: float) -> str: ...
    async def keepalive(self, lease_id: str) -> bool: ...
    async def revoke_lease(self, lease_id: str) -> None: ...

    # pub/sub
    async def publish(self, subject: str, header: Any, payload: bytes = b"") -> None: ...
    async def subscribe(self, subject: str) -> Subscription: ...

    # durable work queue (ack-based redelivery)
    async def queue_push(self, queue: str, header: Any, payload: bytes = b"") -> None: ...
    async def queue_pop(self, queue: str, timeout: Optional[float] = None) -> Optional[QueueItem]: ...
    async def queue_ack(self, queue: str, item_id: str) -> None: ...
    async def queue_nack(self, queue: str, item_id: str) -> None: ...
    async def queue_len(self, queue: str) -> int: ...

    # object store
    async def obj_put(self, name: str, data: bytes) -> None: ...
    async def obj_get(self, name: str) -> Optional[bytes]: ...
    async def obj_delete(self, name: str) -> bool: ...

    async def close(self) -> None: ...

from dynamo_tpu.runtime.fabric.base import AbstractFabric, Subscription
from dynamo_tpu.runtime.fabric.local import LocalFabric
from dynamo_tpu.runtime.fabric.server import FabricServer
from dynamo_tpu.runtime.fabric.client import RemoteFabric
from dynamo_tpu.runtime.fabric.replica import (
    FabricNode,
    ReplicationTail,
    fabric_state_digest,
)

__all__ = [
    "AbstractFabric",
    "Subscription",
    "LocalFabric",
    "FabricServer",
    "RemoteFabric",
    "FabricNode",
    "ReplicationTail",
    "fabric_state_digest",
]

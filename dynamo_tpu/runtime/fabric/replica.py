"""Control-plane HA: warm-standby broker replication + epoch-fenced
failover (docs/operations.md "Control-plane HA").

The reference survives control-plane death because etcd raft-replicates
every write and JetStream runs replicated streams; the single
FabricServer was this stack's last SPOF. This module closes it at the
same scale:

* `ReplicationTail` — the standby's wire client: one `repl.subscribe`
  session bootstraps from the primary's compacted snapshot-as-WAL
  records, then applies the live journal tail (persist.apply_record —
  the byte-for-byte records the WAL holds), acking a watermark the
  primary exposes as `repl_lag_records`. A corrupt frame (CodecError) or
  a backlog reset drops the session and re-bootstraps from a FRESH
  snapshot — a standby can fall behind or restart its tail, but it can
  never silently diverge.

* `FabricNode` — one HA broker process, primary or standby:
  - standby (`run fabric --standby-of a:4222`): serves NotPrimary +
    redirect for data ops while tailing the primary; when the primary is
    unreachable past `--detector-budget` (or an explicit
    `run fabric --promote`), it PROMOTES: leases restore ORPHANED with
    the persist.py grace window, the fence bumps (fsync'd with a WAL —
    it can never regress), the publish seq skips past anything the dead
    primary may have minted beyond the replication watermark, and the
    broker starts serving. The epoch string is KEPT, so subscriber
    resume cursors stay valid — ringed subjects deliver exactly once
    across the failover.
  - a returning stale primary DEMOTES instead of split-braining: on
    startup it probes `--peer` brokers and defers to any serving primary
    with a strictly higher fence; the promoted broker's fencer loop
    also actively delivers `repl.fence` to the old address, so even a
    peer-less restart is fenced out within seconds. A demoted broker
    answers every data op with NotPrimary + the live primary's address
    and re-enters the standby role (fresh bootstrap) — a failover
    leaves you with a warm standby again.

Exactly one node per deployment runs without `--standby-of`.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Optional

import xxhash

from dynamo_tpu.runtime.codec import CodecError, encode_frame, read_frame
from dynamo_tpu.runtime.fabric.local import LocalFabric
from dynamo_tpu.runtime.fabric.persist import (
    DEFAULT_ORPHAN_GRACE,
    PersistentFabric,
    apply_record,
    orphan_leases,
)
from dynamo_tpu.runtime.fabric.server import FabricServer

logger = logging.getLogger(__name__)

#: seconds of primary unreachability before a standby auto-promotes
DEFAULT_DETECTOR_BUDGET_S = 3.0
#: cadence of the promoted broker's active fencing probes
FENCE_INTERVAL_S = 2.0
#: ack cadence: records applied between watermark acks
ACK_EVERY_RECORDS = 64


class ReplicaRedirect(Exception):
    """The tail's target is not the primary; `hint` names who is."""

    def __init__(self, hint: Optional[str]):
        super().__init__(f"replication target is standby of {hint}")
        self.hint = hint


class ReplicationReset(Exception):
    """The primary dropped our tail (journal backlog past the cap):
    re-bootstrap from a fresh snapshot."""


def fabric_state_digest(fabric: LocalFabric) -> tuple[int, int]:
    """(fold, count) over the fabric's full replicated state — KV entries
    (key, lease binding, value), lease TTL table, objects, queue items
    (inflight counts as pending: that is exactly how a restart/standby
    restores it), and the replay rings. The same order-independent
    xxh3-XOR fold shape as kv_router/digest.py, so primary-vs-standby
    equality is one integer comparison in tests and chaos proofs."""
    fold = 0
    n = 0

    def f(*parts: bytes) -> None:
        nonlocal fold, n
        h = xxhash.xxh3_64(b"\x1f".join(parts))
        fold ^= h.intdigest()
        n += 1

    for key, e in fabric.store._data.items():
        f(b"kv", key.encode(), (e.lease_id or "").encode(), e.value)
    for lease, ttl in fabric.store._lease_ttl.items():
        f(b"lease", lease.encode(), struct.pack("<d", float(ttl)))
    for name, data in fabric._objects.items():
        f(b"obj", name.encode(), data)
    for qname, q in fabric._queues.items():
        for item in list(q.inflight.values()) + list(q.items):
            f(b"q", qname.encode(), item.item_id.encode(), item.payload)
    for subj, ring in fabric._rings.items():
        for m in ring:
            f(b"ring", subj.encode(), struct.pack("<Q", m.seq), m.payload)
    return fold, n


async def _probe(address: str, header: dict, timeout: float = 2.0) -> dict:
    """One-shot op against a broker: connect, send, read the reply,
    close. Used for fencing probes and the explicit-promote CLI."""
    host, port = address.rsplit(":", 1)
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, int(port)), timeout
    )
    try:
        header = dict(header, id=1)
        writer.write(encode_frame(header))
        await writer.drain()
        h, _ = await asyncio.wait_for(read_frame(reader), timeout)
        return h
    finally:
        writer.close()


class ReplicationTail:
    """One standby's replication client. `run_once()` = one subscribe
    session (bootstrap + live tail) that raises on any failure; the
    owning FabricNode loops it and owns the promotion detector."""

    def __init__(
        self,
        fabric: LocalFabric,
        primary_address: str,
        ack_every: int = ACK_EVERY_RECORDS,
        idle_timeout_s: float = 5.0,
    ):
        self.fabric = fabric
        self.primary_address = primary_address
        self.ack_every = ack_every
        #: liveness window per read: a QUIET primary is fine (we ping and
        #: wait one more window), but a session wedged mid-frame — e.g. a
        #: bit-flipped length prefix has readexactly awaiting bytes that
        #: will never come — must die and re-bootstrap, not hang the
        #: standby forever
        self.idle_timeout_s = idle_timeout_s
        #: highest record seq acked back to the primary (its lag gauge
        #: reads delivered - this)
        self.watermark = 0
        self.delivered = 0
        #: snapshot bootstraps completed (a fuzz-poisoned or reset
        #: session re-bootstraps, bumping this)
        self.bootstraps = 0
        self.codec_errors = 0
        self.snapshot_applied = False
        #: wall clock of the last applied frame / successful connect —
        #: the promotion detector's liveness signal
        self.last_contact = 0.0
        #: called once per completed bootstrap (FabricNode compacts a
        #: persistent standby here)
        self.on_bootstrap = None

    async def _read(self, reader, writer):
        """read_frame with a liveness bound: on a silent window, ping
        and allow one more — a healthy-but-quiet primary answers the
        ping (any frame proves liveness); a wedged torn read swallows
        the reply bytes, so a second silence kills the session (the
        cancel may tear a partial frame, which the next read surfaces
        as CodecError → clean re-bootstrap; never a silent hang)."""
        try:
            return await asyncio.wait_for(
                read_frame(reader), self.idle_timeout_s
            )
        except asyncio.TimeoutError:
            writer.write(encode_frame({"op": "ping"}))
            await writer.drain()
        try:
            return await asyncio.wait_for(
                read_frame(reader), self.idle_timeout_s
            )
        except asyncio.TimeoutError:
            raise ConnectionError(
                f"replication stream from {self.primary_address} went "
                f"silent past {2 * self.idle_timeout_s:.1f}s"
            )

    async def run_once(self) -> None:
        host, port = self.primary_address.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        try:
            # the target must BE the primary: replicating from a fellow
            # standby would freeze us at its bootstrap state
            writer.write(encode_frame({"op": "repl.state", "id": 1}))
            await writer.drain()
            h, _ = await self._read(reader, writer)
            if h.get("ok") and h.get("role") != "primary":
                raise ReplicaRedirect(h.get("primary") or None)
            writer.write(
                encode_frame({"op": "repl.subscribe", "sub_id": 1, "id": 2})
            )
            await writer.drain()
            while True:
                h, _ = await self._read(reader, writer)
                if h.get("id") == 2:
                    break
            if h.get("not_primary"):
                raise ReplicaRedirect(h.get("primary") or None)
            if not h.get("ok"):
                raise ConnectionError(f"repl.subscribe refused: {h}")
            snapshot_n = int(h.get("snapshot") or 0)
            self.last_contact = time.monotonic()
            # fresh cut: drop local state, adopt the primary's epoch +
            # fence, apply the snapshot records that follow
            self.fabric.reset_for_bootstrap(
                h.get("epoch") or "", int(h.get("fence") or 1)
            )
            self.snapshot_applied = snapshot_n == 0
            self.bootstraps += 1
            if self.snapshot_applied and self.on_bootstrap is not None:
                self.on_bootstrap()
            applied = 0
            unacked = 0
            while True:
                try:
                    fh, fp = await self._read(reader, writer)
                except CodecError:
                    # a bit-flipped frame CANNOT be applied (the payload
                    # boundary itself is untrustworthy): poison the
                    # session, re-bootstrap from a fresh snapshot — the
                    # fuzz suite pins "never a silently diverged standby"
                    self.codec_errors += 1
                    raise
                if fh.get("push") != "repl":
                    continue  # ack replies etc.
                if fh.get("reset"):
                    raise ReplicationReset()
                apply_record(self.fabric, fh["r"], fp)
                self.delivered = int(fh.get("rseq") or 0)
                self.last_contact = time.monotonic()
                applied += 1
                unacked += 1
                if applied == snapshot_n:
                    self.snapshot_applied = True
                    if self.on_bootstrap is not None:
                        self.on_bootstrap()
                if unacked >= self.ack_every or (
                    applied >= snapshot_n and unacked > 0
                ):
                    # id-less ack: fire-and-forget watermark (the server
                    # sends no reply frame for it)
                    writer.write(
                        encode_frame(
                            {"op": "repl.ack", "sub_id": 1,
                             "rseq": self.delivered}
                        )
                    )
                    await writer.drain()
                    self.watermark = self.delivered
                    unacked = 0
        finally:
            writer.close()


class FabricNode:
    """One HA broker: a FabricServer plus the standby/promotion/fencing
    state machine. `run fabric` builds one of these whenever
    --standby-of or --peer is given; without them the plain single-
    broker server path is untouched."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        persist_dir: Optional[str] = None,
        standby_of: Optional[str] = None,
        peers: tuple = (),
        detector_budget_s: float = DEFAULT_DETECTOR_BUDGET_S,
        auto_promote: bool = True,
        orphan_grace: Optional[float] = None,
        fence_interval_s: float = FENCE_INTERVAL_S,
    ):
        self.server = FabricServer(host, port, persist_dir=persist_dir)
        self.standby_of = standby_of
        self.peers = tuple(p for p in peers if p)
        self.detector_budget_s = detector_budget_s
        self.auto_promote = auto_promote
        self.orphan_grace = (
            DEFAULT_ORPHAN_GRACE if orphan_grace is None else orphan_grace
        )
        self.fence_interval_s = fence_interval_s
        self.tail: Optional[ReplicationTail] = None
        #: set the moment this node starts serving as primary (tests and
        #: the CLI banner wait on it)
        self.promoted = asyncio.Event()
        self._tail_task: Optional[asyncio.Task] = None
        self._fence_task: Optional[asyncio.Task] = None
        self._closed = False

    @property
    def fabric(self) -> LocalFabric:
        return self.server.fabric

    @property
    def address(self) -> str:
        return self.server.address

    @property
    def role(self) -> str:
        return self.server.role

    async def start(self) -> None:
        await self.server.start()
        self.server.on_promote = self._admin_promote
        self.server.on_demote = self._on_demote
        if self.standby_of:
            self._enter_standby(self.standby_of)
            return
        # primary-eligible — but a serving primary with a STRICTLY
        # higher fence (someone promoted while we were dead) wins:
        # defer to it instead of split-braining. Equal fences mean no
        # promotion happened; the operator designated us primary.
        superior = await self._find_superior_peer()
        if superior is not None:
            logger.warning(
                "peer %s serves at a higher fence; starting as its standby",
                superior,
            )
            self.server.role = "standby"
            self.server.primary_address = superior
            self._enter_standby(superior)
            return
        self.promoted.set()

    async def _find_superior_peer(self) -> Optional[str]:
        for addr in self.peers:
            try:
                h = await _probe(addr, {"op": "repl.state"})
            except Exception:
                continue
            if (
                h.get("ok")
                and h.get("role") == "primary"
                and int(h.get("fence") or 0) > self.fabric.fence
            ):
                return h.get("address") or addr
        return None

    # -- standby ----------------------------------------------------------

    def _enter_standby(self, primary_address: str) -> None:
        self.server.role = "standby"
        self.server.primary_address = primary_address
        self.promoted.clear()
        if self._fence_task is not None:
            self._fence_task.cancel()
            self._fence_task = None
        self.tail = ReplicationTail(self.fabric, primary_address)
        if isinstance(self.fabric, PersistentFabric):
            # checkpoint each completed bootstrap so a standby restart
            # (or a later promotion) starts from a durable snapshot
            self.tail.on_bootstrap = self.fabric._compact
        self._tail_task = asyncio.get_running_loop().create_task(
            self._standby_loop()
        )
        logger.info(
            "standby of %s (detector budget %.1fs, auto_promote=%s)",
            primary_address, self.detector_budget_s, self.auto_promote,
        )

    async def _standby_loop(self) -> None:
        tail = self.tail
        first_fail: Optional[float] = None
        while not self._closed and self.server.role == "standby":
            try:
                await tail.run_once()
            except asyncio.CancelledError:
                return
            except ReplicaRedirect as e:
                if e.hint and e.hint != self.address:
                    logger.info("replication redirect -> %s", e.hint)
                    tail.primary_address = e.hint
                    self.server.primary_address = e.hint
                    first_fail = None
                    await asyncio.sleep(0.1)
                    continue
                await asyncio.sleep(0.2)
            except (ReplicationReset, CodecError):
                # primary is alive (it just dropped/poisoned the tail):
                # immediate fresh bootstrap, detector untouched
                first_fail = None
                await asyncio.sleep(0.05)
                continue
            except (ConnectionError, OSError, asyncio.IncompleteReadError):
                pass
            except Exception:
                logger.exception("replication tail failed")
            now = time.monotonic()
            if tail.last_contact and tail.last_contact > (first_fail or 0):
                # the session that just died HAD contact: the outage
                # clock starts at its death, not at standby startup
                first_fail = now
            elif first_fail is None:
                first_fail = now
            if (
                self.auto_promote
                and tail.snapshot_applied
                and now - first_fail >= self.detector_budget_s
            ):
                await self.promote(reason="detector")
                return
            await asyncio.sleep(min(0.25, self.detector_budget_s / 4))

    # -- promotion / demotion ---------------------------------------------

    async def _admin_promote(self) -> bool:
        return await self.promote(reason="admin")

    async def promote(self, reason: str = "admin") -> bool:
        """Standby -> primary: orphan the replicated leases (owners get
        the persist.py grace window to reattach), bump the fence +
        skip the publish seq (fsync'd pubmark via the WAL), start
        serving, and actively fence the old primary's address."""
        if self.server.role == "primary":
            return True
        if self.tail is not None and not self.tail.snapshot_applied:
            logger.warning("refusing promotion: bootstrap incomplete")
            return False
        old_primary = self.server.primary_address
        if self._tail_task is not None and (
            self._tail_task is not asyncio.current_task()
        ):
            self._tail_task.cancel()
        self._tail_task = None
        f = self.fabric
        n_orphaned = orphan_leases(f, self.orphan_grace)
        f.promote_state()
        if isinstance(f, PersistentFabric):
            f._compact()  # durable snapshot under the new fence
        self.server.role = "primary"
        self.server.primary_address = None
        self.server.promotions_total += 1
        logger.warning(
            "PROMOTED to primary (%s): fence %d, %d leases orphaned "
            "(grace %.1fs), repl watermark %d",
            reason, f.fence, n_orphaned, self.orphan_grace,
            self.tail.watermark if self.tail else 0,
        )
        from dynamo_tpu.telemetry import events

        events.record(
            "broker_promote", severity="warning", source=self.address,
            fence=f.fence, reason=reason, orphaned_leases=n_orphaned,
        )
        self.promoted.set()
        targets = [
            a
            for a in dict.fromkeys((old_primary, *self.peers))
            if a and a != self.address
        ]
        if targets:
            self._fence_task = asyncio.get_running_loop().create_task(
                self._fence_loop(targets)
            )
        return True

    async def _fence_loop(self, targets: list[str]) -> None:
        """Actively deliver our fence to the old primary's address (and
        any configured peers) forever: a stale primary that resurrects
        — even WITHOUT --peer config — demotes within one interval
        instead of accepting writes indefinitely."""
        while not self._closed and self.server.role == "primary":
            for addr in targets:
                try:
                    h = await _probe(
                        addr,
                        {
                            "op": "repl.fence",
                            "fence": self.fabric.fence,
                            "primary": self.address,
                        },
                    )
                    if h.get("demoted"):
                        logger.warning(
                            "fenced stale broker at %s (their fence %s)",
                            addr, h.get("fence"),
                        )
                except Exception:
                    pass
            await asyncio.sleep(self.fence_interval_s)

    async def _on_demote(self, primary_address: Optional[str]) -> None:
        """server.demote() flipped us to standby (a higher fence spoke):
        become a warm standby of the new primary."""
        self.promoted.clear()
        if self._fence_task is not None:
            self._fence_task.cancel()
            self._fence_task = None
        if primary_address:
            self._enter_standby(primary_address)

    async def stop(self) -> None:
        self._closed = True
        for t in (self._tail_task, self._fence_task):
            if t is not None:
                t.cancel()
        await self.server.stop()


async def promote_standby(address: str) -> dict:
    """Explicit failover (`run fabric --promote host:port`): tell the
    standby at `address` to promote NOW. Returns its reply."""
    return await _probe(address, {"op": "repl.promote"}, timeout=10.0)

"""In-process fabric: MemStore + local bus/queues/objects.

Unit tests and single-process serving (`--static` mode) run on this with
zero external infrastructure.
"""

from __future__ import annotations

import asyncio
import uuid
from collections import deque
from dataclasses import replace
from typing import Any, Optional

from dynamo_tpu.runtime.fabric.base import (
    BusMessage,
    QueueItem,
    Subscription,
    subject_matches,
)
from dynamo_tpu.runtime.store import MemStore, Watch


class _LocalQueue:
    def __init__(self):
        self.items: deque[QueueItem] = deque()
        self.inflight: dict[str, QueueItem] = {}
        self.event = asyncio.Event()

    def push(self, item: QueueItem) -> None:
        self.items.append(item)
        self.event.set()

    def pop_nowait(self) -> Optional[QueueItem]:
        if not self.items:
            self.event.clear()
            return None
        item = self.items.popleft()
        self.inflight[item.item_id] = item
        return item


class LocalFabric:
    def __init__(self):
        self.store = MemStore()
        self._subs: list[Subscription] = []
        self._queues: dict[str, _LocalQueue] = {}
        self._objects: dict[str, bytes] = {}
        #: items put back after a consumer died/nacked (at-least-once
        #: delivery in action — the broker self-observability plane)
        self.redeliveries_total = 0

    def stats(self) -> dict:
        """Broker-side self-metrics (consumed by the fabric server's
        `stats` op and, through it, metrics_service.py)."""
        return {
            "active_subs": sum(1 for s in self._subs if not s._closed),
            "active_leases": len(getattr(self.store, "_leases", ())),
            "objects": len(self._objects),
            "redeliveries_total": self.redeliveries_total,
            # NOT *_total: these are level gauges (they go down), and the
            # exposition layer types *_total keys as Prometheus counters
            "queued_items": sum(
                len(q.items) for q in self._queues.values()
            ),
            "inflight_items": sum(
                len(q.inflight) for q in self._queues.values()
            ),
            "queues": {
                name: len(q.items) for name, q in self._queues.items()
            },
        }

    # -- kv/lease/watch: delegate ------------------------------------------

    async def put(self, key, value, lease_id=None):
        await self.store.put(key, value, lease_id)

    async def create(self, key, value, lease_id=None):
        return await self.store.create(key, value, lease_id)

    async def get(self, key):
        return await self.store.get(key)

    async def get_prefix(self, prefix):
        return await self.store.get_prefix(prefix)

    async def delete(self, key):
        return await self.store.delete(key)

    async def watch_prefix(self, prefix) -> Watch:
        return await self.store.watch_prefix(prefix)

    async def grant_lease(self, ttl):
        return await self.store.grant_lease(ttl)

    async def keepalive(self, lease_id):
        return await self.store.keepalive(lease_id)

    async def reattach_lease(self, lease_id, ttl):
        await self.store.reattach_lease(lease_id, ttl)

    async def revoke_lease(self, lease_id):
        await self.store.revoke_lease(lease_id)

    # -- pub/sub -----------------------------------------------------------

    async def publish(self, subject, header, payload=b""):
        msg = BusMessage(subject, header, payload)
        for sub in self._subs:
            if subject_matches(sub.subject, subject):
                sub._push(msg)

    async def subscribe(self, subject) -> Subscription:
        sub = Subscription(subject)
        self._subs.append(sub)
        return sub

    # -- queues ------------------------------------------------------------

    def _q(self, name: str) -> _LocalQueue:
        return self._queues.setdefault(name, _LocalQueue())

    async def queue_push(self, queue, header, payload=b"") -> QueueItem:
        item = QueueItem(uuid.uuid4().hex, header, payload)
        self._q(queue).push(item)
        return item

    async def queue_pop(self, queue, timeout=None):
        q = self._q(queue)
        deadline = (
            asyncio.get_running_loop().time() + timeout
            if timeout is not None
            else None
        )
        while True:
            item = q.pop_nowait()
            if item is not None:
                return item
            remaining = None
            if deadline is not None:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    return None
            try:
                await asyncio.wait_for(q.event.wait(), remaining)
            except asyncio.TimeoutError:
                return None

    async def queue_ack(self, queue, item_id):
        self._q(queue).inflight.pop(item_id, None)

    async def queue_nack(self, queue, item_id):
        q = self._q(queue)
        item = q.inflight.pop(item_id, None)
        if item is not None:
            self.redeliveries_total += 1
            # per-item redelivery count rides the header so consumers can
            # cap poison items (PrefillQueue folds it into req.attempts —
            # a consumer dying mid-work must not redeliver forever)
            header = dict(item.header or {})
            header["redeliveries"] = int(header.get("redeliveries", 0)) + 1
            item = replace(item, header=header)
            q.items.appendleft(item)
            q.event.set()

    async def queue_len(self, queue):
        return len(self._q(queue).items)

    # -- objects -----------------------------------------------------------

    async def obj_put(self, name, data):
        self._objects[name] = bytes(data)

    async def obj_get(self, name):
        return self._objects.get(name)

    async def obj_delete(self, name):
        return self._objects.pop(name, None) is not None

    async def close(self):
        self.store.close()
        for s in self._subs:
            s.close()

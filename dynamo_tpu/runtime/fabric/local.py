"""In-process fabric: MemStore + local bus/queues/objects.

Unit tests and single-process serving (`--static` mode) run on this with
zero external infrastructure.
"""

from __future__ import annotations

import asyncio
import os
import uuid
from collections import deque
from dataclasses import replace
from typing import Any, Optional

from dynamo_tpu.runtime.fabric.base import (
    BusMessage,
    QueueItem,
    Subscription,
    subject_matches,
)
from dynamo_tpu.runtime.store import MemStore, Watch

#: bounded per-subject replay ring (JetStream-style): messages published
#: to retained subjects are kept so a subscriber can RESUME from its
#: last-seen broker sequence after a reconnect instead of losing the gap.
#: 0 disables the ring entirely (publish/subscribe revert to pure
#: fire-and-forget — the pre-ring wire, bit-identical).
RING_SIZE = int(os.environ.get("DYNTPU_FABRIC_RING", "512"))

#: subject prefixes the ring retains. KV events are the load-bearing
#: default: the router's prefix index silently diverges on any lost
#: event, which is exactly what resume repairs. Metrics/planner frames
#: are latest-wins and deliberately NOT ringed.
RING_SUBJECTS = tuple(
    p
    for p in os.environ.get(
        "DYNTPU_FABRIC_RING_SUBJECTS", "kv_events."
    ).split(",")
    if p
)

#: replication: how many un-consumed journal records a live replication
#: subscriber may fall behind before it is disconnected (it re-bootstraps
#: from a fresh snapshot — bounded memory beats an unbounded backlog)
REPL_QUEUE_CAP = int(os.environ.get("DYNTPU_FABRIC_REPL_QUEUE", "8192"))

#: promotion jumps the publish sequence forward by this much: a standby
#: may lag the dead primary by a few records, so seqs it would otherwise
#: mint could COLLIDE with seqs the primary already delivered — a
#: subscriber's duplicate guard would then swallow fresh messages. The
#: skip keeps post-failover seqs disjoint; cursors inside the skipped
#: range mark a replication-lag gap (resume flags it, sequencing
#: consumers resync).
PROMOTE_SEQ_SKIP = int(
    os.environ.get("DYNTPU_FABRIC_PROMOTE_SEQ_SKIP", "1000000")
)


class _LocalQueue:
    def __init__(self):
        self.items: deque[QueueItem] = deque()
        self.inflight: dict[str, QueueItem] = {}
        self.event = asyncio.Event()

    def push(self, item: QueueItem) -> None:
        self.items.append(item)
        self.event.set()

    def pop_nowait(self) -> Optional[QueueItem]:
        if not self.items:
            self.event.clear()
            return None
        item = self.items.popleft()
        self.inflight[item.item_id] = item
        return item


class LocalFabric:
    def __init__(
        self,
        ring_size: Optional[int] = None,
        ring_subjects: Optional[tuple] = None,
    ):
        self.store = MemStore()
        self._subs: list[Subscription] = []
        self._queues: dict[str, _LocalQueue] = {}
        self._objects: dict[str, bytes] = {}
        #: items put back after a consumer died/nacked (at-least-once
        #: delivery in action — the broker self-observability plane)
        self.redeliveries_total = 0
        #: broker epoch: a resume cursor is only meaningful against the
        #: epoch it was minted under. PersistentFabric restores it from
        #: the WAL so cursors survive server restarts. A promoted standby
        #: KEEPS the epoch (its ring is a replica of the primary's, same
        #: seqs) so resume cursors stay valid across a failover.
        self.epoch = uuid.uuid4().hex
        #: fencing counter (monotonic, unlike the opaque epoch string):
        #: every promotion bumps it, the WAL fsyncs the bump, and a
        #: returning broker with a LOWER fence demotes instead of
        #: split-braining (docs/operations.md "Control-plane HA")
        self.fence = 1
        #: live replication subscribers: every journaled mutation record
        #: fans out to these queues (the `repl.subscribe` stream a warm
        #: standby tails) — empty in single-broker deployments, so the
        #: journal tap costs one falsy check per mutation
        self._repl_subs: list[asyncio.Queue] = []
        #: (pre, post) publish-seq ranges skipped by promotions: a resume
        #: cursor inside one belongs to messages only the dead primary
        #: ever had — flagged as a gap so sequencing consumers resync
        self._promote_gaps: list[tuple[int, int]] = []
        #: global publish sequence — advances ONLY for ring-retained
        #: subjects, so the WAL can restore it exactly (every ringed
        #: publish is journaled; unringed traffic never moves it)
        self.pub_seq = 0
        self.ring_size = RING_SIZE if ring_size is None else ring_size
        self.ring_subjects = (
            RING_SUBJECTS if ring_subjects is None else tuple(ring_subjects)
        )
        #: subject -> deque[BusMessage] (bounded), and the highest seq
        #: each subject's ring has TRIMMED (resume below it = gap)
        self._rings: dict[str, deque[BusMessage]] = {}
        self._ring_trimmed: dict[str, int] = {}

    def _ringed(self, subject: str) -> bool:
        return self.ring_size > 0 and any(
            subject.startswith(p) for p in self.ring_subjects
        )

    def stats(self) -> dict:
        """Broker-side self-metrics (consumed by the fabric server's
        `stats` op and, through it, metrics_service.py)."""
        return {
            "active_subs": sum(1 for s in self._subs if not s._closed),
            "active_leases": len(getattr(self.store, "_leases", ())),
            # leases restored after a restart/promotion whose owners have
            # not reattached yet (the orphan-grace window — climbing
            # after a failover means workers are not finding the new
            # primary)
            "orphaned_leases": len(getattr(self.store, "_orphaned", ())),
            "fence": self.fence,
            "objects": len(self._objects),
            "redeliveries_total": self.redeliveries_total,
            "ring_subjects": len(self._rings),
            "ring_entries": sum(len(r) for r in self._rings.values()),
            "pub_seq": self.pub_seq,
            # NOT *_total: these are level gauges (they go down), and the
            # exposition layer types *_total keys as Prometheus counters
            "queued_items": sum(
                len(q.items) for q in self._queues.values()
            ),
            "inflight_items": sum(
                len(q.inflight) for q in self._queues.values()
            ),
            "queues": {
                name: len(q.items) for name, q in self._queues.items()
            },
        }

    # -- journal tap -------------------------------------------------------
    # Every mutation emits ONE canonical record (the same header shapes
    # PersistentFabric has always written to its WAL — persist.py owns
    # the replay side). LocalFabric's `_journal` fans records out to live
    # replication subscribers; PersistentFabric extends it to also append
    # the WAL. With neither a WAL nor a standby attached, the tap is one
    # falsy check per mutation (single-broker path unchanged).

    def _journal(self, header: dict, payload: bytes = b"") -> None:
        if not self._repl_subs:
            return
        for q in list(self._repl_subs):
            if q.qsize() >= REPL_QUEUE_CAP:
                # a subscriber this far behind re-bootstraps from a fresh
                # snapshot; an unbounded backlog would eat the broker
                self._repl_subs.remove(q)
                q.put_nowait(None)
                continue
            q.put_nowait((header, payload))

    def repl_attach(self) -> asyncio.Queue:
        """Attach a live replication subscriber. Call snapshot_records()
        and this in ONE synchronous block (no await between) so the
        snapshot + tail form a consistent cut of the mutation stream."""
        q: asyncio.Queue = asyncio.Queue()
        self._repl_subs.append(q)
        return q

    def repl_detach(self, q: asyncio.Queue) -> None:
        if q in self._repl_subs:
            self._repl_subs.remove(q)

    def snapshot_records(self) -> list[tuple[dict, bytes]]:
        """Current state as canonical journal records (snapshot-as-WAL):
        the replication bootstrap AND PersistentFabric's compaction both
        write exactly this."""
        records: list[tuple[dict, bytes]] = [
            (
                {"r": "pubmark", "epoch": self.epoch, "seq": self.pub_seq,
                 "fence": self.fence},
                b"",
            )
        ]
        ring_msgs = sorted(
            (m for ring in self._rings.values() for m in ring),
            key=lambda m: m.seq,
        )
        for m in ring_msgs:
            records.append(
                (
                    {"r": "pub", "subject": m.subject, "header": m.header,
                     "seq": m.seq},
                    m.payload,
                )
            )
        for lease_id, ttl in self.store._lease_ttl.items():
            records.append(({"r": "lease", "lease": lease_id, "ttl": ttl}, b""))
        for key, e in self.store._data.items():
            records.append(
                ({"r": "put", "key": key, "lease": e.lease_id}, e.value)
            )
        for name, q in self._queues.items():
            # inflight items were never acked: snapshot them as pending
            for item in list(q.inflight.values()) + list(q.items):
                records.append(
                    (
                        {"r": "qpush", "queue": name, "item": item.item_id,
                         "header": item.header},
                        item.payload,
                    )
                )
        for name, data in self._objects.items():
            records.append(({"r": "oput", "name": name}, data))
        return records

    def promote_state(self, seq_skip: int = PROMOTE_SEQ_SKIP) -> None:
        """Standby -> primary state transition: bump the fence (the
        monotonic split-brain guard), skip the publish sequence past any
        seqs the dead primary may have minted beyond our replication
        watermark, and journal the bump — PersistentFabric fsyncs
        pubmark records ALWAYS, so the promoted fence survives host
        power loss and can never regress."""
        self.fence += 1
        pre = self.pub_seq
        if seq_skip > 0:
            self.pub_seq += seq_skip
            self._promote_gaps.append((pre, self.pub_seq))
        self._journal(
            {"r": "pubmark", "epoch": self.epoch, "seq": self.pub_seq,
             "fence": self.fence}
        )

    def reset_for_bootstrap(self, epoch: str, fence: int) -> None:
        """Drop all state ahead of a replication bootstrap (the snapshot
        records that follow rebuild it) and adopt the primary's epoch +
        fence so resume cursors and the fencing order survive a later
        promotion."""
        self.store.close()
        from dynamo_tpu.runtime.store import MemStore

        self.store = MemStore()
        self._queues.clear()
        self._objects.clear()
        self._rings.clear()
        self._ring_trimmed.clear()
        self.pub_seq = 0
        self.epoch = epoch
        self.fence = int(fence)

    # -- kv/lease/watch: delegate ------------------------------------------

    async def put(self, key, value, lease_id=None):
        await self.store.put(key, value, lease_id)
        self._journal({"r": "put", "key": key, "lease": lease_id}, value)

    async def create(self, key, value, lease_id=None):
        created = await self.store.create(key, value, lease_id)
        if created:
            self._journal({"r": "put", "key": key, "lease": lease_id}, value)
        return created

    async def get(self, key):
        return await self.store.get(key)

    async def get_prefix(self, prefix):
        return await self.store.get_prefix(prefix)

    async def delete(self, key):
        deleted = await self.store.delete(key)
        if deleted:
            self._journal({"r": "del", "key": key})
        return deleted

    async def watch_prefix(self, prefix) -> Watch:
        return await self.store.watch_prefix(prefix)

    async def grant_lease(self, ttl):
        lease = await self.store.grant_lease(ttl)
        self._journal({"r": "lease", "lease": lease, "ttl": ttl})
        return lease

    async def keepalive(self, lease_id):
        return await self.store.keepalive(lease_id)

    async def reattach_lease(self, lease_id, ttl):
        if await self.store.reattach_lease(lease_id, ttl):
            self._journal({"r": "lease", "lease": lease_id, "ttl": ttl})

    async def revoke_lease(self, lease_id):
        await self.store.revoke_lease(lease_id)
        self._journal({"r": "lease_rm", "lease": lease_id})

    # -- pub/sub -----------------------------------------------------------

    def _ring_append(self, msg: BusMessage) -> None:
        ring = self._rings.get(msg.subject)
        if ring is None:
            ring = self._rings[msg.subject] = deque()
        ring.append(msg)
        while len(ring) > self.ring_size:
            dropped = ring.popleft()
            self._ring_trimmed[msg.subject] = dropped.seq

    async def publish(self, subject, header, payload=b""):
        seq = 0
        if self._ringed(subject):
            self.pub_seq += 1
            seq = self.pub_seq
        msg = BusMessage(subject, header, payload, seq)
        if seq:
            self._ring_append(msg)
            # only ring-retained publishes are journaled (they carry the
            # seq watermark; fire-and-forget traffic has no resume story)
            self._journal(
                {"r": "pub", "subject": subject, "header": header,
                 "seq": seq},
                payload,
            )
        for sub in self._subs:
            if subject_matches(sub.subject, subject):
                sub._push(msg)

    async def subscribe(
        self, subject, from_seq: Optional[int] = None
    ) -> Subscription:
        """Subscribe; with `from_seq`, first replay every retained
        message with seq > from_seq whose subject matches (merged across
        subjects in publish order). The registration and the replay are
        one synchronous block, so a concurrent publish can neither be
        missed nor delivered twice. Sets `sub.resume_gap` when some ring
        trimmed past the cursor (messages were lost for good)."""
        sub = Subscription(subject)
        sub.epoch = self.epoch
        sub.last_seq = self.pub_seq
        self._subs.append(sub)
        if from_seq is not None:
            replay: list[BusMessage] = []
            gap = False
            for subj, ring in self._rings.items():
                if not subject_matches(subject, subj):
                    continue
                if self._ring_trimmed.get(subj, 0) > from_seq:
                    gap = True
                replay.extend(m for m in ring if m.seq > from_seq)
            for pre, post in self._promote_gaps:
                # cursor inside a promotion skip range: the subscriber
                # saw messages only the dead primary ever had (they
                # outran replication) — honest loss, resync territory
                if pre < from_seq <= post:
                    gap = True
            replay.sort(key=lambda m: m.seq)
            for m in replay:
                sub._push(m)
            sub.resume_gap = gap
        return sub

    # -- queues ------------------------------------------------------------

    def _q(self, name: str) -> _LocalQueue:
        return self._queues.setdefault(name, _LocalQueue())

    async def queue_push(self, queue, header, payload=b"") -> QueueItem:
        item = QueueItem(uuid.uuid4().hex, header, payload)
        self._q(queue).push(item)
        self._journal(
            {"r": "qpush", "queue": queue, "item": item.item_id,
             "header": header},
            payload,
        )
        return item

    async def queue_pop(self, queue, timeout=None):
        q = self._q(queue)
        deadline = (
            asyncio.get_running_loop().time() + timeout
            if timeout is not None
            else None
        )
        while True:
            item = q.pop_nowait()
            if item is not None:
                return item
            remaining = None
            if deadline is not None:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    return None
            try:
                await asyncio.wait_for(q.event.wait(), remaining)
            except asyncio.TimeoutError:
                return None

    async def queue_ack(self, queue, item_id):
        self._q(queue).inflight.pop(item_id, None)
        self._journal({"r": "qack", "queue": queue, "item": item_id})

    async def queue_nack(self, queue, item_id):
        q = self._q(queue)
        item = q.inflight.pop(item_id, None)
        if item is not None:
            self.redeliveries_total += 1
            # per-item redelivery count rides the header so consumers can
            # cap poison items (PrefillQueue folds it into req.attempts —
            # a consumer dying mid-work must not redeliver forever)
            header = dict(item.header or {})
            header["redeliveries"] = int(header.get("redeliveries", 0)) + 1
            item = replace(item, header=header)
            q.items.appendleft(item)
            q.event.set()

    async def queue_len(self, queue):
        return len(self._q(queue).items)

    # -- objects -----------------------------------------------------------

    async def obj_put(self, name, data):
        self._objects[name] = bytes(data)
        self._journal({"r": "oput", "name": name}, bytes(data))

    async def obj_get(self, name):
        return self._objects.get(name)

    async def obj_delete(self, name):
        deleted = self._objects.pop(name, None) is not None
        if deleted:
            self._journal({"r": "odel", "name": name})
        return deleted

    async def close(self):
        self.store.close()
        for s in self._subs:
            s.close()
        for q in self._repl_subs:
            q.put_nowait(None)
        self._repl_subs.clear()

"""Fabric persistence: append-only WAL + startup compaction.

The reference's control plane survives restarts because etcd raft-persists
every write and JetStream journals queue items. This gives the single
fabric server the same survival story at its scale: every mutation record
LocalFabric journals (local.py `_journal` — the same stream a warm standby
tails over `repl.subscribe`) is appended to a WAL (codec-framed records,
so a torn tail from a crash is detected by checksum and dropped); startup
replays the log, then compacts it to a fresh snapshot-as-WAL. Leases are
restored in an ORPHANED state — deadline = now + max(ttl, orphan_grace) —
giving their owners a reconnect window (lease.reattach) before expiry
deletes their keys, which is exactly etcd's lease-TTL-survives-restart
behavior (transports/etcd.rs:78).

Durability trade (`DYNTPU_FABRIC_FSYNC`):
  epoch (default)  records are flushed (OS buffer) but only `pubmark`
                   records — the broker epoch/fence bumps a promotion
                   writes — are fsync'd: a host power loss can drop the
                   mutation tail, but FENCING stays monotonic, so a
                   promoted standby can never be out-fenced by a
                   resurrected stale primary (a process crash drops
                   nothing either way).
  always           fsync every record (etcd-grade durability; lease
                   grants and KV writes survive power loss too).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from dynamo_tpu.runtime.codec import CodecError, decode_frame, encode_frame
from dynamo_tpu.runtime.fabric.base import BusMessage, QueueItem
from dynamo_tpu.runtime.fabric.local import LocalFabric

logger = logging.getLogger(__name__)

WAL_NAME = "fabric.wal"
#: reconnect window for lease owners after a server restart
DEFAULT_ORPHAN_GRACE = 10.0
#: compact when the WAL holds this many records beyond live state
COMPACT_SLACK = 5000


def _fsync_mode() -> str:
    mode = os.environ.get("DYNTPU_FABRIC_FSYNC", "epoch").strip().lower()
    if mode not in ("epoch", "always"):
        logger.warning(
            "DYNTPU_FABRIC_FSYNC=%r is not epoch|always; using epoch", mode
        )
        return "epoch"
    return mode


def apply_record(fabric: LocalFabric, h: dict, p: bytes) -> None:
    """Apply ONE canonical journal record to a fabric. Shared by WAL
    replay and the replication tail (fabric/replica.py), so a standby
    applying the record stream converges on exactly the state a restart
    would rebuild. Lease records register the lease with NO deadline
    (deadline 0 is already-expired under the reaper — callers stamp
    deadlines: replay orphans with the grace window, a standby pins them
    far-future until promotion orphans them)."""
    op = h["r"]
    store = fabric.store
    if op == "pubmark":
        # replay-ring continuity: the broker epoch + publish seq + fence
        # survive the restart, so subscriber resume cursors stay valid
        # (client.py _apply_sub_reply) and fencing stays monotonic
        fabric.epoch = h["epoch"]
        fabric.pub_seq = max(fabric.pub_seq, int(h.get("seq") or 0))
        fabric.fence = max(fabric.fence, int(h.get("fence") or 1))
    elif op == "pub":
        seq = int(h.get("seq") or 0)
        fabric._ring_append(BusMessage(h["subject"], h.get("header"), p, seq))
        fabric.pub_seq = max(fabric.pub_seq, seq)
    elif op == "lease":
        store._leases.setdefault(h["lease"], float("inf"))
        store._lease_ttl[h["lease"]] = h["ttl"]
        store._lease_keys.setdefault(h["lease"], set())
    elif op == "lease_rm":
        # synchronous revoke: _lease_keys deletions must not await
        store._leases.pop(h["lease"], None)
        store._lease_ttl.pop(h["lease"], None)
        getattr(store, "_orphaned", set()).discard(h["lease"])
        for key in list(store._lease_keys.pop(h["lease"], ())):
            e = store._data.pop(key, None)
            if e is not None:
                from dynamo_tpu.runtime.store import WatchEvent

                store._notify(WatchEvent("delete", key))
    elif op == "put":
        lease = h.get("lease")
        if lease is not None:
            # the record stream grants leases before binding keys, but a
            # torn WAL tail / replication race must not kill the apply
            store._leases.setdefault(lease, float("inf"))
            store._lease_ttl.setdefault(lease, 3.0)
        prev = store._data.get(key := h["key"])
        if prev is not None and prev.lease_id and prev.lease_id != lease:
            store._lease_keys.get(prev.lease_id, set()).discard(key)
        if lease is not None:
            store._lease_keys.setdefault(lease, set()).add(key)
        from dynamo_tpu.runtime.store import KvEntry, WatchEvent

        store._data[key] = KvEntry(key, p, lease)
        store._notify(WatchEvent("put", key, p))
    elif op == "del":
        e = store._data.pop(h["key"], None)
        if e is not None:
            if e.lease_id and e.lease_id in store._lease_keys:
                store._lease_keys[e.lease_id].discard(h["key"])
            from dynamo_tpu.runtime.store import WatchEvent

            store._notify(WatchEvent("delete", h["key"]))
    elif op == "qpush":
        q = fabric._q(h["queue"])
        if h["item"] not in q.inflight and not any(
            it.item_id == h["item"] for it in q.items
        ):
            q.push(QueueItem(h["item"], h.get("header"), p))
    elif op == "qack":
        q = fabric._q(h["queue"])
        q.inflight.pop(h["item"], None)
        for i, item in enumerate(q.items):
            if item.item_id == h["item"]:
                del q.items[i]
                break
    elif op == "oput":
        fabric._objects[h["name"]] = bytes(p)
    elif op == "odel":
        fabric._objects.pop(h["name"], None)
    else:
        raise ValueError(f"unknown journal record {op!r}")


def orphan_leases(fabric: LocalFabric, grace: float) -> int:
    """Stamp every lease with deadline = now + max(ttl, grace): owners
    get a reconnect window (lease.reattach), then normal expiry deletes
    their keys. Used by WAL replay AND standby promotion."""
    store = fabric.store
    now = time.monotonic()
    orphaned = getattr(store, "_orphaned", None)
    if orphaned is None:
        orphaned = store._orphaned = set()
    for lease_id, ttl in store._lease_ttl.items():
        store._leases[lease_id] = now + max(ttl, grace)
        orphaned.add(lease_id)
    if store._lease_ttl:
        store._ensure_reaper()
    return len(store._lease_ttl)


class PersistentFabric(LocalFabric):
    """LocalFabric journaling every mutation to a WAL under `directory`."""

    def __init__(
        self, directory: str, orphan_grace: float = DEFAULT_ORPHAN_GRACE
    ):
        super().__init__()
        self.directory = directory
        self.orphan_grace = orphan_grace
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, WAL_NAME)
        self._wal = None
        self._records = 0
        self._fsync = _fsync_mode()

    # -- journal -----------------------------------------------------------

    def _journal(self, header: dict, payload: bytes = b"") -> None:
        super()._journal(header, payload)  # live replication subscribers
        if self._wal is None:
            return
        self._wal.write(encode_frame(header, payload))
        self._wal.flush()
        if self._fsync == "always" or header.get("r") == "pubmark":
            # pubmark carries the epoch + FENCE: a promotion's fence bump
            # must survive host power loss, or a resurrected stale
            # primary could out-fence the live one (split brain)
            os.fsync(self._wal.fileno())
        self._records += 1
        if self._records >= COMPACT_SLACK:
            self._compact()

    async def load_and_open(self) -> None:
        """Replay an existing WAL, then compact and start journaling."""
        records = []
        if os.path.exists(self._path):
            with open(self._path, "rb") as f:
                buf = f.read()
            off = 0
            while off < len(buf):
                try:
                    h, p, used = decode_frame(buf[off:])
                except CodecError:
                    logger.warning(
                        "WAL tail truncated at byte %d (%d bytes dropped)",
                        off, len(buf) - off,
                    )
                    break
                records.append((h, p))
                off += used
        for h, p in records:
            try:
                apply_record(self, h, p)
            except Exception:
                logger.exception("WAL replay failed for %r", h)
        # Orphan every restored lease: owners get a reconnect window,
        # then normal expiry semantics delete their keys.
        orphan_leases(self, self.orphan_grace)
        if records:
            logger.info(
                "fabric WAL replayed: %d records, %d keys, %d leases, "
                "%d queues, %d objects (fence %d)",
                len(records), len(self.store._data), len(self.store._leases),
                len(self._queues), len(self._objects), self.fence,
            )
        self._compact()

    def _compact(self) -> None:
        """Rewrite the WAL as current state (snapshot-as-WAL)."""
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            for h, p in self.snapshot_records():
                f.write(encode_frame(h, p))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)
        if self._wal is not None:
            self._wal.close()
        self._wal = open(self._path, "ab")
        self._records = 0

    async def close(self):
        await super().close()
        if self._wal is not None:
            self._wal.close()
            self._wal = None

"""Fabric persistence: append-only WAL + startup compaction.

The reference's control plane survives restarts because etcd raft-persists
every write and JetStream journals queue items. This gives the single
fabric server the same survival story at its scale: every mutation is
appended to a WAL (codec-framed records, so a torn tail from a crash is
detected by checksum and dropped); startup replays the log, then compacts
it to a fresh snapshot-as-WAL. Leases are restored in an ORPHANED state —
deadline = now + max(ttl, orphan_grace) — giving their owners a reconnect
window (lease.reattach) before expiry deletes their keys, which is exactly
etcd's lease-TTL-survives-restart behavior (transports/etcd.rs:78).

Durability trade: records are flushed (OS buffer) but not fsync'd per
record — a host power loss can drop the tail; a process crash cannot.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from dynamo_tpu.runtime.codec import CodecError, decode_frame, encode_frame
from dynamo_tpu.runtime.fabric.base import QueueItem
from dynamo_tpu.runtime.fabric.local import LocalFabric

logger = logging.getLogger(__name__)

WAL_NAME = "fabric.wal"
#: reconnect window for lease owners after a server restart
DEFAULT_ORPHAN_GRACE = 10.0
#: compact when the WAL holds this many records beyond live state
COMPACT_SLACK = 5000


class PersistentFabric(LocalFabric):
    """LocalFabric journaling every mutation to a WAL under `directory`."""

    def __init__(
        self, directory: str, orphan_grace: float = DEFAULT_ORPHAN_GRACE
    ):
        super().__init__()
        self.directory = directory
        self.orphan_grace = orphan_grace
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, WAL_NAME)
        self._wal = None
        self._records = 0

    # -- journal -----------------------------------------------------------

    def _append(self, header: dict, payload: bytes = b"") -> None:
        if self._wal is None:
            return
        self._wal.write(encode_frame(header, payload))
        self._wal.flush()
        self._records += 1

    async def load_and_open(self) -> None:
        """Replay an existing WAL, then compact and start journaling."""
        records = []
        if os.path.exists(self._path):
            with open(self._path, "rb") as f:
                buf = f.read()
            off = 0
            while off < len(buf):
                try:
                    h, p, used = decode_frame(buf[off:])
                except CodecError:
                    logger.warning(
                        "WAL tail truncated at byte %d (%d bytes dropped)",
                        off, len(buf) - off,
                    )
                    break
                records.append((h, p))
                off += used
        await self._replay(records)
        await self._compact()

    async def _replay(self, records) -> None:
        import time

        for h, p in records:
            op = h["r"]
            try:
                if op == "pubmark":
                    # replay-ring continuity: the broker epoch + publish
                    # seq survive the restart, so subscriber resume
                    # cursors stay valid (client.py _apply_sub_reply)
                    self.epoch = h["epoch"]
                    self.pub_seq = max(self.pub_seq, int(h.get("seq") or 0))
                elif op == "pub":
                    from dynamo_tpu.runtime.fabric.base import BusMessage

                    seq = int(h.get("seq") or 0)
                    self._ring_append(
                        BusMessage(h["subject"], h.get("header"), p, seq)
                    )
                    self.pub_seq = max(self.pub_seq, seq)
                elif op == "lease":
                    # restore the id verbatim; deadline set below
                    self.store._leases[h["lease"]] = 0.0
                    self.store._lease_ttl[h["lease"]] = h["ttl"]
                    self.store._lease_keys.setdefault(h["lease"], set())
                elif op == "lease_rm":
                    await self.store.revoke_lease(h["lease"])
                elif op == "put":
                    await self.store.put(h["key"], p, h.get("lease"))
                elif op == "del":
                    await self.store.delete(h["key"])
                elif op == "qpush":
                    self._q(h["queue"]).push(
                        QueueItem(h["item"], h.get("header"), p)
                    )
                elif op == "qack":
                    q = self._q(h["queue"])
                    q.inflight.pop(h["item"], None)
                    for i, item in enumerate(q.items):
                        if item.item_id == h["item"]:
                            del q.items[i]
                            break
                elif op == "oput":
                    self._objects[h["name"]] = bytes(p)
                elif op == "odel":
                    self._objects.pop(h["name"], None)
            except Exception:
                logger.exception("WAL replay failed for %r", h)
        # Orphan every restored lease: owners get a reconnect window, then
        # normal expiry semantics delete their keys.
        now = time.monotonic()
        for lease_id, ttl in self.store._lease_ttl.items():
            self.store._leases[lease_id] = now + max(ttl, self.orphan_grace)
        if records:
            self.store._ensure_reaper()
            logger.info(
                "fabric WAL replayed: %d records, %d keys, %d leases, "
                "%d queues, %d objects",
                len(records), len(self.store._data), len(self.store._leases),
                len(self._queues), len(self._objects),
            )

    async def _compact(self) -> None:
        """Rewrite the WAL as current state (snapshot-as-WAL)."""
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(
                encode_frame(
                    {"r": "pubmark", "epoch": self.epoch, "seq": self.pub_seq}
                )
            )
            ring_msgs = sorted(
                (m for ring in self._rings.values() for m in ring),
                key=lambda m: m.seq,
            )
            for m in ring_msgs:
                f.write(
                    encode_frame(
                        {"r": "pub", "subject": m.subject,
                         "header": m.header, "seq": m.seq},
                        m.payload,
                    )
                )
            for lease_id, ttl in self.store._lease_ttl.items():
                f.write(encode_frame({"r": "lease", "lease": lease_id, "ttl": ttl}))
            for key, e in self.store._data.items():
                f.write(
                    encode_frame(
                        {"r": "put", "key": key, "lease": e.lease_id}, e.value
                    )
                )
            for name, q in self._queues.items():
                # inflight items were never acked: restore them as pending
                for item in list(q.inflight.values()) + list(q.items):
                    f.write(
                        encode_frame(
                            {"r": "qpush", "queue": name, "item": item.item_id,
                             "header": item.header},
                            item.payload,
                        )
                    )
            for name, data in self._objects.items():
                f.write(encode_frame({"r": "oput", "name": name}, data))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)
        if self._wal is not None:
            self._wal.close()
        self._wal = open(self._path, "ab")
        self._records = 0

    async def _maybe_compact(self) -> None:
        if self._records >= COMPACT_SLACK:
            await self._compact()

    # -- journaled mutations ----------------------------------------------

    async def publish(self, subject, header, payload=b""):
        before = self.pub_seq
        await super().publish(subject, header, payload)
        if self.pub_seq != before:
            # ring-retained subject: journal it so the replay ring (and
            # the seq watermark) survive a server restart — the WAL's
            # JetStream-shaped corner
            self._append(
                {"r": "pub", "subject": subject, "header": header,
                 "seq": self.pub_seq},
                payload,
            )
            await self._maybe_compact()

    async def put(self, key, value, lease_id=None):
        await super().put(key, value, lease_id)
        self._append({"r": "put", "key": key, "lease": lease_id}, value)
        await self._maybe_compact()

    async def create(self, key, value, lease_id=None):
        created = await super().create(key, value, lease_id)
        if created:
            self._append({"r": "put", "key": key, "lease": lease_id}, value)
            await self._maybe_compact()
        return created

    async def delete(self, key):
        deleted = await super().delete(key)
        if deleted:
            self._append({"r": "del", "key": key})
        return deleted

    async def grant_lease(self, ttl):
        lease = await super().grant_lease(ttl)
        self._append({"r": "lease", "lease": lease, "ttl": ttl})
        return lease

    async def reattach_lease(self, lease_id: str, ttl: float) -> None:
        """Re-establish a lease by id after a restart (or create it if the
        orphan window already expired — the owner re-puts its keys next)."""
        if await self.store.reattach_lease(lease_id, ttl):
            self._append({"r": "lease", "lease": lease_id, "ttl": ttl})

    async def revoke_lease(self, lease_id):
        await super().revoke_lease(lease_id)
        self._append({"r": "lease_rm", "lease": lease_id})

    async def queue_push(self, queue, header, payload=b""):
        item = await super().queue_push(queue, header, payload)
        self._append(
            {"r": "qpush", "queue": queue, "item": item.item_id,
             "header": header},
            payload,
        )
        await self._maybe_compact()
        return item

    async def queue_ack(self, queue, item_id):
        await super().queue_ack(queue, item_id)
        self._append({"r": "qack", "queue": queue, "item": item_id})

    async def obj_put(self, name, data):
        await super().obj_put(name, data)
        self._append({"r": "oput", "name": name}, bytes(data))
        await self._maybe_compact()

    async def obj_delete(self, name):
        deleted = await super().obj_delete(name)
        if deleted:
            self._append({"r": "odel", "name": name})
        return deleted

    async def close(self):
        await super().close()
        if self._wal is not None:
            self._wal.close()
            self._wal = None

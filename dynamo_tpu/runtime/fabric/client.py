"""RemoteFabric: client to a FabricServer (AbstractFabric over TCP)."""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, Optional

from dynamo_tpu.runtime.codec import encode_frame, read_frame
from dynamo_tpu.runtime.fabric.base import BusMessage, QueueItem, Subscription
from dynamo_tpu.runtime.store import Watch, WatchEvent

logger = logging.getLogger(__name__)


class FabricConnectionError(ConnectionError):
    pass


class RemoteFabric:
    def __init__(self, address: str):
        self.address = address
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watches: dict[int, Watch] = {}
        self._subs: dict[int, Subscription] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self._leases: set[str] = set()
        self._send_lock = asyncio.Lock()

    @classmethod
    async def connect(cls, address: str) -> "RemoteFabric":
        self = cls(address)
        host, port = address.rsplit(":", 1)
        try:
            self._reader, self._writer = await asyncio.open_connection(
                host, int(port)
            )
        except OSError as e:
            raise FabricConnectionError(f"cannot reach fabric at {address}: {e}")
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop()
        )
        return self

    async def _read_loop(self) -> None:
        try:
            while True:
                header, payload = await read_frame(self._reader)
                if "push" in header:
                    self._handle_push(header, payload)
                    continue
                fut = self._pending.pop(header.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result((header, payload))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            err = FabricConnectionError(f"fabric connection {self.address} lost")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            for w in list(self._watches.values()):
                w.close()
            for s in list(self._subs.values()):
                s.close()

    def _handle_push(self, h: Any, payload: bytes) -> None:
        if h["push"] == "watch":
            w = self._watches.get(h["watch_id"])
            if w is not None:
                w._push(
                    WatchEvent(
                        h["kind"], h["key"], payload if h["kind"] == "put" else None
                    )
                )
        elif h["push"] == "msg":
            s = self._subs.get(h["sub_id"])
            if s is not None:
                s._push(BusMessage(h["subject"], h.get("header"), payload))

    async def _call(self, header: dict, payload: bytes = b"") -> tuple[Any, bytes]:
        rid = next(self._ids)
        header["id"] = rid
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._send_lock:
            if self._writer is None:
                raise FabricConnectionError("not connected")
            self._writer.write(encode_frame(header, payload))
            await self._writer.drain()
        h, p = await fut
        if not h.get("ok"):
            raise RuntimeError(f"fabric {header.get('op')}: {h.get('error')}")
        return h, p

    # -- kv ----------------------------------------------------------------

    async def put(self, key, value, lease_id=None):
        await self._call({"op": "kv.put", "key": key, "lease": lease_id}, value)

    async def create(self, key, value, lease_id=None):
        h, _ = await self._call(
            {"op": "kv.create", "key": key, "lease": lease_id}, value
        )
        return h["created"]

    async def get(self, key):
        h, p = await self._call({"op": "kv.get", "key": key})
        return p if h["found"] else None

    async def get_prefix(self, prefix):
        h, _ = await self._call({"op": "kv.get_prefix", "prefix": prefix})
        return h["items"]

    async def delete(self, key):
        h, _ = await self._call({"op": "kv.delete", "key": key})
        return h["deleted"]

    async def watch_prefix(self, prefix) -> Watch:
        watch_id = next(self._ids)
        w = Watch()
        self._watches[watch_id] = w
        await self._call(
            {"op": "kv.watch", "prefix": prefix, "watch_id": watch_id}
        )

        # closing the local Watch tears down the server-side pump too
        orig_close = w.close

        def close_with_unwatch():
            orig_close()
            self._watches.pop(watch_id, None)
            if self._writer is not None and not self._writer.is_closing():
                asyncio.get_running_loop().create_task(self._unwatch(watch_id))

        w.close = close_with_unwatch  # type: ignore[method-assign]
        return w

    async def _unwatch(self, watch_id: int) -> None:
        try:
            await self._call({"op": "kv.unwatch", "watch_id": watch_id})
        except Exception:
            pass

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl):
        h, _ = await self._call({"op": "lease.grant", "ttl": ttl})
        self._leases.add(h["lease"])
        self._ensure_keepalive(ttl)
        return h["lease"]

    async def keepalive(self, lease_id):
        h, _ = await self._call({"op": "lease.keepalive", "lease": lease_id})
        return h["alive"]

    async def revoke_lease(self, lease_id):
        self._leases.discard(lease_id)
        await self._call({"op": "lease.revoke", "lease": lease_id})

    def _ensure_keepalive(self, ttl: float) -> None:
        if self._keepalive_task is None or self._keepalive_task.done():
            self._keepalive_task = asyncio.get_running_loop().create_task(
                self._keepalive_loop(max(ttl / 3.0, 0.2))
            )

    async def _keepalive_loop(self, interval: float) -> None:
        try:
            while True:
                await asyncio.sleep(interval)
                for lease in list(self._leases):
                    try:
                        await self.keepalive(lease)
                    except Exception:
                        logger.warning("keepalive failed for %s", lease)
        except asyncio.CancelledError:
            pass

    # -- pub/sub -----------------------------------------------------------

    async def publish(self, subject, header, payload=b""):
        await self._call(
            {"op": "bus.pub", "subject": subject, "header": header}, payload
        )

    async def subscribe(self, subject) -> Subscription:
        sub_id = next(self._ids)
        s = Subscription(subject)
        self._subs[sub_id] = s
        await self._call({"op": "bus.sub", "subject": subject, "sub_id": sub_id})

        orig_close = s.close

        def close_with_unsub():
            orig_close()
            self._subs.pop(sub_id, None)
            if self._writer is not None and not self._writer.is_closing():
                asyncio.get_running_loop().create_task(self._unsub(sub_id))

        s.close = close_with_unsub  # type: ignore[method-assign]
        return s

    async def _unsub(self, sub_id: int) -> None:
        try:
            await self._call({"op": "bus.unsub", "sub_id": sub_id})
        except Exception:
            pass

    # -- queue -------------------------------------------------------------

    async def queue_push(self, queue, header, payload=b""):
        await self._call({"op": "queue.push", "queue": queue, "header": header}, payload)

    async def queue_pop(self, queue, timeout=None):
        h, p = await self._call(
            {"op": "queue.pop", "queue": queue, "timeout": timeout}
        )
        if not h["found"]:
            return None
        return QueueItem(h["item_id"], h.get("header"), p)

    async def queue_ack(self, queue, item_id):
        await self._call({"op": "queue.ack", "queue": queue, "item_id": item_id})

    async def queue_nack(self, queue, item_id):
        await self._call({"op": "queue.nack", "queue": queue, "item_id": item_id})

    async def queue_len(self, queue):
        h, _ = await self._call({"op": "queue.len", "queue": queue})
        return h["len"]

    # -- objects -----------------------------------------------------------

    async def obj_put(self, name, data):
        await self._call({"op": "obj.put", "name": name}, data)

    async def obj_get(self, name):
        h, p = await self._call({"op": "obj.get", "name": name})
        return p if h["found"] else None

    async def obj_delete(self, name):
        h, _ = await self._call({"op": "obj.delete", "name": name})
        return h["deleted"]

    async def ping(self) -> bool:
        h, _ = await self._call({"op": "ping"})
        return bool(h.get("ok"))

    async def close(self):
        if self._keepalive_task:
            self._keepalive_task.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:
                pass

"""RemoteFabric: client to a FabricServer (AbstractFabric over TCP).

Survival story: on connection loss the client reconnects with backoff and
re-establishes its SESSION — leases are reattached under their original
ids (server op lease.reattach), leased keys are re-put, watches re-created
(each local Watch first receives a synthetic "reset" event so consumers
drop state that may have been deleted during the outage — the server
re-sends current state as puts), and subscriptions re-subscribed. In-flight
calls during the outage fail fast with FabricConnectionError; callers
retry or surface the error, matching etcd client semantics (the reference
leans on etcd's own lease keepalive + re-watch machinery the same way).

Control-plane HA (docs/operations.md "Control-plane HA"): the address may
be a comma-separated list (`--fabric a:4222,b:4222`) — the reconnect loop
rotates through it, a `NotPrimary` refusal is followed to the hinted
primary transparently (the op retries there, it was never executed), and
the per-subscription resume cursors + seq dedup make ringed subjects
deliver exactly once ACROSS a broker failover. When no broker answers
past `DYNTPU_DEGRADED_AFTER` seconds the client reports `degraded` — the
designed broker-less mode: consumers keep serving from cached discovery
snapshots, publishers buffer or shed, and both Prometheus surfaces gauge
the state (telemetry/debug.control_plane_lines).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import random
import time
from typing import Any, Optional

from dynamo_tpu.runtime.codec import encode_frame, read_frame
from dynamo_tpu.runtime.fabric.base import BusMessage, QueueItem, Subscription
from dynamo_tpu.runtime.store import Watch, WatchEvent
from dynamo_tpu.testing import faults

logger = logging.getLogger(__name__)

#: seconds of continuous broker unreachability before the client calls
#: itself DEGRADED (the designed broker-less mode: cached-discovery
#: serving, bounded publish buffering, planner HOLD)
DEGRADED_AFTER_S = float(os.environ.get("DYNTPU_DEGRADED_AFTER", "5.0"))


class FabricConnectionError(ConnectionError):
    pass


class RemoteFabric:
    def __init__(self, address: str, reconnect: bool = True):
        #: failover rotation: `address` may be "a:4222,b:4222" — the
        #: first entry is tried first, NotPrimary redirects and the
        #: reconnect loop rotate through the rest
        self.addresses = [a.strip() for a in address.split(",") if a.strip()]
        if not self.addresses:
            raise ValueError(f"no fabric address in {address!r}")
        self.address = self.addresses[0]
        self.reconnect = reconnect
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watches: dict[int, Watch] = {}
        self._watch_prefixes: dict[int, str] = {}
        self._subs: dict[int, Subscription] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._leases: set[str] = set()
        self._lease_ttls: dict[str, float] = {}
        #: leased key -> (value, lease_id): the session state re-put on
        #: reconnect (liveness registrations, model entries)
        self._restorable: dict[str, tuple[bytes, Optional[str]]] = {}
        self._send_lock = asyncio.Lock()
        self._switch_lock = asyncio.Lock()
        self._switching = False
        self._in_reestablish = False
        #: connection generation: a read loop only tears the session
        #: down if it is still the CURRENT connection's loop (an address
        #: switch bumps this and owns the transition)
        self._gen = 0
        self._closed = False
        #: degraded-mode bookkeeping (docs/operations.md "Control-plane
        #: HA"): connection state, when it was lost, and the counters
        #: both Prometheus surfaces expose via control_plane_lines()
        self.connected = False
        self.degraded_after_s = DEGRADED_AFTER_S
        self._disconnected_at: Optional[float] = None
        self._degraded_marked = False
        self.degraded_total = 0
        self.degraded_seconds_total = 0.0
        #: times the established broker ADDRESS changed (a failover the
        #: client rode out — redirect-following or rotation)
        self.failovers_total = 0
        self._established: Optional[str] = None
        # exposition registry (weak): whatever Prometheus surface this
        # process has gauges dynamo_tpu_control_plane_degraded off us
        from dynamo_tpu.telemetry import debug as _debug

        _debug.register_fabric_client(self)

    @property
    def degraded(self) -> bool:
        """True once no broker has answered past the budget — consumers
        switch to the designed broker-less mode (cached discovery,
        bounded buffering, planner HOLD)."""
        return (
            not self.connected
            and self._disconnected_at is not None
            and time.monotonic() - self._disconnected_at
            >= self.degraded_after_s
        )

    @property
    def disconnected_s(self) -> float:
        if self.connected or self._disconnected_at is None:
            return 0.0
        return time.monotonic() - self._disconnected_at

    @classmethod
    async def connect(
        cls, address: str, reconnect: bool = True
    ) -> "RemoteFabric":
        self = cls(address, reconnect=reconnect)
        last: Optional[Exception] = None
        for addr in list(self.addresses):
            self.address = addr
            try:
                await self._open()
            except FabricConnectionError as e:
                last = e
                continue
            # follow a standby's redirect BEFORE the caller's first op:
            # connecting to the warm standby of a two-broker deployment
            # must land the session on the primary
            try:
                await self._follow_primary()
            except FabricConnectionError as e:
                last = e
                continue
            self._mark_established()
            return self
        raise last or FabricConnectionError(
            f"cannot reach any fabric in {address!r}"
        )

    async def _follow_primary(self, hops: int = 3) -> None:
        """Probe `repl.state` (served in every role) and hop to the
        advertised primary if this broker is a standby."""
        for _ in range(hops):
            try:
                h, _ = await self._call_raw({"op": "repl.state"})
            except RuntimeError:
                return  # pre-HA server: no repl ops, it IS the primary
            if h.get("role") == "primary" or not h.get("ok"):
                return
            hint = h.get("primary") or None
            # a standby learns its primary lazily; fall back to rotation
            nxt = hint or self._next_address()
            if nxt is None or nxt == self.address:
                return
            await self._reopen(nxt)
        raise FabricConnectionError("redirect loop while locating primary")

    def _next_address(self) -> Optional[str]:
        if len(self.addresses) < 2:
            return None
        i = self.addresses.index(self.address) if (
            self.address in self.addresses
        ) else -1
        return self.addresses[(i + 1) % len(self.addresses)]

    def _learn_address(self, addr: str) -> None:
        if addr and addr not in self.addresses:
            self.addresses.append(addr)

    async def _reopen(self, addr: str) -> None:
        """Tear the current connection down quietly (no reconnect-loop
        spawn) and open `addr` instead. Ops still in flight on the old
        connection fail fast with FabricConnectionError — they were
        addressed at a broker that is not (or no longer) the primary."""
        self._switching = True
        try:
            self.connected = False
            if self._reader_task is not None:
                self._reader_task.cancel()
            if self._writer is not None:
                self._writer.close()
            self._fail_pending()
            self.address = addr
            try:
                await self._open()
            except BaseException:
                # the redirect target is unreachable — hand recovery to
                # the reconnect loop (the cancelled read loop skipped
                # spawning one because this switch owned the transition,
                # so WITHOUT this the client would stay dead forever)
                if self._disconnected_at is None:
                    self._disconnected_at = time.monotonic()
                if (
                    not self._closed
                    and self.reconnect
                    and (
                        self._reconnect_task is None
                        or self._reconnect_task.done()
                    )
                ):
                    self._reconnect_task = (
                        asyncio.get_running_loop().create_task(
                            self._reconnect_loop()
                        )
                    )
                raise
        finally:
            self._switching = False

    def _mark_established(self) -> None:
        # an establishment always ends any outage bookkeeping: a
        # connect-time redirect's cancelled read loop may have stamped
        # _disconnected_at mid-switch, and leaving it stale would make a
        # LATER sub-second blip read as instantly past the degraded
        # budget (hours-old timestamp)
        self._clear_outage()
        prev, self._established = self._established, self.address
        if prev is not None and prev != self.address:
            self.failovers_total += 1
            logger.warning(
                "fabric failover: %s -> %s", prev, self.address
            )
            from dynamo_tpu.telemetry import events

            events.record(
                "broker_failover", severity="warning", source=prev,
                to=self.address,
            )

    async def _open(self) -> None:
        host, port = self.address.rsplit(":", 1)
        try:
            self._reader, self._writer = await asyncio.open_connection(
                host, int(port)
            )
        except OSError as e:
            raise FabricConnectionError(f"cannot reach fabric at {self.address}: {e}")
        self._gen += 1
        self.connected = True
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop()
        )

    def _fail_pending(self) -> None:
        err = FabricConnectionError(f"fabric connection {self.address} lost")
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
                # a requester that was itself cancelled at teardown never
                # awaits this future; pre-retrieve the exception so GC
                # doesn't log "exception was never retrieved" (a later
                # await still raises — only the log flag is cleared)
                fut.exception()
        self._pending.clear()

    async def _read_loop(self) -> None:
        gen, reader = self._gen, self._reader
        try:
            while True:
                header, payload = await read_frame(reader)
                if "push" in header:
                    self._handle_push(header, payload)
                    continue
                fut = self._pending.pop(header.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result((header, payload))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            if gen != self._gen:
                # a deliberate address switch already replaced this
                # connection (and failed its pending futures) — a late-
                # running finally must not tear the NEW session down
                return
            self.connected = False
            if self._disconnected_at is None:
                self._disconnected_at = time.monotonic()
            self._fail_pending()
            if self._closed or not self.reconnect:
                for w in list(self._watches.values()):
                    w.close()
                for s in list(self._subs.values()):
                    s.close()
            elif self._switching:
                pass  # _reopen owns the transition
            elif self._reconnect_task is None or self._reconnect_task.done():
                self._reconnect_task = asyncio.get_running_loop().create_task(
                    self._reconnect_loop()
                )

    # -- session re-establishment ------------------------------------------

    def _maybe_mark_degraded(self) -> None:
        if self._degraded_marked or not self.degraded:
            return
        self._degraded_marked = True
        self.degraded_total += 1
        logger.warning(
            "control plane DEGRADED: no broker answered for %.1fs "
            "(tried %s) — serving from cached discovery, publishes "
            "buffer/shed until a broker returns",
            self.disconnected_s, ",".join(self.addresses),
        )
        from dynamo_tpu.telemetry import events

        events.record(
            "degraded", severity="warning", source=self.address,
            phase="enter", addresses=",".join(self.addresses),
        )

    def _clear_outage(self) -> None:
        if self._disconnected_at is not None and self._degraded_marked:
            outage = time.monotonic() - self._disconnected_at
            self.degraded_seconds_total += outage
            logger.info(
                "control plane recovered after %.1fs degraded", outage
            )
            from dynamo_tpu.telemetry import events

            events.record(
                "degraded", source=self.address, phase="exit",
                outage_s=round(outage, 2),
            )
        self._degraded_marked = False
        self._disconnected_at = None

    async def _reconnect_loop(self) -> None:
        delay = 0.2
        start = (
            self.addresses.index(self.address)
            if self.address in self.addresses
            else 0
        )
        attempt = 0
        while not self._closed:
            await asyncio.sleep(delay * (0.7 + 0.6 * random.random()))
            delay = min(delay * 1.7, 2.0)
            self._maybe_mark_degraded()
            # rotate through the address list: whichever broker answers
            # (and, via _follow_primary, whoever it says is primary) wins
            self.address = self.addresses[
                (start + attempt) % len(self.addresses)
            ]
            attempt += 1
            try:
                await self._open()
                await self._follow_primary()
                await self._reestablish()
            except Exception:
                self.connected = False
                if self._writer is not None:
                    self._writer.close()
                continue
            self._mark_established()
            self._clear_outage()
            logger.info("fabric session re-established with %s", self.address)
            return

    async def _reestablish(self) -> None:
        self._in_reestablish = True
        try:
            await self._reestablish_inner()
        finally:
            self._in_reestablish = False

    async def _reestablish_inner(self) -> None:
        for lease in list(self._leases):
            await self._call(
                {
                    "op": "lease.reattach", "lease": lease,
                    "ttl": self._lease_ttls.get(lease, 3.0),
                }
            )
        for key, (value, lease) in list(self._restorable.items()):
            await self._call(
                {"op": "kv.put", "key": key, "lease": lease}, value
            )
        for watch_id, prefix in list(self._watch_prefixes.items()):
            w = self._watches.get(watch_id)
            if w is None or w._closed:
                continue
            # reset BEFORE re-watching: the server replays current state
            # as puts; consumers drop anything deleted during the outage
            w._push(WatchEvent("reset", ""))
            await self._call(
                {"op": "kv.watch", "prefix": prefix, "watch_id": watch_id}
            )
        for sub_id, s in list(self._subs.items()):
            if not s._closed:
                # resume from the last-seen broker seq: the server replays
                # the ring-retained gap, so a subscriber that rode out an
                # outage observes every retained message exactly once.
                # DISARM the duplicate guard BEFORE the call: replayed
                # pushes can be processed from the same read batch as the
                # reply (the read loop does not yield to this coroutine
                # between frames), and under a changed broker epoch their
                # fresh low seqs would be swallowed by the stale cursor.
                # No duplicate can arrive while disarmed — a same-epoch
                # server replays strictly past `cursor`, a new-epoch
                # server's ring is entirely unseen — and the first push
                # re-arms it.
                cursor, epoch = s.last_seq, s.epoch
                s.last_seq = 0
                try:
                    h, _ = await self._call(
                        {
                            "op": "bus.sub", "subject": s.subject,
                            "sub_id": sub_id, "resume": cursor,
                            "epoch": epoch,
                        }
                    )
                except BaseException:
                    # the link dropped again mid-reestablish: put the
                    # cursor back so the NEXT attempt doesn't resume
                    # from 0 and replay the whole ring as duplicates
                    s.last_seq = max(s.last_seq, cursor)
                    raise
                if h.get("epoch") == epoch:
                    # same epoch: the cursor stays meaningful — restore
                    # it (max: replayed pushes may already have advanced
                    # past it) so a quiet subject doesn't leave the NEXT
                    # resume at 0, which would replay the whole ring
                    s.last_seq = max(s.last_seq, cursor)
                s.epoch = h.get("epoch")
                if h.get("gap"):
                    s.resume_gap = True
                    logger.warning(
                        "subscription %s resumed with a replay gap "
                        "(ring trimmed or broker epoch changed)",
                        s.subject,
                    )

    @staticmethod
    def _apply_sub_reply(s: Subscription, h: Any) -> None:
        """Fold a fresh bus.sub reply into the subscription's resume
        cursor: baseline = the broker's seq at registration. max() with
        the live cursor — pushes from the same read batch as the reply
        may already have advanced it, and regressing would be harmless
        but confusing. (Resume replies are handled in _reestablish,
        which disarms the guard first.)"""
        s.last_seq = max(s.last_seq, int(h.get("seq") or 0))
        s.epoch = h.get("epoch")

    def _handle_push(self, h: Any, payload: bytes) -> None:
        if h["push"] == "watch":
            w = self._watches.get(h["watch_id"])
            if w is not None:
                w._push(
                    WatchEvent(
                        h["kind"], h["key"], payload if h["kind"] == "put" else None
                    )
                )
        elif h["push"] == "msg":
            s = self._subs.get(h["sub_id"])
            if s is not None:
                seq = int(h.get("seq") or 0)
                if seq:
                    # at-least-once transport + this guard = exactly-once
                    # delivery per subscription for ring-retained
                    # subjects (a resume replay can overlap messages that
                    # raced out just before the drop)
                    if seq <= s.last_seq:
                        return
                    s.last_seq = seq
                s._push(BusMessage(h["subject"], h.get("header"), payload, seq))

    async def _call_raw(
        self, header: dict, payload: bytes = b""
    ) -> tuple[Any, bytes]:
        """Send one op and await its reply frame — no ok/NotPrimary
        interpretation (that's _call's job)."""
        # fault-injection hook (dynamo_tpu/testing/faults.py): a no-op
        # global check unless a chaos scenario installed an injector
        try:
            await faults.fire("fabric.call", op=header.get("op"))
        except ConnectionError as e:
            raise FabricConnectionError(str(e))
        rid = next(self._ids)
        header["id"] = rid
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._send_lock:
            if self._writer is None:
                self._pending.pop(rid, None)
                raise FabricConnectionError("not connected")
            # corrupt-kind chaos rules flip a byte of the encoded frame
            # (queue payloads included) AFTER the codec checksummed it —
            # the server's read_frame rejects it and drops the session;
            # this call must fail, never deliver rotten bytes
            self._writer.write(
                faults.corrupt_bytes(
                    "fabric.call",
                    encode_frame(header, payload),
                    op=header.get("op"),
                )
            )
            await self._writer.drain()
        return await fut

    async def _call(self, header: dict, payload: bytes = b"") -> tuple[Any, bytes]:
        for _ in range(4):
            sent_on = self.address
            h, p = await self._call_raw(header, payload)
            if h.get("not_primary"):
                # epoch-fenced redirect: the broker refused because it is
                # a standby / demoted stale primary. The op was NOT
                # executed, so retrying it on the hinted primary is safe.
                hint = h.get("primary") or None
                if hint:
                    self._learn_address(hint)
                if self._in_reestablish:
                    # _reestablish runs under the reconnect loop (which
                    # re-probes for the primary) — switching here would
                    # re-enter the switch lock. Fail fast; the loop
                    # rotates and retries.
                    raise FabricConnectionError(
                        f"fabric at {sent_on} is not primary"
                    )
                nxt = hint or self._next_address()
                if nxt is None:
                    raise FabricConnectionError(
                        f"fabric at {sent_on} is not primary and no "
                        "alternate address is configured"
                    )
                async with self._switch_lock:
                    if self.address == sent_on and not self._closed:
                        logger.warning(
                            "fabric %s answered NotPrimary; following "
                            "redirect to %s", sent_on, nxt,
                        )
                        await self._reopen(nxt)
                        # the new primary needs this client's SESSION —
                        # leases reattached, leased keys re-put, watches
                        # reset, subscriptions resumed from their cursors
                        await self._reestablish()
                        self._mark_established()
                        self._clear_outage()
                continue
            if not h.get("ok"):
                raise RuntimeError(f"fabric {header.get('op')}: {h.get('error')}")
            return h, p
        raise FabricConnectionError("NotPrimary redirect loop")

    # -- kv ----------------------------------------------------------------

    async def put(self, key, value, lease_id=None):
        await self._call({"op": "kv.put", "key": key, "lease": lease_id}, value)
        if lease_id is not None:
            self._restorable[key] = (bytes(value), lease_id)
        else:
            self._restorable.pop(key, None)  # unleased put unbinds the key

    async def create(self, key, value, lease_id=None):
        h, _ = await self._call(
            {"op": "kv.create", "key": key, "lease": lease_id}, value
        )
        if h["created"] and lease_id is not None:
            self._restorable[key] = (bytes(value), lease_id)
        return h["created"]

    async def get(self, key):
        h, p = await self._call({"op": "kv.get", "key": key})
        return p if h["found"] else None

    async def get_prefix(self, prefix):
        h, _ = await self._call({"op": "kv.get_prefix", "prefix": prefix})
        return h["items"]

    async def delete(self, key):
        self._restorable.pop(key, None)
        h, _ = await self._call({"op": "kv.delete", "key": key})
        return h["deleted"]

    async def watch_prefix(self, prefix) -> Watch:
        watch_id = next(self._ids)
        w = Watch()
        self._watches[watch_id] = w
        self._watch_prefixes[watch_id] = prefix
        await self._call(
            {"op": "kv.watch", "prefix": prefix, "watch_id": watch_id}
        )

        # closing the local Watch tears down the server-side pump too
        orig_close = w.close

        def close_with_unwatch():
            orig_close()
            self._watches.pop(watch_id, None)
            self._watch_prefixes.pop(watch_id, None)
            if self._writer is not None and not self._writer.is_closing():
                asyncio.get_running_loop().create_task(self._unwatch(watch_id))

        w.close = close_with_unwatch  # type: ignore[method-assign]
        return w

    async def _unwatch(self, watch_id: int) -> None:
        try:
            await self._call({"op": "kv.unwatch", "watch_id": watch_id})
        except Exception:
            pass

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl):
        h, _ = await self._call({"op": "lease.grant", "ttl": ttl})
        self._leases.add(h["lease"])
        self._lease_ttls[h["lease"]] = ttl
        self._ensure_keepalive(ttl)
        return h["lease"]

    async def keepalive(self, lease_id):
        h, _ = await self._call({"op": "lease.keepalive", "lease": lease_id})
        return h["alive"]

    async def revoke_lease(self, lease_id):
        self._leases.discard(lease_id)
        self._lease_ttls.pop(lease_id, None)
        for key in [
            k for k, (_, l) in self._restorable.items() if l == lease_id
        ]:
            del self._restorable[key]
        await self._call({"op": "lease.revoke", "lease": lease_id})

    def _ensure_keepalive(self, ttl: float) -> None:
        if self._keepalive_task is None or self._keepalive_task.done():
            self._keepalive_task = asyncio.get_running_loop().create_task(
                self._keepalive_loop(max(ttl / 3.0, 0.2))
            )

    async def _keepalive_loop(self, interval: float) -> None:
        try:
            while True:
                await asyncio.sleep(interval)
                for lease in list(self._leases):
                    try:
                        alive = await self.keepalive(lease)
                    except Exception:
                        logger.warning("keepalive failed for %s", lease)
                        continue
                    if not alive:
                        # Lease vanished server-side (expired during an
                        # outage, or revoked by a stale connection's
                        # cleanup): re-establish it and restore its keys
                        # instead of silently disappearing from discovery.
                        logger.warning(
                            "lease %s lost; reattaching + restoring keys",
                            lease,
                        )
                        try:
                            await self._call(
                                {
                                    "op": "lease.reattach", "lease": lease,
                                    "ttl": self._lease_ttls.get(lease, 3.0),
                                }
                            )
                            for key, (value, l) in list(
                                self._restorable.items()
                            ):
                                if l == lease:
                                    await self._call(
                                        {
                                            "op": "kv.put", "key": key,
                                            "lease": lease,
                                        },
                                        value,
                                    )
                        except Exception:
                            logger.warning(
                                "lease %s recovery failed", lease,
                                exc_info=True,
                            )
        except asyncio.CancelledError:
            pass

    # -- pub/sub -----------------------------------------------------------

    async def publish(self, subject, header, payload=b""):
        await self._call(
            {"op": "bus.pub", "subject": subject, "header": header}, payload
        )

    async def subscribe(self, subject) -> Subscription:
        sub_id = next(self._ids)
        s = Subscription(subject)
        self._subs[sub_id] = s
        h, _ = await self._call(
            {"op": "bus.sub", "subject": subject, "sub_id": sub_id}
        )
        self._apply_sub_reply(s, h)

        orig_close = s.close

        def close_with_unsub():
            orig_close()
            self._subs.pop(sub_id, None)
            if self._writer is not None and not self._writer.is_closing():
                asyncio.get_running_loop().create_task(self._unsub(sub_id))

        s.close = close_with_unsub  # type: ignore[method-assign]
        return s

    async def _unsub(self, sub_id: int) -> None:
        try:
            await self._call({"op": "bus.unsub", "sub_id": sub_id})
        except Exception:
            pass

    # -- queue -------------------------------------------------------------

    async def queue_push(self, queue, header, payload=b""):
        await self._call({"op": "queue.push", "queue": queue, "header": header}, payload)

    async def queue_pop(self, queue, timeout=None):
        h, p = await self._call(
            {"op": "queue.pop", "queue": queue, "timeout": timeout}
        )
        if not h["found"]:
            return None
        return QueueItem(h["item_id"], h.get("header"), p)

    async def queue_ack(self, queue, item_id):
        await self._call({"op": "queue.ack", "queue": queue, "item_id": item_id})

    async def queue_nack(self, queue, item_id):
        await self._call({"op": "queue.nack", "queue": queue, "item_id": item_id})

    async def queue_len(self, queue):
        h, _ = await self._call({"op": "queue.len", "queue": queue})
        return h["len"]

    # -- objects -----------------------------------------------------------

    async def obj_put(self, name, data):
        await self._call({"op": "obj.put", "name": name}, data)

    async def obj_get(self, name):
        h, p = await self._call({"op": "obj.get", "name": name})
        return p if h["found"] else None

    async def obj_delete(self, name):
        h, _ = await self._call({"op": "obj.delete", "name": name})
        return h["deleted"]

    async def ping(self) -> bool:
        h, _ = await self._call({"op": "ping"})
        return bool(h.get("ok"))

    async def stats(self) -> dict:
        """Broker self-metrics snapshot (server op `stats`)."""
        h, _ = await self._call({"op": "stats"})
        return h.get("stats") or {}

    async def close(self):
        self._closed = True
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._keepalive_task:
            self._keepalive_task.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:
                pass

"""Fabric server: the control-plane process.

One asyncio TCP server providing KV/lease/watch + pub/sub + queues + object
store to every worker/frontend/router process (the role etcd + NATS +
JetStream play for the reference — SURVEY.md L0). Wire protocol: codec.py
frames; request/response correlated by `id`; server-initiated pushes carry
`push` instead.

Connection-scoped cleanup is the liveness model: leases granted on a
connection are revoked when it drops (⇒ all its registrations vanish),
subscriptions/watches die with it, and unacked queue items are redelivered.
Start standalone:  python -m dynamo_tpu.runtime.fabric.server --port 4222
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Any, Optional

from dynamo_tpu.runtime.codec import encode_frame, read_frame
from dynamo_tpu.runtime.fabric.local import LocalFabric

logger = logging.getLogger(__name__)


class _Conn:
    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.leases: set[str] = set()
        self.watches: dict[int, Any] = {}  # watch_id -> (Watch, pump task)
        self.subs: dict[int, Any] = {}  # sub_id -> (Subscription, pump task)
        self.inflight: set[tuple[str, str]] = set()  # (queue, item_id)
        self.tasks: set[asyncio.Task] = set()  # pending op dispatches
        self.lock = asyncio.Lock()
        #: replication subscriber state (a warm standby tailing us over
        #: `repl.subscribe`): the live record queue + pump task, the
        #: highest record seq delivered, and the standby's acked
        #: watermark — delivered - acked is the standby's lag
        self.repl: Any = None  # (queue, pump task)
        self.repl_delivered = 0
        self.repl_acked = 0

    async def send(self, header: Any, payload: bytes = b"") -> None:
        async with self.lock:
            self.writer.write(encode_frame(header, payload))
            await self.writer.drain()


class FabricServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        persist_dir: Optional[str] = None,
    ):
        self.host = host
        self.port = port
        if persist_dir:
            from dynamo_tpu.runtime.fabric.persist import PersistentFabric

            self.fabric = PersistentFabric(persist_dir)
        else:
            self.fabric = LocalFabric()
        self._server: Optional[asyncio.Server] = None
        self._conns: set[_Conn] = set()
        self._connections_total = 0
        self._ops_total = 0
        #: HA role (docs/operations.md "Control-plane HA"): a standby
        #: answers every data op with NotPrimary + the primary's address
        #: (clients follow the redirect) while serving repl.*/ping/stats,
        #: until fabric/replica.py promotes it
        self.role = "primary"
        self.primary_address: Optional[str] = None
        self.promotions_total = 0
        self.demotions_total = 0
        #: replica.py hooks: `repl.promote` (admin op) and an incoming
        #: higher-fence `repl.fence` claim route through these so the
        #: owning FabricNode can flip roles / start tailing
        self.on_promote = None  # async () -> bool
        self.on_demote = None  # async (primary_address) -> None

    #: ops a standby still serves (everything else redirects): liveness
    #: probes, self-metrics, and the whole replication/fencing plane
    _STANDBY_OPS = frozenset(
        ("ping", "stats", "repl.subscribe", "repl.ack", "repl.state",
         "repl.fence", "repl.promote")
    )

    def stats(self) -> dict:
        """Broker self-metrics: the server's own health joins the
        observability plane (op `stats`; metrics_service.py polls it and
        exposes Prometheus `dynamo_tpu_fabric_*` gauges)."""
        repl_conns = [c for c in self._conns if c.repl is not None]
        return {
            "connections": len(self._conns),
            "connections_total": self._connections_total,
            "ops_total": self._ops_total,
            "active_watches": sum(len(c.watches) for c in self._conns),
            "pending_dispatches": sum(len(c.tasks) for c in self._conns),
            # control-plane HA: standby count + worst replication lag in
            # records (doctor's replication-lag rule: a lagging standby
            # is not safe to promote) + promotion/demotion counters
            "repl_subscribers": len(repl_conns),
            "repl_lag_records": max(
                (c.repl_delivered - c.repl_acked for c in repl_conns),
                default=0,
            ),
            "promotions_total": self.promotions_total,
            "demotions_total": self.demotions_total,
            "is_primary": 1 if self.role == "primary" else 0,
            **self.fabric.stats(),
        }

    async def start(self) -> None:
        if hasattr(self.fabric, "load_and_open"):
            await self.fabric.load_and_open()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("fabric server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # 3.12 wait_closed() also waits for handlers: drop live conns.
            for conn in list(self._conns):
                conn.writer.close()
            await self._server.wait_closed()
        await self.fabric.close()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ------------------------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn = _Conn(writer)
        self._conns.add(conn)
        self._connections_total += 1
        try:
            while True:
                header, payload = await read_frame(reader)
                t = asyncio.get_running_loop().create_task(
                    self._dispatch(conn, header, payload)
                )
                conn.tasks.add(t)
                t.add_done_callback(conn.tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:
            logger.exception("fabric connection error")
        finally:
            self._conns.discard(conn)
            await self._cleanup(conn)
            writer.close()

    async def _cleanup(self, conn: _Conn) -> None:
        # kill pending dispatches first (e.g. a blocked queue.pop would
        # otherwise pop an item for this dead connection and strand it)
        for t in list(conn.tasks):
            t.cancel()
        if conn.repl is not None:
            q, task = conn.repl
            self.fabric.repl_detach(q)
            task.cancel()
            conn.repl = None
        for _, (w, task) in conn.watches.items():
            w.close()
            task.cancel()
        for _, (s, task) in conn.subs.items():
            s.close()
            task.cancel()
        for queue, item_id in list(conn.inflight):
            await self.fabric.queue_nack(queue, item_id)
        for lease in list(conn.leases):
            await self.fabric.revoke_lease(lease)

    async def _dispatch(self, conn: _Conn, h: Any, payload: bytes) -> None:
        op, rid = h.get("op"), h.get("id")
        f = self.fabric
        self._ops_total += 1
        if self.role != "primary" and op not in self._STANDBY_OPS:
            # epoch-fenced refusal: a standby (or a demoted stale
            # primary) answers every data op with the live primary's
            # address instead of serving stale state or split-braining
            # writes — clients follow the redirect (client.py)
            if rid is not None:
                try:
                    await conn.send(
                        {
                            "id": rid, "ok": False, "error": "NotPrimary",
                            "not_primary": True,
                            "primary": self.primary_address or "",
                        }
                    )
                except Exception:
                    pass
            return
        try:
            if op == "kv.put":
                await f.put(h["key"], payload, h.get("lease"))
                await conn.send({"id": rid, "ok": True})
            elif op == "kv.create":
                created = await f.create(h["key"], payload, h.get("lease"))
                await conn.send({"id": rid, "ok": True, "created": created})
            elif op == "kv.get":
                v = await f.get(h["key"])
                await conn.send(
                    {"id": rid, "ok": True, "found": v is not None}, v or b""
                )
            elif op == "kv.get_prefix":
                items = await f.get_prefix(h["prefix"])
                await conn.send({"id": rid, "ok": True, "items": items})
            elif op == "kv.delete":
                deleted = await f.delete(h["key"])
                await conn.send({"id": rid, "ok": True, "deleted": deleted})
            elif op == "kv.watch":
                watch = await f.watch_prefix(h["prefix"])
                watch_id = h["watch_id"]
                task = asyncio.get_running_loop().create_task(
                    self._pump_watch(conn, watch_id, watch)
                )
                conn.watches[watch_id] = (watch, task)
                await conn.send({"id": rid, "ok": True})
            elif op == "kv.unwatch":
                entry = conn.watches.pop(h["watch_id"], None)
                if entry:
                    entry[0].close()
                    entry[1].cancel()
                await conn.send({"id": rid, "ok": True})
            elif op == "lease.grant":
                lease = await f.grant_lease(h["ttl"])
                conn.leases.add(lease)
                await conn.send({"id": rid, "ok": True, "lease": lease})
            elif op == "lease.reattach":
                # Post-restart/reconnect session re-establishment:
                # re-create (or refresh) the client's lease under its
                # ORIGINAL id so its re-puts keep their liveness binding.
                await f.reattach_lease(h["lease"], h["ttl"])
                # The lease now belongs to THIS connection. Disown it from
                # any lingering half-dead connection of the same client —
                # otherwise that conn's eventual _cleanup would revoke the
                # reattached lease and silently delete every re-put key.
                for other in self._conns:
                    if other is not conn:
                        other.leases.discard(h["lease"])
                conn.leases.add(h["lease"])
                await conn.send({"id": rid, "ok": True})
            elif op == "lease.keepalive":
                ok = await f.keepalive(h["lease"])
                await conn.send({"id": rid, "ok": True, "alive": ok})
            elif op == "lease.revoke":
                conn.leases.discard(h["lease"])
                await f.revoke_lease(h["lease"])
                await conn.send({"id": rid, "ok": True})
            elif op == "bus.pub":
                await f.publish(h["subject"], h.get("header"), payload)
                if rid is not None:
                    await conn.send({"id": rid, "ok": True})
            elif op == "bus.sub":
                # resume semantics (replay ring, local.py): a reconnecting
                # subscriber passes its last-seen seq + the epoch it was
                # minted under; an epoch mismatch (broker restarted
                # without its WAL) invalidates the cursor — replay the
                # whole ring (the client has seen none of THIS epoch) and
                # flag the gap so sequencing consumers resync.
                resume = h.get("resume")
                from_seq = None
                epoch_gap = False
                if resume is not None:
                    if h.get("epoch") == getattr(f, "epoch", None):
                        from_seq = int(resume)
                    else:
                        from_seq = 0
                        epoch_gap = True
                # baseline read BEFORE registration (no await between —
                # LocalFabric.subscribe never yields): a publish racing
                # this dispatch either lands pre-registration (seq <=
                # baseline, not queued for us) or post (seq > baseline,
                # queued and passes the client's duplicate guard)
                base_seq = getattr(f, "pub_seq", 0)
                sub = await f.subscribe(h["subject"], from_seq=from_seq)
                sub_id = h["sub_id"]
                # reply BEFORE the pump starts: the client must learn the
                # epoch/seq baseline before any replayed push arrives, or
                # its duplicate guard could drop legitimate replays
                await conn.send(
                    {
                        "id": rid, "ok": True,
                        "seq": base_seq,
                        "epoch": getattr(f, "epoch", ""),
                        "gap": bool(
                            epoch_gap or getattr(sub, "resume_gap", False)
                        ),
                    }
                )
                task = asyncio.get_running_loop().create_task(
                    self._pump_sub(conn, sub_id, sub)
                )
                conn.subs[sub_id] = (sub, task)
            elif op == "bus.unsub":
                entry = conn.subs.pop(h["sub_id"], None)
                if entry:
                    entry[0].close()
                    entry[1].cancel()
                await conn.send({"id": rid, "ok": True})
            elif op == "queue.push":
                await f.queue_push(h["queue"], h.get("header"), payload)
                await conn.send({"id": rid, "ok": True})
            elif op == "queue.pop":
                item = await f.queue_pop(h["queue"], h.get("timeout"))
                if item is None:
                    await conn.send({"id": rid, "ok": True, "found": False})
                else:
                    conn.inflight.add((h["queue"], item.item_id))
                    try:
                        await conn.send(
                            {
                                "id": rid, "ok": True, "found": True,
                                "item_id": item.item_id, "header": item.header,
                            },
                            item.payload,
                        )
                    except Exception:
                        # consumer died between pop and send: put it back
                        conn.inflight.discard((h["queue"], item.item_id))
                        await f.queue_nack(h["queue"], item.item_id)
                        raise
            elif op == "queue.ack":
                conn.inflight.discard((h["queue"], h["item_id"]))
                await f.queue_ack(h["queue"], h["item_id"])
                await conn.send({"id": rid, "ok": True})
            elif op == "queue.nack":
                conn.inflight.discard((h["queue"], h["item_id"]))
                await f.queue_nack(h["queue"], h["item_id"])
                await conn.send({"id": rid, "ok": True})
            elif op == "queue.len":
                n = await f.queue_len(h["queue"])
                await conn.send({"id": rid, "ok": True, "len": n})
            elif op == "obj.put":
                await f.obj_put(h["name"], payload)
                await conn.send({"id": rid, "ok": True})
            elif op == "obj.get":
                data = await f.obj_get(h["name"])
                await conn.send(
                    {"id": rid, "ok": True, "found": data is not None},
                    data or b"",
                )
            elif op == "obj.delete":
                deleted = await f.obj_delete(h["name"])
                await conn.send({"id": rid, "ok": True, "deleted": deleted})
            elif op == "stats":
                await conn.send({"id": rid, "ok": True, "stats": self.stats()})
            elif op == "ping":
                await conn.send({"id": rid, "ok": True})
            elif op == "repl.subscribe":
                # warm-standby bootstrap + live tail: snapshot-as-WAL
                # records first, then every journaled mutation as it
                # happens. snapshot_records() and repl_attach() run in
                # ONE synchronous block (no await between), so the
                # snapshot + tail are a consistent cut of the stream.
                if conn.repl is not None:
                    q_old, t_old = conn.repl
                    f.repl_detach(q_old)
                    t_old.cancel()
                records = f.snapshot_records()
                q = f.repl_attach()
                await conn.send(
                    {
                        "id": rid, "ok": True, "epoch": f.epoch,
                        "fence": f.fence, "snapshot": len(records),
                        "seq": f.pub_seq,
                    }
                )
                task = asyncio.get_running_loop().create_task(
                    self._pump_repl(conn, h["sub_id"], records, q)
                )
                conn.repl = (q, task)
            elif op == "repl.ack":
                conn.repl_acked = max(conn.repl_acked, int(h.get("rseq") or 0))
                if rid is not None:
                    await conn.send({"id": rid, "ok": True})
            elif op == "repl.state":
                # fencing probe: peers compare (role, fence) on startup
                # and after promotions to decide who serves
                await conn.send(
                    {
                        "id": rid, "ok": True, "role": self.role,
                        "fence": f.fence, "epoch": f.epoch,
                        "address": self.address,
                    }
                )
            elif op == "repl.fence":
                # a peer claims primaryship at `fence`: a LOWER-fenced
                # primary demotes (answers NotPrimary + redirect from the
                # next op on) instead of split-braining — the promoted
                # standby's fencer loop delivers this to a returning
                # stale primary (fabric/replica.py)
                claimed = int(h.get("fence") or 0)
                demoted = False
                if claimed > f.fence and self.role == "primary":
                    await self.demote(h.get("primary") or None)
                    demoted = True
                await conn.send(
                    {
                        "id": rid, "ok": True, "demoted": demoted,
                        "fence": f.fence, "role": self.role,
                    }
                )
            elif op == "repl.promote":
                # explicit promotion (`run fabric --promote addr`):
                # only meaningful on a broker whose owner wired the hook
                if self.on_promote is None:
                    await conn.send(
                        {
                            "id": rid, "ok": False,
                            "error": "not a standby (no promote hook)",
                        }
                    )
                else:
                    ok = bool(await self.on_promote())
                    await conn.send(
                        {
                            "id": rid, "ok": ok, "role": self.role,
                            "fence": f.fence,
                        }
                    )
            else:
                await conn.send({"id": rid, "ok": False, "error": f"bad op {op}"})
        except Exception as e:  # noqa: BLE001 — report op failures to caller
            logger.exception("fabric op %s failed", op)
            if rid is not None:
                try:
                    await conn.send({"id": rid, "ok": False, "error": str(e)})
                except Exception:
                    pass

    async def _pump_watch(self, conn: _Conn, watch_id: int, watch) -> None:
        async for ev in watch:
            await conn.send(
                {
                    "push": "watch", "watch_id": watch_id, "kind": ev.kind,
                    "key": ev.key,
                },
                ev.value or b"",
            )

    async def _pump_sub(self, conn: _Conn, sub_id: int, sub) -> None:
        async for msg in sub:
            await conn.send(
                {
                    "push": "msg", "sub_id": sub_id, "subject": msg.subject,
                    "header": msg.header, "seq": msg.seq,
                },
                msg.payload,
            )

    async def _pump_repl(self, conn: _Conn, sub_id: int, records, q) -> None:
        """Ship the snapshot, then the live journal tail. Each frame
        carries a per-subscription record seq (`rseq`) the standby acks
        back (`repl.ack`) — delivered minus acked is its lag. A standby
        dropping mid-pump just ends the pump (it re-bootstraps on
        reconnect); the queue is detached either way so the journal tap
        stops feeding a dead subscriber."""
        rseq = 0
        try:
            for h, p in records:
                rseq += 1
                conn.repl_delivered = rseq
                await conn.send(
                    {"push": "repl", "sub_id": sub_id, "rseq": rseq,
                     "r": h},
                    p,
                )
            while True:
                item = await q.get()
                if item is None:
                    # the journal tap dropped us (backlog past the cap)
                    # or the fabric closed: the standby sees the stream
                    # end and re-bootstraps from a fresh snapshot
                    await conn.send(
                        {"push": "repl", "sub_id": sub_id, "reset": True}
                    )
                    return
                h, p = item
                rseq += 1
                conn.repl_delivered = rseq
                await conn.send(
                    {"push": "repl", "sub_id": sub_id, "rseq": rseq,
                     "r": h},
                    p,
                )
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass
        finally:
            self.fabric.repl_detach(q)

    async def demote(self, primary_address: Optional[str]) -> None:
        """Fence this broker out: flip to standby (every subsequent data
        op answers NotPrimary + redirect) and hand control to the owner
        hook so it can start tailing the new primary."""
        if self.role != "primary":
            self.primary_address = primary_address or self.primary_address
            return
        self.role = "standby"
        self.primary_address = primary_address
        self.demotions_total += 1
        logger.warning(
            "broker demoted (stale fence %d); redirecting to %s",
            self.fabric.fence, primary_address,
        )
        from dynamo_tpu.telemetry import events

        events.record(
            "broker_demote", severity="warning", source=self.address,
            fence=self.fabric.fence, primary=str(primary_address or ""),
        )
        if self.on_demote is not None:
            try:
                await self.on_demote(primary_address)
            except Exception:
                logger.exception("demote hook failed")

    def kill(self) -> None:
        """Abrupt death for chaos tests / the blackout bench: abort every
        connection and the listener with NO cleanup (the in-process
        equivalent of SIGKILL — leases survive server-side, clients see
        a hard connection loss)."""
        if self._server is not None:
            self._server.close()
        for conn in list(self._conns):
            for t in list(conn.tasks):
                t.cancel()
            for _, (w, task) in conn.watches.items():
                w.close()
                task.cancel()
            for _, (s, task) in conn.subs.items():
                s.close()
                task.cancel()
            if conn.repl is not None:
                self.fabric.repl_detach(conn.repl[0])
                conn.repl[1].cancel()
            transport = conn.writer.transport
            if transport is not None:
                transport.abort()
        self._conns.clear()


async def _amain(args) -> None:
    server = FabricServer(args.host, args.port, persist_dir=args.persist_dir)
    await server.start()
    print(f"fabric listening on {server.address}", flush=True)
    await asyncio.Event().wait()


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo-tpu fabric server")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=4222)
    p.add_argument(
        "--persist-dir", default=None, dest="persist_dir",
        help="WAL directory: state survives server restarts",
    )
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()

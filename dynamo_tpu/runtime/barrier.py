"""Leader/worker rendezvous barrier over the fabric KV store.

Multi-host model serving needs a bring-up handshake before any collective
runs: the leader publishes the serving plan (mesh shape, coordinator
address, engine config digest) and blocks until every expected worker has
registered; workers register and block until the leader's payload is
visible. Reference parity: leader_worker_barrier.rs:26-121 (`barrier_key`
+ `wait_for_key_count`) — here rebuilt on the fabric store's `create` +
`watch_prefix` primitives instead of etcd, so one mechanism serves both
the in-process MemStore and the TCP fabric.

Keys (namespaced under the barrier id):
    barrier/{id}/leader            -> leader payload (the plan)
    barrier/{id}/worker/{worker}   -> worker payload (usually empty)

Both sides are idempotent per (id, role): re-entering the same barrier
with the same worker id succeeds (the create that loses the race is
treated as already-registered). A barrier id is single-use by contract —
reusing one after a completed rendezvous returns immediately with the
old payload, which is exactly the crash-restart behavior we want (a
restarted worker re-reads the plan instead of deadlocking).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from dynamo_tpu.runtime.store import KeyValueStore

__all__ = ["BarrierTimeout", "leader_sync", "worker_sync"]


class BarrierTimeout(TimeoutError):
    """Rendezvous did not complete in time; carries who was missing."""


def _prefix(barrier_id: str) -> str:
    return f"barrier/{barrier_id}/"


async def leader_sync(
    store: KeyValueStore,
    barrier_id: str,
    num_workers: int,
    payload: bytes,
    *,
    timeout: Optional[float] = None,
    lease_id: Optional[str] = None,
) -> list[str]:
    """Publish `payload` and wait until `num_workers` distinct workers
    have registered. Returns the sorted worker ids.

    The payload is published BEFORE waiting (workers may arrive first and
    must be able to read the plan immediately). With `lease_id`, all
    barrier keys die with the leader's lease — a crashed bring-up cleans
    itself up instead of wedging the next attempt.
    """
    key = _prefix(barrier_id) + "leader"
    created = await store.create(key, payload, lease_id=lease_id)
    if not created:
        existing = await store.get(key)
        if existing != payload:
            raise RuntimeError(
                f"barrier {barrier_id!r} already has a leader with a "
                "different payload"
            )
    worker_prefix = _prefix(barrier_id) + "worker/"

    async def _wait() -> list[str]:
        # Subscribe BEFORE the snapshot so registrations that land
        # between the two are seen on the watch rather than lost.
        watch = await store.watch_prefix(worker_prefix)
        try:
            seen = set((await store.get_prefix(worker_prefix)).keys())
            while len(seen) < num_workers:
                ev = await watch.next()
                if ev is None:
                    raise RuntimeError("store closed during barrier wait")
                if ev.kind == "put":
                    seen.add(ev.key)
            return sorted(k[len(worker_prefix):] for k in seen)
        finally:
            watch.close()

    try:
        return await asyncio.wait_for(_wait(), timeout)
    except asyncio.TimeoutError:
        have = await store.get_prefix(worker_prefix)
        raise BarrierTimeout(
            f"barrier {barrier_id!r}: {len(have)}/{num_workers} workers "
            f"after {timeout}s (have: "
            f"{sorted(k[len(worker_prefix):] for k in have)})"
        ) from None


async def worker_sync(
    store: KeyValueStore,
    barrier_id: str,
    worker_id: str,
    *,
    payload: bytes = b"",
    timeout: Optional[float] = None,
    lease_id: Optional[str] = None,
) -> bytes:
    """Register under the barrier and wait for the leader's payload."""
    key = _prefix(barrier_id) + "worker/" + worker_id
    await store.create(key, payload, lease_id=lease_id)  # lost race == re-entry
    leader_key = _prefix(barrier_id) + "leader"

    async def _wait() -> bytes:
        watch = await store.watch_prefix(leader_key)
        try:
            data = await store.get(leader_key)
            while data is None:
                ev = await watch.next()
                if ev is None:
                    raise RuntimeError("store closed during barrier wait")
                if ev.kind == "put":
                    data = ev.value
            return data
        finally:
            watch.close()

    try:
        return await asyncio.wait_for(_wait(), timeout)
    except asyncio.TimeoutError:
        raise BarrierTimeout(
            f"barrier {barrier_id!r}: leader payload not published after "
            f"{timeout}s (worker {worker_id!r} is registered)"
        ) from None

"""Logical naming tree + instance discovery.

Namespace → Component → Endpoint naming with lease-bound instance
registration and prefix-watch discovery (reference: lib/runtime/src/
component.rs — Namespace :408, Component :114, Endpoint :263, Instance :92;
etcd path per instance :348). An Instance record points at the worker's
ingress TCP address; clients keep a live instance set from a watch and
route per RouterMode.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

import msgpack

from dynamo_tpu.runtime.store import Watch, WatchEvent

logger = logging.getLogger(__name__)

INSTANCE_ROOT = "v1/instances"
MODEL_ROOT = "v1/models"
DEFAULT_LEASE_TTL = 3.0


@dataclass(frozen=True)
class Instance:
    namespace: str
    component: str
    endpoint: str
    instance_id: str
    host: str
    port: int
    metadata: dict[str, Any] = field(default_factory=dict)

    @property
    def path(self) -> str:
        return instance_key(
            self.namespace, self.component, self.endpoint, self.instance_id
        )

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)

    def pack(self) -> bytes:
        return msgpack.packb(
            {
                "namespace": self.namespace,
                "component": self.component,
                "endpoint": self.endpoint,
                "instance_id": self.instance_id,
                "host": self.host,
                "port": self.port,
                "metadata": self.metadata,
            },
            use_bin_type=True,
        )

    @staticmethod
    def unpack(data: bytes) -> "Instance":
        d = msgpack.unpackb(data, raw=False)
        return Instance(**d)


def endpoint_prefix(namespace: str, component: str, endpoint: str) -> str:
    return f"{INSTANCE_ROOT}/{namespace}/{component}/{endpoint}/"


def instance_key(
    namespace: str, component: str, endpoint: str, instance_id: str
) -> str:
    return endpoint_prefix(namespace, component, endpoint) + instance_id


class EndpointRegistration:
    """A live (endpoint × lease) registration; revoking the lease (or the
    process dying and missing keepalives) erases it everywhere."""

    def __init__(
        self, fabric, instance: Instance, lease_id: str, owns_lease: bool
    ):
        self.fabric = fabric
        self.instance = instance
        self.lease_id = lease_id
        #: False when riding the process's shared primary lease — then
        #: deregister only deletes this key (revoking would erase every
        #: registration of the process).
        self.owns_lease = owns_lease

    @classmethod
    async def register(
        cls,
        fabric,
        namespace: str,
        component: str,
        endpoint: str,
        host: str,
        port: int,
        metadata: Optional[dict] = None,
        lease_ttl: float = DEFAULT_LEASE_TTL,
        lease_id: Optional[str] = None,
        instance_id: Optional[str] = None,
    ) -> "EndpointRegistration":
        """`instance_id` lets a worker KEEP its identity across a role
        flip (deregister from one endpoint, re-register under another):
        KV events, metrics frames, and router prefix indexes stay keyed
        to the same id, so the flipped worker's hot KV pages remain
        routable (docs/operations.md "Closed-loop autoscaling & role
        flips")."""
        owns_lease = lease_id is None
        if lease_id is None:
            lease_id = await fabric.grant_lease(lease_ttl)
        inst = Instance(
            namespace=namespace,
            component=component,
            endpoint=endpoint,
            instance_id=instance_id or uuid.uuid4().hex[:12],
            host=host,
            port=port,
            metadata=metadata or {},
        )
        await fabric.put(inst.path, inst.pack(), lease_id=lease_id)
        logger.info("registered %s at %s:%d", inst.path, host, port)
        return cls(fabric, inst, lease_id, owns_lease)

    async def deregister(self) -> None:
        await self.fabric.delete(self.instance.path)
        if self.owns_lease:
            await self.fabric.revoke_lease(self.lease_id)


class InstanceSource:
    """Live set of instances for one endpoint, fed by a prefix watch."""

    def __init__(self, fabric, namespace: str, component: str, endpoint: str):
        self.fabric = fabric
        self.prefix = endpoint_prefix(namespace, component, endpoint)
        self.instances: dict[str, Instance] = {}
        self._watch: Optional[Watch] = None
        self._task: Optional[asyncio.Task] = None
        self._changed = asyncio.Event()

    async def start(self) -> None:
        self._watch = await self.fabric.watch_prefix(self.prefix)
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        async for ev in self._watch:
            if ev.kind == "put":
                inst = Instance.unpack(ev.value)
                self.instances[inst.instance_id] = inst
            elif ev.kind == "reset":
                # reconnected after a fabric outage: current state replays
                # as puts next — drop instances that may have died meanwhile
                self.instances.clear()
            else:
                iid = ev.key.rsplit("/", 1)[-1]
                self.instances.pop(iid, None)
            self._changed.set()

    def list(self) -> list[Instance]:
        return sorted(self.instances.values(), key=lambda i: i.instance_id)

    def mark_down(self, instance_id: str) -> None:
        """Active fault detection: drop locally before the lease expires."""
        if self.instances.pop(instance_id, None) is not None:
            logger.warning("marked instance %s down", instance_id)
            self._changed.set()

    async def wait_for_instances(self, timeout: float = 5.0) -> list[Instance]:
        deadline = asyncio.get_running_loop().time() + timeout
        while not self.instances:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(f"no instances under {self.prefix}")
            self._changed.clear()
            try:
                await asyncio.wait_for(self._changed.wait(), remaining)
            except asyncio.TimeoutError:
                pass
        return self.list()

    async def stop(self) -> None:
        if self._watch:
            self._watch.close()
        if self._task:
            self._task.cancel()

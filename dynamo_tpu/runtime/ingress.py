"""Worker ingress: the response-stream data plane.

A TCP server on each worker that accepts pushed requests and streams
responses back on the same connection, multiplexed by request id —
collapsing the reference's NATS-push + separate-TCP-response pair
(network.rs Ingress :279 + tcp/server.rs) into one direct, checksummed
stream per client↔worker pair (fewer hops; the fabric stays control-only).

Handler contract: `async def handler(context, request) -> AsyncIterator`
yielding msgpack-able responses. Client-side cancel frames cancel the
context mid-stream.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Awaitable, Callable, Optional

from dynamo_tpu import telemetry
from dynamo_tpu.runtime.codec import encode_frame, read_frame
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.overload import OverloadedError
from dynamo_tpu.testing import faults

logger = logging.getLogger(__name__)

Handler = Callable[[Context, dict], AsyncIterator]


class RetryableHandlerError(RuntimeError):
    """Handler failure that is safe to retry on ANOTHER instance (e.g. the
    worker's external engine subprocess is down/restarting). The error
    frame carries retryable=true; PushRouter marks the instance down and
    retries elsewhere if the stream hasn't produced data yet."""


class IngressServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: dict[str, Handler] = {}
        self._server: Optional[asyncio.Server] = None
        #: inflight request contexts by (connection id, request id)
        self._inflight: dict[tuple[int, str], Context] = {}
        self._conn_ids = iter(range(1, 1 << 62))
        self._writers: set[asyncio.StreamWriter] = set()

    def add_handler(self, endpoint: str, handler: Handler) -> None:
        self._handlers[endpoint] = handler

    @property
    def num_inflight(self) -> int:
        """Live handler calls (used by graceful drain)."""
        return len(self._inflight)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("ingress on %s:%d", self.host, self.port)

    def drop_connections(self) -> None:
        """Abruptly close every live client connection (the server keeps
        listening). Chaos harness primitive: to a router this is exactly
        a network partition / process death mid-stream — in-flight
        frames stop, the read loop sees EOF, streams drop."""
        for w in list(self._writers):
            w.close()

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # wait_closed() (3.12) waits for connection handlers too — kill
            # live connections first or a stop with connected clients hangs.
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()

    async def _handle(self, reader, writer) -> None:
        conn_id = next(self._conn_ids)
        self._writers.add(writer)
        write_lock = asyncio.Lock()
        tasks: set[asyncio.Task] = set()
        try:
            while True:
                header, payload = await read_frame(reader)
                op = header.get("op")
                if op == "call":
                    # Register the context BEFORE yielding to the loop, so a
                    # cancel frame buffered in the same read batch finds it.
                    rid = header["request_id"]
                    ctx = Context(
                        request_id=rid, metadata=header.get("metadata") or {}
                    )
                    self._inflight[(conn_id, rid)] = ctx
                    t = asyncio.get_running_loop().create_task(
                        self._serve_call(
                            conn_id, ctx, header, payload, writer, write_lock
                        )
                    )
                    tasks.add(t)
                    t.add_done_callback(tasks.discard)
                elif op == "cancel":
                    ctx = self._inflight.get((conn_id, header["request_id"]))
                    if ctx is not None:
                        ctx.cancel()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            # connection gone: cancel everything it had in flight
            for (cid, rid), ctx in list(self._inflight.items()):
                if cid == conn_id:
                    ctx.cancel()
                    self._inflight.pop((cid, rid), None)
            for t in tasks:
                t.cancel()
            self._writers.discard(writer)
            writer.close()

    async def _serve_call(
        self, conn_id: int, ctx: Context, header, payload: bytes, writer,
        write_lock,
    ) -> None:
        import msgpack

        rid = header["request_id"]
        endpoint = header.get("endpoint", "")

        async def send(h, p=b""):
            async with write_lock:
                writer.write(encode_frame(h, p))
                await writer.drain()

        try:
            # fault-injection hook (dynamo_tpu/testing/faults.py): an
            # injected error/drop surfaces as a handler error frame
            await faults.fire("ingress.call", endpoint=endpoint)
            handler = self._handlers.get(endpoint)
            if handler is None:
                await send(
                    {"op": "error", "request_id": rid,
                     "message": f"no handler for endpoint {endpoint!r}"}
                )
                return
            request = msgpack.unpackb(payload, raw=False) if payload else None
            # the trace context rode the call header's metadata (PushRouter
            # injects it); this span is the worker-side stitch point every
            # engine/disagg span below nests under (same task => contextvar)
            with telemetry.span(
                f"worker.{endpoint}", service="worker",
                parent=telemetry.extract(ctx.metadata),
                attrs={"request_id": rid},
            ) as wspan:
                n_items = 0
                async for item in handler(ctx, request):
                    if ctx.cancelled:
                        break
                    n_items += 1
                    if n_items == 1:
                        wspan.add_event("first_item")
                    await send(
                        {"op": "data", "request_id": rid},
                        msgpack.packb(item, use_bin_type=True),
                    )
                wspan.set_attr("items", n_items)
            await send({"op": "end", "request_id": rid, "cancelled": ctx.cancelled})
        except asyncio.CancelledError:
            try:
                await send({"op": "end", "request_id": rid, "cancelled": True})
            except Exception:
                pass
        except Exception as e:  # noqa: BLE001 — stream errors to the caller
            logger.exception("handler error for %s", endpoint)
            frame = {
                "op": "error", "request_id": rid, "message": str(e),
                "retryable": isinstance(e, RetryableHandlerError),
            }
            if isinstance(e, OverloadedError):
                # bounded admission refused this request: the worker is
                # healthy, so the router retries ANOTHER instance without
                # marking this one down, and the frontend answers 429
                # with the Retry-After hint
                frame["code"] = "overloaded"
                if e.retry_after_s is not None:
                    frame["retry_after_s"] = e.retry_after_s
            try:
                await send(frame)
            except Exception:
                pass
        finally:
            self._inflight.pop((conn_id, rid), None)

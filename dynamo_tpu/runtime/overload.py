"""Overload-protection primitives shared across the stack.

The graceful-degradation plane (docs/operations.md "Overload &
draining") has three load-bearing pieces that must agree on types:

- `OverloadedError`: a worker (or the frontend's own admission gate)
  refusing work because a bounded queue is full. Carries the
  `retry_after_s` hint computed from the live SLO sketches; the HTTP
  frontend maps it to 429 + `Retry-After`. Deliberately NOT a
  RetryableHandlerError: an overloaded worker is healthy, so the router
  retries a different instance WITHOUT marking this one down.

- `estimate_retry_after_s`: prices "when is capacity likely to free"
  from a telemetry/slo.py SloTracker — p95 ITL x queue depth (how long
  the queue ahead takes to drain in the decode-bound regime), floored
  by the median request residency (e2e p50). Clamped to [1, 30] s so a
  cold sketch can't tell clients to hammer or to go away for an hour.

- `deadline_guard`: wraps an engine stream with an absolute deadline
  (epoch seconds): on expiry the request context is cancelled (which
  propagates cancel frames to subprocess children and remote workers)
  and the stream error-finishes instead of hanging the client.

Deadlines are absolute epoch times so they survive process hops
(frontend -> router -> worker -> disagg -> external child); multi-host
deployments assume loosely NTP-synced clocks, same as the reference.
"""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator, Optional


class OverloadedError(RuntimeError):
    """Bounded admission refused this request (queue full / shed)."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


#: Retry-After clamp: never tell a client "retry immediately" or
#: "come back in an hour" off a cold or pathological sketch
RETRY_AFTER_MIN_S = 1.0
RETRY_AFTER_MAX_S = 30.0


def estimate_retry_after_s(
    tracker, queue_depth: int = 0, default_s: float = 1.0
) -> float:
    """Retry-After from a telemetry/slo.py SloTracker (None-safe)."""
    est_ms = 0.0
    if tracker is not None:
        itl = tracker.sketches.get("itl_ms")
        if itl is not None and itl.count:
            p95 = itl.quantile(0.95)
            if p95:
                est_ms = p95 * max(queue_depth, 1)
        e2e = tracker.sketches.get("e2e_ms")
        if e2e is not None and e2e.count:
            p50 = e2e.quantile(0.5)
            if p50:
                est_ms = max(est_ms, p50)
    est_s = est_ms / 1000.0 if est_ms else default_s
    return min(max(est_s, RETRY_AFTER_MIN_S), RETRY_AFTER_MAX_S)


async def deadline_guard(
    context, deadline: float, stream: AsyncIterator[dict]
) -> AsyncIterator[dict]:
    """Enforce an absolute deadline over an engine stream: items pass
    through until the deadline, then the context is cancelled (cancel
    frames reach subprocess children / remote workers) and one final
    error-finish item ends the stream cleanly."""
    it = stream.__aiter__()
    expired = False
    try:
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                expired = True
                break
            try:
                item = await asyncio.wait_for(it.__anext__(), remaining)
            except StopAsyncIteration:
                return
            except asyncio.TimeoutError:
                expired = True
                break
            yield item
        # the error finish must go out BEFORE the context is cancelled:
        # the ingress send loop drops items once ctx.cancelled, and a
        # silently truncated stream would read as a clean finish
        if expired:
            yield {"token_ids": [], "finish_reason": "error"}
    finally:
        if expired:
            context.cancel()
            aclose = getattr(it, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass

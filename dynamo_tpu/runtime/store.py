"""Key-value store abstraction with leases and prefix watches.

The control-plane seam: everything above (component registration, model
cards, discovery, barriers) talks to this interface; the backend is either
the in-process MemStore (unit tests need no infra — the reference's
storage/key_value_store/mem.rs lesson, SURVEY.md §4) or the fabric server's
store (production). Liveness is lease-scoped: a key bound to a lease is
deleted when the lease expires, which is the entire crash-detection story
(reference: etcd primary lease — transports/etcd.rs:78).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from dataclasses import dataclass, field
from typing import AsyncIterator, Literal, Optional, Protocol


@dataclass(frozen=True)
class WatchEvent:
    #: "reset" = the watch's channel re-established after an outage:
    #: consumers drop derived state; the server replays current state as
    #: puts immediately after
    kind: Literal["put", "delete", "reset"]
    key: str
    value: Optional[bytes] = None


@dataclass
class KvEntry:
    key: str
    value: bytes
    lease_id: Optional[str] = None


class KeyValueStore(Protocol):
    async def put(
        self, key: str, value: bytes, lease_id: Optional[str] = None
    ) -> None: ...

    async def create(
        self, key: str, value: bytes, lease_id: Optional[str] = None
    ) -> bool:
        """Put only if absent; returns False if the key exists."""
        ...

    async def get(self, key: str) -> Optional[bytes]: ...

    async def get_prefix(self, prefix: str) -> dict[str, bytes]: ...

    async def delete(self, key: str) -> bool: ...

    async def watch_prefix(self, prefix: str) -> "Watch": ...

    async def grant_lease(self, ttl: float) -> str: ...

    async def keepalive(self, lease_id: str) -> bool: ...

    async def revoke_lease(self, lease_id: str) -> None: ...


class Watch:
    """A stream of WatchEvents for a key prefix. Initial state is replayed
    as synthetic 'put' events so consumers need no separate list+watch."""

    def __init__(self):
        self.queue: asyncio.Queue[Optional[WatchEvent]] = asyncio.Queue()
        self._closed = False

    def _push(self, event: Optional[WatchEvent]) -> None:
        if not self._closed:
            self.queue.put_nowait(event)

    async def __aiter__(self) -> AsyncIterator[WatchEvent]:
        while True:
            ev = await self.queue.get()
            if ev is None:
                return
            yield ev

    async def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        if timeout is None:
            return await self.queue.get()
        try:
            return await asyncio.wait_for(self.queue.get(), timeout)
        except asyncio.TimeoutError:
            return None

    def close(self) -> None:
        self._closed = True
        self.queue.put_nowait(None)


class MemStore:
    """In-process KeyValueStore with real lease expiry and watches."""

    def __init__(self):
        self._data: dict[str, KvEntry] = {}
        self._leases: dict[str, float] = {}  # lease_id -> deadline
        self._lease_ttl: dict[str, float] = {}
        self._lease_keys: dict[str, set[str]] = {}
        #: leases restored after a broker restart/promotion, counting
        #: down their orphan-grace window until the owner reattaches
        #: (fabric/persist.py orphan_leases) — a stats gauge, and the
        #: failover runbook's "did everyone find the new primary" signal
        self._orphaned: set[str] = set()
        self._watches: list[tuple[str, Watch]] = []
        self._reaper: Optional[asyncio.Task] = None

    def _ensure_reaper(self) -> None:
        if self._reaper is None or self._reaper.done():
            self._reaper = asyncio.get_running_loop().create_task(
                self._reap_loop()
            )

    async def _reap_loop(self) -> None:
        while True:
            await asyncio.sleep(0.05)
            now = time.monotonic()
            for lease_id in [
                l for l, dl in self._leases.items() if dl < now
            ]:
                await self.revoke_lease(lease_id)

    def _notify(self, event: WatchEvent) -> None:
        dead = [w for w in self._watches if w[1]._closed]
        for entry in dead:
            self._watches.remove(entry)
        for prefix, watch in self._watches:
            if event.key.startswith(prefix):
                watch._push(event)

    # -- kv ----------------------------------------------------------------

    async def put(self, key, value, lease_id=None) -> None:
        if lease_id is not None:
            if lease_id not in self._leases:
                raise KeyError(f"unknown lease {lease_id}")
        prev = self._data.get(key)
        if prev is not None and prev.lease_id and prev.lease_id != lease_id:
            # rebinding: the old lease's expiry must not delete the new entry
            self._lease_keys.get(prev.lease_id, set()).discard(key)
        if lease_id is not None:
            self._lease_keys.setdefault(lease_id, set()).add(key)
        self._data[key] = KvEntry(key, value, lease_id)
        self._notify(WatchEvent("put", key, value))

    async def create(self, key, value, lease_id=None) -> bool:
        if key in self._data:
            return False
        await self.put(key, value, lease_id)
        return True

    async def get(self, key) -> Optional[bytes]:
        e = self._data.get(key)
        return e.value if e else None

    async def get_prefix(self, prefix) -> dict[str, bytes]:
        return {
            k: e.value for k, e in self._data.items() if k.startswith(prefix)
        }

    async def delete(self, key) -> bool:
        e = self._data.pop(key, None)
        if e is None:
            return False
        if e.lease_id and e.lease_id in self._lease_keys:
            self._lease_keys[e.lease_id].discard(key)
        self._notify(WatchEvent("delete", key))
        return True

    # -- watches -----------------------------------------------------------

    async def watch_prefix(self, prefix) -> Watch:
        w = Watch()
        for k, e in self._data.items():
            if k.startswith(prefix):
                w._push(WatchEvent("put", k, e.value))
        self._watches.append((prefix, w))
        return w

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl: float) -> str:
        self._ensure_reaper()
        lease_id = uuid.uuid4().hex[:16]
        self._leases[lease_id] = time.monotonic() + ttl
        self._lease_ttl[lease_id] = ttl
        self._lease_keys[lease_id] = set()
        return lease_id

    async def keepalive(self, lease_id: str) -> bool:
        if lease_id not in self._leases:
            return False
        self._leases[lease_id] = time.monotonic() + self._lease_ttl[lease_id]
        self._orphaned.discard(lease_id)
        return True

    async def reattach_lease(self, lease_id: str, ttl: float) -> bool:
        """Re-establish a lease under its ORIGINAL id after a restart or
        reconnect; True when it had to be re-created (the owner should
        re-put its keys)."""
        if await self.keepalive(lease_id):
            return False
        self._ensure_reaper()
        self._leases[lease_id] = time.monotonic() + ttl
        self._lease_ttl[lease_id] = ttl
        self._lease_keys.setdefault(lease_id, set())
        return True

    async def revoke_lease(self, lease_id: str) -> None:
        self._leases.pop(lease_id, None)
        self._lease_ttl.pop(lease_id, None)
        self._orphaned.discard(lease_id)
        for key in list(self._lease_keys.pop(lease_id, ())):
            await self.delete(key)

    def close(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
            self._reaper = None

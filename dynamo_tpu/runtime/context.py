"""Cancellation tree + request context.

The universal request envelope: every request flowing through pipelines and
over the network carries a Context with a request id and a cancellation
token; cancelling a parent cancels all children (parity with the reference's
AsyncEngineContext / CancellationToken tree — /root/reference
lib/runtime/src/engine.rs:124, lib.rs:69).
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, Optional


class CancellationToken:
    """Hierarchical cancellation: child tokens are cancelled with the parent."""

    def __init__(self, parent: Optional["CancellationToken"] = None):
        self._event = asyncio.Event()
        self._parent = parent
        self._children: list[CancellationToken] = []
        if parent is not None:
            parent._children.append(self)
            if parent.cancelled:
                self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        if self._event.is_set():
            return
        self._event.set()
        for child in self._children:
            child.cancel()

    def child(self) -> "CancellationToken":
        return CancellationToken(parent=self)

    async def wait(self) -> None:
        await self._event.wait()

    def raise_if_cancelled(self) -> None:
        if self.cancelled:
            raise asyncio.CancelledError("context cancelled")


class Context:
    """Request context: id + cancellation + free-form metadata."""

    def __init__(
        self,
        request_id: Optional[str] = None,
        token: Optional[CancellationToken] = None,
        metadata: Optional[dict[str, Any]] = None,
    ):
        self.request_id = request_id or uuid.uuid4().hex
        self.token = token or CancellationToken()
        self.metadata = metadata or {}

    @property
    def cancelled(self) -> bool:
        return self.token.cancelled

    def cancel(self) -> None:
        self.token.cancel()

    def child(self) -> "Context":
        return Context(
            request_id=self.request_id,
            token=self.token.child(),
            metadata=dict(self.metadata),
        )

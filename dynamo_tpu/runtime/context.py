"""Cancellation tree + request context.

The universal request envelope: every request flowing through pipelines and
over the network carries a Context with a request id and a cancellation
token; cancelling a parent cancels all children (parity with the reference's
AsyncEngineContext / CancellationToken tree — /root/reference
lib/runtime/src/engine.rs:124, lib.rs:69).
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, Optional


class CancellationToken:
    """Hierarchical cancellation: child tokens are cancelled with the parent."""

    def __init__(self, parent: Optional["CancellationToken"] = None):
        self._event = asyncio.Event()
        self._parent = parent
        self._children: list[CancellationToken] = []
        if parent is not None:
            parent._children.append(self)
            if parent.cancelled:
                self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        if self._event.is_set():
            return
        self._event.set()
        for child in self._children:
            child.cancel()

    def child(self) -> "CancellationToken":
        return CancellationToken(parent=self)

    async def wait(self) -> None:
        await self._event.wait()

    def raise_if_cancelled(self) -> None:
        if self.cancelled:
            raise asyncio.CancelledError("context cancelled")


#: sentinel returned by queue_get_or_cancelled when cancellation won
CANCELLED = object()


async def queue_get_or_cancelled(context: "Context", q: asyncio.Queue):
    """await q.get() raced against the context's cancellation; returns the
    item, or CANCELLED if cancellation fired first (the caller re-checks
    `context.cancelled` and notifies its peer). The single home for the
    subtle two-task race used by streaming consumers (PushRouter,
    SubprocessEngine): both tasks are always reaped, and a get() that
    completed in the same wakeup as the cancel still delivers its item."""
    get_task = asyncio.ensure_future(q.get())
    cancel_task = asyncio.ensure_future(context.token.wait())
    done, _ = await asyncio.wait(
        {get_task, cancel_task}, return_when=asyncio.FIRST_COMPLETED
    )
    cancel_task.cancel()
    if get_task not in done:
        get_task.cancel()
        return CANCELLED
    return get_task.result()


class Context:
    """Request context: id + cancellation + free-form metadata."""

    def __init__(
        self,
        request_id: Optional[str] = None,
        token: Optional[CancellationToken] = None,
        metadata: Optional[dict[str, Any]] = None,
        deadline: Optional[float] = None,
    ):
        self.request_id = request_id or uuid.uuid4().hex
        self.token = token or CancellationToken()
        self.metadata = metadata or {}
        #: absolute end-to-end deadline (epoch seconds; None = none) —
        #: set by the HTTP frontend, copied onto the PreprocessedRequest
        #: (which is what actually rides the wire)
        self.deadline = deadline

    @property
    def cancelled(self) -> bool:
        return self.token.cancelled

    def cancel(self) -> None:
        self.token.cancel()

    def child(self) -> "Context":
        return Context(
            request_id=self.request_id,
            token=self.token.child(),
            metadata=dict(self.metadata),
            deadline=self.deadline,
        )

"""Checksummed two-part wire framing.

Every cross-process payload rides frames of (header: msgpack, payload: raw
bytes), each length-prefixed and xxh3-checksummed — the reference's
TwoPartCodec contract (/root/reference lib/runtime/src/pipeline/network/
codec/two_part.rs) re-done for asyncio streams. Control messages leave the
payload empty; bulk bytes (token streams, KV pages) ride the payload
untouched by msgpack.

Frame layout:
  u32 header_len | u32 payload_len | u64 xxh3(header) | u64 xxh3(payload)
  | header bytes | payload bytes
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional

import msgpack
import xxhash

_PREFIX = struct.Struct("<IIQQ")

#: refuse absurd frames instead of allocating gigabytes on a corrupt length
MAX_FRAME = 1 << 30


class CodecError(Exception):
    pass


def encode_frame(header: Any, payload: bytes = b"") -> bytes:
    h = msgpack.packb(header, use_bin_type=True)
    return (
        _PREFIX.pack(
            len(h),
            len(payload),
            xxhash.xxh3_64_intdigest(h),
            xxhash.xxh3_64_intdigest(payload),
        )
        + h
        + payload
    )


async def read_frame(reader: asyncio.StreamReader) -> tuple[Any, bytes]:
    prefix = await reader.readexactly(_PREFIX.size)
    hlen, plen, hsum, psum = _PREFIX.unpack(prefix)
    if hlen > MAX_FRAME or plen > MAX_FRAME:
        raise CodecError(f"frame too large: header={hlen} payload={plen}")
    h = await reader.readexactly(hlen)
    p = await reader.readexactly(plen) if plen else b""
    if xxhash.xxh3_64_intdigest(h) != hsum:
        raise CodecError("header checksum mismatch")
    if xxhash.xxh3_64_intdigest(p) != psum:
        raise CodecError("payload checksum mismatch")
    return msgpack.unpackb(h, raw=False), p


def decode_frame(buf: bytes) -> tuple[Any, bytes, int]:
    """Sync variant for tests/tools: returns (header, payload, consumed)."""
    if len(buf) < _PREFIX.size:
        raise CodecError("short buffer")
    hlen, plen, hsum, psum = _PREFIX.unpack(buf[: _PREFIX.size])
    end = _PREFIX.size + hlen + plen
    if len(buf) < end:
        raise CodecError("short buffer")
    h = buf[_PREFIX.size : _PREFIX.size + hlen]
    p = buf[_PREFIX.size + hlen : end]
    if xxhash.xxh3_64_intdigest(h) != hsum or xxhash.xxh3_64_intdigest(p) != psum:
        raise CodecError("checksum mismatch")
    return msgpack.unpackb(h, raw=False), p, end

"""Checksummed two-part wire framing.

Every cross-process payload rides frames of (header: msgpack, payload: raw
bytes), each length-prefixed and xxh3-checksummed — the reference's
TwoPartCodec contract (/root/reference lib/runtime/src/pipeline/network/
codec/two_part.rs) re-done for asyncio streams. Control messages leave the
payload empty; bulk bytes (token streams, KV pages) ride the payload
untouched by msgpack.

Frame layout:
  u32 header_len | u32 payload_len | u64 xxh3(header) | u64 xxh3(payload)
  | header bytes | payload bytes
"""

from __future__ import annotations

import asyncio
import ctypes
import struct
from typing import Any

import msgpack
import xxhash

from dynamo_tpu import native

_PREFIX = struct.Struct("<IIQQ")

#: refuse absurd frames instead of allocating gigabytes on a corrupt length
MAX_FRAME = 1 << 30


class CodecError(Exception):
    pass


def encode_frame(header: Any, payload: bytes = b"") -> bytes:
    h = msgpack.packb(header, use_bin_type=True)
    if len(h) > MAX_FRAME or len(payload) > MAX_FRAME:
        # Mirror the read-side bound: the native path casts lengths to u32,
        # so an oversized input would silently emit a corrupt frame.
        raise CodecError(
            f"frame too large: header={len(h)} payload={len(payload)}"
        )
    lib = native.lib()
    if lib is not None:
        prefix = (ctypes.c_uint8 * _PREFIX.size)()
        lib.dyn_frame_prefix(h, len(h), payload, len(payload), prefix)
        return bytes(prefix) + h + payload
    return (
        _PREFIX.pack(
            len(h),
            len(payload),
            xxhash.xxh3_64_intdigest(h),
            xxhash.xxh3_64_intdigest(payload),
        )
        + h
        + payload
    )


async def write_frame(
    writer: asyncio.StreamWriter, header: Any, parts: list = ()
) -> None:
    """Vectored frame write: identical wire format to encode_frame, but the
    payload is written part-by-part with a STREAMING checksum — no
    concatenation copy of multi-MB KV payloads. `parts` are buffer-likes
    (bytes / memoryview / contiguous array views)."""
    h = msgpack.packb(header, use_bin_type=True)
    views = [memoryview(p).cast("B") for p in parts]
    plen = sum(v.nbytes for v in views)
    if len(h) > MAX_FRAME or plen > MAX_FRAME:
        raise CodecError(f"frame too large: header={len(h)} payload={plen}")
    psum = xxhash.xxh3_64()
    for v in views:
        psum.update(v)
    writer.write(
        _PREFIX.pack(
            len(h), plen, xxhash.xxh3_64_intdigest(h), psum.intdigest()
        )
        + h
    )
    for v in views:
        writer.write(v)
    await writer.drain()


def _check_frame(prefix: bytes, h: bytes, p: bytes) -> None:
    lib = native.lib()
    if lib is not None:
        rc = lib.dyn_frame_check(prefix, h, len(h), p, len(p))
        if rc == 1:
            raise CodecError("header checksum mismatch")
        if rc == 2:
            raise CodecError("payload checksum mismatch")
        return
    _, _, hsum, psum = _PREFIX.unpack(prefix)
    if xxhash.xxh3_64_intdigest(h) != hsum:
        raise CodecError("header checksum mismatch")
    if xxhash.xxh3_64_intdigest(p) != psum:
        raise CodecError("payload checksum mismatch")


async def read_frame(reader: asyncio.StreamReader) -> tuple[Any, bytes]:
    prefix = await reader.readexactly(_PREFIX.size)
    hlen, plen, _, _ = _PREFIX.unpack(prefix)
    if hlen > MAX_FRAME or plen > MAX_FRAME:
        raise CodecError(f"frame too large: header={hlen} payload={plen}")
    h = await reader.readexactly(hlen)
    p = await reader.readexactly(plen) if plen else b""
    _check_frame(prefix, h, p)
    return msgpack.unpackb(h, raw=False), p


def decode_frame(buf: bytes) -> tuple[Any, bytes, int]:
    """Sync variant for tests/tools: returns (header, payload, consumed)."""
    if len(buf) < _PREFIX.size:
        raise CodecError("short buffer")
    hlen, plen, _, _ = _PREFIX.unpack(buf[: _PREFIX.size])
    if hlen > MAX_FRAME or plen > MAX_FRAME:
        raise CodecError(f"frame too large: header={hlen} payload={plen}")
    end = _PREFIX.size + hlen + plen
    if len(buf) < end:
        raise CodecError("short buffer")
    h = buf[_PREFIX.size : _PREFIX.size + hlen]
    p = buf[_PREFIX.size + hlen : end]
    try:
        _check_frame(buf[: _PREFIX.size], h, p)
    except CodecError:
        raise CodecError("checksum mismatch") from None
    return msgpack.unpackb(h, raw=False), p, end

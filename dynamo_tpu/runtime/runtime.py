"""DistributedRuntime: the wiring root every process starts from.

Holds the fabric connection (or an in-process LocalFabric in static mode),
grants the process's primary lease, and hands out namespaced helpers
(reference: DistributedRuntime — lib/runtime/src/distributed.rs:34-85,
is_static mode lib.rs:97).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from dynamo_tpu.runtime.component import (
    DEFAULT_LEASE_TTL,
    EndpointRegistration,
    InstanceSource,
)
from dynamo_tpu.runtime.fabric import LocalFabric, RemoteFabric
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode

logger = logging.getLogger(__name__)

DEFAULT_FABRIC_ADDR = os.environ.get("DYNTPU_FABRIC", "127.0.0.1:4222")


class DistributedRuntime:
    def __init__(self, fabric, primary_lease: Optional[str] = None):
        self.fabric = fabric
        self.primary_lease = primary_lease

    @classmethod
    async def create(
        cls,
        fabric_address: Optional[str] = None,
        static: bool = False,
        lease_ttl: float = DEFAULT_LEASE_TTL,
    ) -> "DistributedRuntime":
        """static=True: no discovery plane, in-process fabric (single-node
        pipelines, tests). Otherwise connect to the fabric server and take
        the process's primary lease."""
        if static:
            fabric = LocalFabric()
        else:
            fabric = await RemoteFabric.connect(
                fabric_address or DEFAULT_FABRIC_ADDR
            )
        lease = await fabric.grant_lease(lease_ttl)
        return cls(fabric, primary_lease=lease)

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    async def close(self) -> None:
        await self.fabric.close()


class Namespace:
    def __init__(self, runtime: DistributedRuntime, name: str):
        self.runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name

    @property
    def _rt(self) -> DistributedRuntime:
        return self.component.namespace.runtime

    @property
    def path(self) -> tuple[str, str, str]:
        return (self.component.namespace.name, self.component.name, self.name)

    async def register(
        self, host: str, port: int, metadata: Optional[dict] = None,
        instance_id: Optional[str] = None,
    ) -> EndpointRegistration:
        ns, comp, ep = self.path
        return await EndpointRegistration.register(
            self._rt.fabric, ns, comp, ep, host, port,
            metadata=metadata, lease_id=self._rt.primary_lease,
            instance_id=instance_id,
        )

    async def instance_source(self) -> InstanceSource:
        ns, comp, ep = self.path
        src = InstanceSource(self._rt.fabric, ns, comp, ep)
        await src.start()
        return src

    async def router(
        self, mode: RouterMode = RouterMode.ROUND_ROBIN, kv_chooser=None,
        replay: bool = False,
    ) -> PushRouter:
        src = await self.instance_source()
        return PushRouter(
            src, self.name, mode=mode, kv_chooser=kv_chooser, replay=replay
        )

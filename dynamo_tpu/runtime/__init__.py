from dynamo_tpu.runtime.context import CancellationToken, Context
from dynamo_tpu.runtime.runtime import (
    Component,
    DistributedRuntime,
    Endpoint,
    Namespace,
)
from dynamo_tpu.runtime.component import (
    EndpointRegistration,
    Instance,
    InstanceSource,
)
from dynamo_tpu.runtime.ingress import IngressServer
from dynamo_tpu.runtime.push_router import (
    EngineStreamError,
    NoInstancesError,
    PushRouter,
    RouterMode,
)
from dynamo_tpu.runtime.store import MemStore, Watch, WatchEvent

__all__ = [
    "CancellationToken",
    "Context",
    "Component",
    "DistributedRuntime",
    "Endpoint",
    "Namespace",
    "EndpointRegistration",
    "Instance",
    "InstanceSource",
    "IngressServer",
    "EngineStreamError",
    "NoInstancesError",
    "PushRouter",
    "RouterMode",
    "MemStore",
    "Watch",
    "WatchEvent",
]

"""PushRouter: client-side instance selection + streaming RPC with fault
detection.

RouterMode round_robin / random / direct / kv (kv delegates the choice to a
KvRouter — dynamo_tpu.router) mirroring the reference's PushRouter
(egress/push_router.rs:43, RouterMode :74). Fault detection: connection
refused or a mid-stream drop marks the instance down locally (the lease
mechanism cleans up globally) and retries on another instance
(generate_with_fault_detection — push_router.rs:185-224).
"""

from __future__ import annotations

import asyncio
import enum
import itertools
import logging
import random
import time
import uuid
from typing import Any, AsyncIterator, Optional

import msgpack

from dynamo_tpu import telemetry
from dynamo_tpu.runtime.codec import encode_frame, read_frame
from dynamo_tpu.runtime.component import Instance, InstanceSource
from dynamo_tpu.runtime.context import (
    CANCELLED,
    Context,
    queue_get_or_cancelled,
)
from dynamo_tpu.runtime.overload import OverloadedError
from dynamo_tpu.telemetry import phases

logger = logging.getLogger(__name__)


class RouterMode(str, enum.Enum):
    ROUND_ROBIN = "round_robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"


class EngineStreamError(Exception):
    pass


class NoInstancesError(Exception):
    pass


class _WorkerConn:
    """One multiplexed TCP connection to a worker's ingress."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.streams: dict[str, asyncio.Queue] = {}
        self.lock = asyncio.Lock()
        self.alive = True
        self._task = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self):
        try:
            while True:
                header, payload = await read_frame(self.reader)
                q = self.streams.get(header.get("request_id"))
                if q is not None:
                    q.put_nowait((header, payload))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self.alive = False
            for q in self.streams.values():
                q.put_nowait(None)

    async def send(self, header, payload=b""):
        async with self.lock:
            self.writer.write(encode_frame(header, payload))
            await self.writer.drain()

    def close(self):
        self.alive = False
        self._task.cancel()
        self.writer.close()


class PushRouter:
    #: retry backoff: capped exponential with full jitter — a retry
    #: storm against a recovering worker arrives spread out, not as a
    #: synchronized hammer (docs/operations.md "Overload & draining")
    RETRY_BACKOFF_BASE_MS = 25.0
    RETRY_BACKOFF_MAX_MS = 500.0

    def __init__(
        self,
        source: InstanceSource,
        endpoint: str,
        mode: RouterMode = RouterMode.ROUND_ROBIN,
        kv_chooser=None,
        retry_backoff_base_ms: Optional[float] = None,
        retry_backoff_max_ms: Optional[float] = None,
        replay: bool = False,
        max_replays: int = 2,
    ):
        self.source = source
        self.endpoint = endpoint
        self.mode = mode
        self.kv_chooser = kv_chooser  # async (request) -> instance_id
        self.retry_backoff_base_ms = (
            self.RETRY_BACKOFF_BASE_MS
            if retry_backoff_base_ms is None
            else retry_backoff_base_ms
        )
        self.retry_backoff_max_ms = (
            self.RETRY_BACKOFF_MAX_MS
            if retry_backoff_max_ms is None
            else retry_backoff_max_ms
        )
        #: crash-replayed streams (default OFF — router behavior is
        #: bit-identical to before when off, pinned by tests): when a
        #: worker dies MID-stream, re-dispatch the request to a survivor
        #: as original-prompt + tokens-emitted-so-far. The survivor
        #: generates strictly the NEXT tokens (the emitted ones are part
        #: of its prompt), so the client stream continues with no
        #: duplicate and no gap — bit-identical for greedy (and the
        #: survivor's prefix cache / G4 onboarding makes the replayed
        #: prefill near-free). Sampled streams resume under a derived
        #: seed (see _replay_request). docs/operations.md "Crash-replayed
        #: streams".
        self.replay = replay
        self.max_replays = max_replays
        self.replays = 0
        self.replayed_streams = 0
        self._rr = itertools.count()
        self._conns: dict[str, _WorkerConn] = {}

    # -- crash replay ------------------------------------------------------

    @staticmethod
    def _replay_eligible(request: Any, emitted: list) -> bool:
        """A stream can replay iff the request is the standard
        PreprocessedRequest dict and the continuation is expressible as
        prompt + emitted tokens: logprob streams can't (their arrays
        must align from the first emitted token), multimodal prompts
        can't (token ids alone don't identify the image embeds)."""
        if not isinstance(request, dict):
            return False
        if not isinstance(request.get("token_ids"), (list, tuple)):
            return False
        lp = request.get("logprobs", -1)
        if lp is not None and int(lp) >= 0:
            return False
        if request.get("mm_embeds") is not None:
            return False
        # penalty state covers GENERATED tokens only (engine/sampling.py
        # deliberately never penalizes the prompt) — a replay turns the
        # emitted tokens INTO prompt, so the survivor would drop all
        # penalty pressure accumulated over them and the continuation
        # (greedy included) could diverge from the lost stream. Refuse:
        # these streams keep the pre-existing error surface.
        if float(request.get("frequency_penalty", 0.0) or 0.0) != 0.0:
            return False
        if float(request.get("presence_penalty", 0.0) or 0.0) != 0.0:
            return False
        if float(request.get("repetition_penalty", 1.0) or 1.0) != 1.0:
            return False
        if len(emitted) >= int(request.get("max_tokens", 0) or 0):
            return False  # nothing left to generate (finish was in flight)
        return True

    def _replay_request(self, request: dict, emitted: list, n: int) -> dict:
        """Build the continuation request: prompt grows by the emitted
        tokens, budgets shrink by them. Greedy continuations are
        bit-identical to the lost stream by construction. Sampled
        continuations resume under a DERIVED seed (seed + replay index
        — deterministic, but a different draw sequence than the dead
        worker would have produced; unseeded requests simply keep
        sampling). Penalty-carrying requests never reach here
        (_replay_eligible refuses them) — documented in
        docs/operations.md."""
        new = dict(request)
        new["token_ids"] = list(request["token_ids"]) + [
            int(t) for t in emitted
        ]
        new["max_tokens"] = int(request.get("max_tokens", 0)) - len(emitted)
        if new.get("min_tokens"):
            new["min_tokens"] = max(
                0, int(new["min_tokens"]) - len(emitted)
            )
        if new.get("seed") is not None:
            new["seed"] = int(new["seed"]) + 1000003 * n
        rid = str(new.get("request_id") or "req")
        new["request_id"] = f"{rid}+r{n}"
        ann = dict(new.get("annotations") or {})
        ann["replay"] = n
        ann["replayed_tokens"] = len(emitted)
        new["annotations"] = ann
        return new

    # -- selection ---------------------------------------------------------

    async def _pick(
        self,
        request: Any,
        instance_id: Optional[str],
        avoid: Optional[str] = None,
    ) -> Instance:
        """`avoid` names an instance whose stream JUST dropped (crash
        replay re-dispatch): mark_down already removed it locally, but a
        racing lease-watch `put` can re-add it while its lease is still
        live — e.g. a handing-over worker that deregistered but has not
        exited. Skip it whenever any other instance exists; the replay
        must land on a survivor."""
        instances = self.source.list()
        if not instances:
            instances = await self.source.wait_for_instances(timeout=2.0)
        if avoid is not None:
            others = [i for i in instances if i.instance_id != avoid]
            if others:
                instances = others
        if self.mode == RouterMode.DIRECT:
            if instance_id is None:
                raise ValueError("direct mode requires instance_id")
            for inst in instances:
                if inst.instance_id == instance_id:
                    return inst
            raise NoInstancesError(f"instance {instance_id} not found")
        if self.mode == RouterMode.KV and self.kv_chooser is not None:
            chosen = await self.kv_chooser(request)
            for inst in instances:
                if inst.instance_id == chosen:
                    return inst
            logger.warning("kv-chosen instance %s gone; falling back", chosen)
        if self.mode == RouterMode.RANDOM:
            return random.choice(instances)
        return instances[next(self._rr) % len(instances)]

    async def _conn_for(self, inst: Instance) -> _WorkerConn:
        conn = self._conns.get(inst.instance_id)
        if conn is not None and conn.alive:
            return conn
        reader, writer = await asyncio.open_connection(inst.host, inst.port)
        conn = _WorkerConn(reader, writer)
        self._conns[inst.instance_id] = conn
        return conn

    # -- the call ----------------------------------------------------------

    async def generate(
        self,
        request: Any,
        context: Optional[Context] = None,
        instance_id: Optional[str] = None,
        max_attempts: int = 3,
    ) -> AsyncIterator[Any]:
        """Push `request`; yields the response stream. Retries on instances
        that fail before producing any output; mid-stream failure surfaces
        as EngineStreamError after marking the instance down."""
        ctx = context or Context()
        attempts = 0
        #: crash-replay bookkeeping (self.replay): cumulative tokens the
        #: client has already received, and the live (possibly rebuilt)
        #: request the next dispatch carries
        emitted: list = []
        replays = 0
        live_request = request
        avoid: Optional[str] = None  # instance whose stream just dropped
        with telemetry.span(
            "router.dispatch", service="router",
            attrs={"endpoint": self.endpoint, "mode": self.mode.value},
        ) as rspan:
            t_dispatch = time.perf_counter()
            dispatched = False  # first response frame seen (any op)
            backoff_total_ms = 0.0

            def _first_frame() -> None:
                nonlocal dispatched
                if not dispatched:
                    dispatched = True
                    phases.observe(
                        "router_dispatch_ms",
                        (time.perf_counter() - t_dispatch) * 1000.0,
                    )
                    rspan.add_event("first_frame")

            async def _retry_backoff() -> None:
                """Capped exponential backoff with full jitter before the
                NEXT attempt; cumulative ms lands on the dispatch span
                beside `attempts` so retry storms are visible."""
                nonlocal backoff_total_ms
                delay_ms = min(
                    self.retry_backoff_max_ms,
                    self.retry_backoff_base_ms * (2 ** (attempts - 1)),
                ) * random.random()
                backoff_total_ms += delay_ms
                rspan.set_attr(
                    "retry_backoff_ms", round(backoff_total_ms, 2)
                )
                if delay_ms > 0:
                    await asyncio.sleep(delay_ms / 1000.0)

            while True:
                attempts += 1
                inst = await self._pick(live_request, instance_id, avoid)
                rspan.set_attr("instance_id", inst.instance_id)
                rspan.set_attr("attempts", attempts)
                try:
                    conn = await self._conn_for(inst)
                except OSError:
                    self.source.mark_down(inst.instance_id)
                    rspan.add_event(
                        "mark_down", instance=inst.instance_id,
                        reason="connect failed",
                    )
                    if attempts >= max_attempts:
                        raise NoInstancesError(
                            f"no reachable instance for {self.endpoint}"
                        )
                    await _retry_backoff()
                    continue

                rid = ctx.request_id + "-" + uuid.uuid4().hex[:6]
                q: asyncio.Queue = asyncio.Queue()
                conn.streams[rid] = q
                try:
                    await conn.send(
                        {
                            "op": "call", "request_id": rid,
                            "endpoint": self.endpoint,
                            # trace context rides the request-header
                            # metadata so the worker's spans stitch under
                            # this dispatch span
                            "metadata": telemetry.inject(
                                dict(ctx.metadata)
                            ),
                        },
                        msgpack.packb(live_request, use_bin_type=True),
                    )
                except (OSError, ConnectionError):
                    conn.streams.pop(rid, None)
                    self.source.mark_down(inst.instance_id)
                    rspan.add_event(
                        "mark_down", instance=inst.instance_id,
                        reason="send failed",
                    )
                    if attempts >= max_attempts:
                        raise NoInstancesError(
                            f"no reachable instance for {self.endpoint}"
                        )
                    await _retry_backoff()
                    continue

                got_data = False
                try:
                    while True:
                        if ctx.cancelled:
                            try:
                                await conn.send({"op": "cancel", "request_id": rid})
                            except Exception:
                                pass
                            return
                        # race q.get() against cancellation so a cancel issued
                        # while idle reaches the worker immediately
                        item = await queue_get_or_cancelled(ctx, q)
                        if item is CANCELLED:
                            continue  # loop re-checks ctx.cancelled and notifies
                        if item is None:  # connection dropped mid-stream
                            avoid = inst.instance_id
                            self.source.mark_down(inst.instance_id)
                            rspan.add_event(
                                "mark_down", instance=inst.instance_id,
                                reason="stream dropped",
                            )
                            if (
                                got_data
                                and self.replay
                                and replays < self.max_replays
                            ):
                                if (
                                    isinstance(request, dict)
                                    and emitted
                                    and len(emitted)
                                    >= int(request.get("max_tokens", 0) or 0)
                                    > 0
                                ):
                                    # the worker died between emitting the
                                    # final token and the finish frame:
                                    # the budget is spent — close the
                                    # stream instead of replaying a
                                    # zero-token continuation
                                    yield {
                                        "token_ids": [],
                                        "finish_reason": "length",
                                    }
                                    return
                                if self._replay_eligible(request, emitted):
                                    replays += 1
                                    self.replays += 1
                                    if replays == 1:
                                        self.replayed_streams += 1
                                    live_request = self._replay_request(
                                        request, emitted, replays
                                    )
                                    rspan.add_event(
                                        "replay",
                                        instance=inst.instance_id,
                                        replayed_tokens=len(emitted),
                                        n=replays,
                                    )
                                    # fleet event timeline: a replayed
                                    # stream is exactly the kind of
                                    # moment an incident reconstruction
                                    # needs on the annotation layer
                                    from dynamo_tpu.telemetry import (
                                        events as _events,
                                    )

                                    _events.record(
                                        "stream_replay",
                                        severity="warning",
                                        source=inst.instance_id,
                                        replayed_tokens=len(emitted),
                                        n=replays,
                                    )
                                    logger.warning(
                                        "replaying stream %s on a survivor "
                                        "(%d tokens already emitted, "
                                        "replay #%d)",
                                        live_request["request_id"],
                                        len(emitted), replays,
                                    )
                                    # fresh stream: pre-data retry logic
                                    # applies to the replay dispatch too
                                    got_data = False
                                    attempts = 0
                                    await _retry_backoff()
                                    break  # re-dispatch to a survivor
                            if got_data or attempts >= max_attempts:
                                raise EngineStreamError(
                                    f"stream from {inst.instance_id} dropped"
                                )
                            await _retry_backoff()
                            break  # retry another instance
                        header, payload = item
                        op = header["op"]
                        _first_frame()
                        if op == "data":
                            got_data = True
                            data = msgpack.unpackb(payload, raw=False)
                            if self.replay and isinstance(data, dict):
                                emitted.extend(data.get("token_ids") or ())
                            yield data
                        elif op == "end":
                            return
                        elif op == "error":
                            if (
                                header.get("code") == "overloaded"
                                and not got_data
                            ):
                                # bounded admission refused: the worker
                                # is healthy (do NOT mark it down) —
                                # back off and try another instance;
                                # exhausted attempts surface as 429 at
                                # the frontend with the Retry-After hint
                                rspan.add_event(
                                    "overloaded",
                                    instance=inst.instance_id,
                                )
                                if attempts >= max_attempts:
                                    raise OverloadedError(
                                        header.get("message")
                                        or "all instances overloaded",
                                        header.get("retry_after_s"),
                                    )
                                await _retry_backoff()
                                break
                            if header.get("retryable") and not got_data:
                                # the worker itself says another instance
                                # should take this (its engine subprocess is
                                # down/restarting): mark down + retry, same
                                # as a pre-stream connection failure
                                self.source.mark_down(inst.instance_id)
                                rspan.add_event(
                                    "mark_down",
                                    instance=inst.instance_id,
                                    reason="retryable error",
                                )
                                if attempts >= max_attempts:
                                    raise EngineStreamError(
                                        header.get("message")
                                    )
                                await _retry_backoff()
                                break
                            raise EngineStreamError(header.get("message"))
                finally:
                    conn.streams.pop(rid, None)

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()

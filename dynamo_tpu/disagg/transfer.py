"""KV page transfer plane: prefill → decode bulk KV movement.

The decode worker runs a KvTransferServer next to its engine; a prefill
worker connects and streams the prompt's KV pages, addressed by the decode
worker's reserved page ids. Pages ride the checksummed two-part framing
(header: page ids + dtype/shape; payload: raw k‖v bytes), then an async
write callback scatters them into the decode engine's device pool and the
request's waiter fires with the first sampled token.

This is the reference's NIXL RDMA KV write (dynamo_flow.md:36-38,
block_manager/storage/nixl.rs) re-designed for TPU. Two strategies share
one control channel and one interface (the reference's pluggable transfer
strategies, block/transfer.rs:83-111):

- **device path** (preferred): the prefill side stages its still-device-
  resident pages on an XLA transfer server and sends a tiny "offer" frame;
  the decode side pulls the bytes device-to-device over the PjRt transfer
  fabric (ICI intra-slice, DCN across hosts) and acks. See
  device_transfer.py.
- **host path** (fallback / DYN_KV_TRANSFER=host): pages ride the
  checksummed two-part framing device→host→TCP→host→device.

Metadata rendezvous (who listens where) rides the lease store exactly like
the reference's nixl.py:58-86 etcd pattern: the transfer address is
published in the worker's instance metadata.
"""

from __future__ import annotations

import asyncio
import atexit
import logging
import mmap
import os
import re
import socket
import struct
import threading
import uuid as _uuid
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional, Sequence

import numpy as np
import xxhash

from dynamo_tpu.disagg.device_transfer import DevicePlane
from dynamo_tpu.testing import faults
from dynamo_tpu.runtime.codec import (
    MAX_FRAME,
    CodecError,
    encode_frame,
    read_frame,
    write_frame,
)

logger = logging.getLogger(__name__)

#: process-global count of transfer frames rejected by the codec checksum
#: (wire bit-rot / injected corruption); exposed on both Prometheus
#: surfaces as dynamo_tpu_transfer_corrupt_total
#: (telemetry/debug.integrity_lines)
transfer_corrupt_total = 0

#: asyncio's default 64 KiB StreamReader buffer forces ~1000 event-loop
#: wakeups per 64 MB KV frame; bulk-plane connections use a bigger window
_STREAM_LIMIT = 16 << 20

# --- same-host shared-memory fast path -------------------------------------
# TCP loopback through one asyncio loop tops out well under 1 GB/s on a
# single core (sender + receiver share it, every byte crosses the kernel
# twice). Same-host KV movement instead rides a pooled /dev/shm segment:
# one warm memcpy in, zero-copy map out — the control frame stays on TCP.
# Remote targets keep the TCP path untouched.

_SHM_DIR = "/dev/shm"
#: receiver-side cap on cached segment maps per connection (LRU)
_MAX_SHM_MAPS = 8

# --- remote bulk plane ------------------------------------------------------
# An asyncio loop moving a multi-MB payload through StreamWriter/StreamReader
# tops out well under 0.5 GB/s (every 256 KB chunk is an event-loop wakeup,
# and sender+receiver often share the loop). Payloads past _BULK_MIN instead
# ride a SECOND, blocking TCP connection serviced by plain threads on both
# sides: sendall/recv_into move the bytes at kernel speed (~2+ GB/s loopback
# on one core, measured) and the xxh3 checksum runs off-loop too. The control
# frame (op "write_bulk") stays on the asyncio channel and carries the
# metadata + transfer uuid; the ack still means "pages landed".

#: payloads below this stay on the inline asyncio path (a thread hop isn't
#: worth it); "off" disables the bulk plane entirely
_BULK_MIN = int(os.environ.get("DYN_KV_BULK_MIN", 4 << 20))


def _bulk_enabled() -> bool:
    return os.environ.get("DYN_KV_BULK", "on") != "off"


_BULK_SOCKBUF = int(os.environ.get("DYN_KV_BULK_SOCKBUF", 2 << 20))


def _tune_bulk_socket(sock: socket.socket) -> None:
    # 2 MB buffers measured fastest on loopback (0.5 MB: 2.2 GB/s, 2 MB:
    # 3.0 GB/s, 4 MB: 2.4 GB/s — deeper pipelining vs cache locality);
    # NODELAY because each transfer ends with a sub-MSS tail.
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, _BULK_SOCKBUF)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, _BULK_SOCKBUF)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


#: bulk wire layout: [16B uuid | u64 payload_len | u8 flags] payload
#: [16B uuid echo | (u64 xxh3 if flags&1)]. The uuid echo detects stream
#: desync (the realistic software failure on a reliable transport); the
#: payload xxh3 is OPT-IN (DYN_KV_BULK_SUM=on) because TCP/ethernet
#: already checksum every segment and hashing 2x64MB on the transfer
#: cores costs ~40% of the plane's bandwidth — the same trade the
#: reference makes on its NIXL bulk path (RDMA transport CRC, no
#: software sum; block_manager/storage/nixl.rs) and our shm plane makes
#: (raw memcpy, control frame checksummed). When enabled, the sum TRAILS
#: so both sides hash chunkwise while the bytes stream.
_BULK_PREFIX = 16 + 8 + 1
_BULK_CHUNK = 2 << 20


def _bulk_summed() -> bool:
    return os.environ.get("DYN_KV_BULK_SUM", "off") == "on"


class _BulkListener:
    """Receiver half of the bulk plane: accepts connections on a side
    port, drains self-describing payloads into per-connection reusable
    buffers in plain threads, and hands (buffer, checksum_ok) to the
    asyncio side keyed by transfer uuid."""

    def __init__(self, host: str):
        self._srv = socket.create_server((host, 0))
        self._srv.settimeout(0.5)
        self.port = self._srv.getsockname()[1]
        self._loop = asyncio.get_running_loop()
        #: uuid(bytes) -> asyncio.Future resolving to (memoryview, ok)
        self.waiters: dict[bytes, asyncio.Future] = {}
        self._stop = False
        self._threads: list[threading.Thread] = []
        t = threading.Thread(
            target=self._accept_loop, daemon=True, name="kv-bulk-accept"
        )
        t.start()
        self._threads.append(t)

    def expect(self, uuid: bytes) -> asyncio.Future:
        fut = self.waiters.get(uuid)
        if fut is None:
            fut = self.waiters[uuid] = self._loop.create_future()
        return fut

    def _deliver(self, uuid: bytes, view, ok: bool) -> None:
        def _set():
            fut = self.waiters.get(uuid)
            if fut is None:
                fut = self.waiters[uuid] = self._loop.create_future()
            if not fut.done():
                fut.set_result((view, ok))
            if len(self.waiters) > 64:
                # prune resolved-but-unconsumed entries (no_waiter nacks,
                # dead transfers), keeping the newest few in flight
                done = [k for k, f in self.waiters.items() if f.done()]
                for k in done[:-8]:
                    self.waiters.pop(k, None)

        self._loop.call_soon_threadsafe(_set)

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            _tune_bulk_socket(conn)
            t = threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True,
                name="kv-bulk-recv",
            )
            t.start()
            # prune exited receiver threads so weeks of client churn
            # don't accumulate dead Thread objects
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _conn_loop(self, conn: socket.socket) -> None:
        # One transfer in flight per bulk connection (the sender's control
        # channel serializes writes), so one reusable buffer suffices.
        buf = bytearray()
        try:
            while not self._stop:
                prefix = b""
                while len(prefix) < _BULK_PREFIX:
                    chunk = conn.recv(_BULK_PREFIX - len(prefix))
                    if not chunk:
                        return
                    prefix += chunk
                uuid = prefix[:16]
                nbytes, flags = struct.unpack("<QB", prefix[16:])
                summed = bool(flags & 1)
                if nbytes > MAX_FRAME:
                    return  # corrupt length: drop the connection
                if len(buf) < nbytes:
                    buf = bytearray(1 << max(20, (nbytes - 1).bit_length()))
                view = memoryview(buf)[:nbytes]
                h = xxhash.xxh3_64() if summed else None
                off = 0
                while off < nbytes:
                    # summed mode caps reads so hashing pipelines with the
                    # stream; unsummed grabs whatever the kernel has
                    n = conn.recv_into(
                        view[off:],
                        min(_BULK_CHUNK, nbytes - off)
                        if h is not None
                        else nbytes - off,
                    )
                    if n == 0:
                        return
                    if h is not None:
                        h.update(view[off : off + n])
                    off += n
                tlen = 16 + (8 if summed else 0)
                trailer = b""
                while len(trailer) < tlen:
                    chunk = conn.recv(tlen - len(trailer))
                    if not chunk:
                        return
                    trailer += chunk
                ok = trailer[:16] == uuid and (
                    h is None
                    or h.intdigest() == struct.unpack("<Q", trailer[16:])[0]
                )
                self._deliver(uuid, view, ok)
                # NOTE: the buffer is reused for this connection's next
                # transfer; the sender's control channel serializes writes
                # so the next payload only arrives after the previous ack
        finally:
            conn.close()

    def close(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass
        for fut in self.waiters.values():
            if not fut.done():
                fut.cancel()
        self.waiters.clear()
_SHM_NAME_RE = re.compile(r"^dynkv-[0-9]+-[0-9a-f]{12}$")
_LOCAL_HOSTS = ("127.0.0.1", "::1", "localhost")


def _shm_enabled() -> bool:
    return (
        os.environ.get("DYN_KV_SHM", "on") != "off"
        and os.path.isdir(_SHM_DIR)
        and os.access(_SHM_DIR, os.W_OK)
    )


#: host -> bool verdict (permanent) or int negative-TTL countdown
_local_addr_cache: dict[str, "bool | int"] = {}


def _resolve_is_local(host: str) -> bool:
    """Blocking half of the locality check — callers run it off-loop.

    An address is "local" iff the kernel routes it over a local
    interface: connect() a UDP socket (no packet is sent) toward each
    resolved address and check whether the source address the kernel
    picks IS the target — true exactly for addresses assigned to this
    machine. This avoids getaddrinfo(gethostname()), which on stock
    Debian maps to 127.0.1.1 and never lists the NIC IPs a
    Worker.advertise_host deployment actually advertises."""
    for family, _, _, _, sockaddr in socket.getaddrinfo(
        host, 0, type=socket.SOCK_DGRAM
    ):
        addr = sockaddr[0]
        if addr in ("127.0.0.1", "::1"):
            return True
        try:
            with socket.socket(family, socket.SOCK_DGRAM) as s:
                s.connect((addr, 9))
                if s.getsockname()[0] == addr:
                    return True
        except OSError:
            continue
    return False


#: resolver FAILURES suppress the check for this many transfers, then
#: one retry — a resolver that is briefly down at startup must not pin
#: the slow path, but a broken one must not cost every transfer.
_NEG_TTL_FAIL = 64
#: clean non-local VERDICTS last much longer: the host resolved fine to
#: an address this machine demonstrably does not own, so re-checking is
#: only insurance against startup races (address not yet assigned).
_NEG_TTL_VERDICT = 4096

_resolve_locks_guard = threading.Lock()
_resolve_locks: dict[str, threading.Lock] = {}


def _resolve_verdict(host: str, force: bool = False) -> bool:
    """Blocking cached resolve — runs in a worker thread. Serialized
    PER HOST so a burst of first-time transfers to one host performs ONE
    resolution (late arrivals block on that host's lock, then read the
    cache) while distinct hosts resolve concurrently. `force` is set by
    the one caller whose TTL countdown expired — it re-resolves even
    though the (re-armed) negative entry is present."""
    with _resolve_locks_guard:
        lock = _resolve_locks.setdefault(host, threading.Lock())
    with lock:
        cached = _local_addr_cache.get(host)
        if cached is True:
            return True
        if (
            not force
            and isinstance(cached, int)
            and not isinstance(cached, bool)
        ):
            return False  # a concurrent caller just resolved: negative
        try:
            verdict = _resolve_is_local(host)
            ttl = _NEG_TTL_VERDICT
        except (OSError, UnicodeError):
            # UnicodeError: getaddrinfo IDNA-encodes hostnames and raises
            # it (not OSError) for malformed labels — a bad
            # advertise_host must cost the TCP fallback, not the transfer
            verdict = False
            ttl = _NEG_TTL_FAIL
        _local_addr_cache[host] = True if verdict else ttl
        return verdict


async def _is_local_host(host: str) -> bool:
    """Single-host deployments often advertise a routable IP
    (Worker.advertise_host), not loopback — resolve the target and check
    whether it is one of this machine's own addresses so they still take
    the shm fast path. Resolution runs in a worker thread (a slow
    resolver must not stall every transfer sharing the loop). True
    verdicts are cached for the process lifetime; failures AND False
    verdicts get only a bounded negative TTL, so a startup transient
    (resolver down, address not yet assigned) cannot pin a local
    deployment to the TCP slow path forever. A wrong verdict only costs
    the TCP fallback, never correctness (the receiver nacks `shm_failed`
    if it cannot map the segment)."""
    if host in _LOCAL_HOSTS or host == socket.gethostname():
        return True
    cached = _local_addr_cache.get(host)
    if cached is True:
        return True
    force = False
    if isinstance(cached, int) and not isinstance(cached, bool):
        if cached > 1:
            _local_addr_cache[host] = cached - 1
            return False
        # Budget spent: THIS caller re-resolves. Re-arm the countdown
        # first so concurrent transfers keep taking the cached TCP
        # fallback instead of piling onto the per-host lock for the
        # full resolver timeout — only one transfer pays the probe.
        _local_addr_cache[host] = _NEG_TTL_FAIL
        force = True
    return await asyncio.to_thread(_resolve_verdict, host, force)


class _ShmSegment:
    def __init__(self, size: int):
        self.name = f"dynkv-{os.getpid()}-{_uuid.uuid4().hex[:12]}"
        self.path = os.path.join(_SHM_DIR, self.name)
        fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.size = size
        # pre-touch: tmpfs first-touch page allocation halves the first
        # copy's bandwidth; pay it once at pool-creation time instead
        np.frombuffer(self.mm, np.uint8)[:: mmap.PAGESIZE] = 0

    def close(self) -> None:
        try:
            self.mm.close()
        except BufferError:  # an exported view still alive — leave mapped
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _ShmPool:
    """Sender-owned segments, reused after each acked transfer (the ack
    guarantees the receiver has copied out). Unlinked at close/atexit."""

    def __init__(self):
        self._free: list[_ShmSegment] = []
        self._all: list[_ShmSegment] = []
        atexit.register(self.close)
        self._sweep_orphans()

    @staticmethod
    def _sweep_orphans() -> None:
        """atexit never runs for SIGKILLed workers (the FT kill scenarios
        do exactly that), so their segments outlive them. Every new pool
        reaps segments whose owning pid — embedded in the name — is gone."""
        try:
            names = os.listdir(_SHM_DIR)
        except OSError:
            return
        for name in names:
            if not _SHM_NAME_RE.match(name):
                continue
            pid = int(name.split("-")[1])
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                try:
                    os.unlink(os.path.join(_SHM_DIR, name))
                    logger.info("reaped orphaned KV shm segment %s", name)
                except OSError:
                    pass
            except PermissionError:
                pass  # someone else's live pid

    #: free segments kept warm; beyond this (or the byte budget) the
    #: excess is unlinked — these are RAM-backed (tmpfs) and pre-touched,
    #: so an unbounded pool is a resident-memory leak
    _MAX_FREE = 4
    _MAX_FREE_BYTES = int(
        os.environ.get("DYN_KV_SHM_POOL_BYTES", 512 << 20)
    )

    def acquire(self, nbytes: int) -> _ShmSegment:
        # Round up so a workload with drifting transfer sizes reuses one
        # segment instead of minting one per distinct size: powers of two
        # up to 64 MiB, then 64 MiB granularity — segments are pre-touched
        # (fully RAM-resident in tmpfs), so pow2 rounding above that would
        # waste up to 2x the request.
        gran = 64 << 20
        if nbytes <= gran:
            want = 1 << max(20, (nbytes - 1).bit_length())
        else:
            want = -(-nbytes // gran) * gran
        # Best-fit with a size-ratio cap: a lone post-burst huge segment
        # must not get pinned forever serving tiny transfers (it would
        # always first-fit and never be evicted) — beyond 4x the rounded
        # need, mint a right-sized segment and let eviction age the big
        # one out.
        best = None
        for i, seg in enumerate(self._free):
            if nbytes <= seg.size <= 4 * want and (
                best is None or seg.size < self._free[best].size
            ):
                best = i
        if best is not None:
            return self._free.pop(best)
        seg = _ShmSegment(want)
        self._all.append(seg)
        return seg

    def release(self, seg: _ShmSegment) -> None:
        self._free.append(seg)
        # FIFO eviction on both a count and a byte budget: oldest-released
        # first, so segments sized for a workload phase that has passed
        # (e.g. one burst of huge transfers) age out instead of pinning
        # tmpfs RAM for the process lifetime, while the sizes currently
        # in rotation keep getting re-acquired off the back of the list.
        while len(self._free) > self._MAX_FREE or (
            len(self._free) > 1
            and sum(s.size for s in self._free) > self._MAX_FREE_BYTES
        ):
            self.discard(self._free.pop(0))

    def discard(self, seg: _ShmSegment) -> None:
        """Permanently retire a segment (unacked transfer: a receiver may
        still hold a live map of it — never reuse, just unlink)."""
        seg.close()
        try:
            self._all.remove(seg)
        except ValueError:
            pass

    def close(self) -> None:
        for seg in self._all:
            seg.close()
        self._all.clear()
        self._free.clear()
        atexit.unregister(self.close)

#: Byte cap for one G4 fetch response. Real-model blocks run ~MBs each, so
#: an uncapped deep prefix chain would serialize hundreds of MB into one
#: frame (and past MAX_FRAME the encode raises AFTER the extraction work is
#: done, permanently failing every long-prefix onboard). Long chains are
#: instead truncated to a prefix that fits — the peer onboards that prefix
#: and can fetch deeper next request. Operator overrides are clamped below
#: MAX_FRAME, else a large override reintroduces the encode failure.
_FETCH_MAX_BYTES = min(
    int(os.environ.get("DYN_KV_FETCH_MAX_BYTES", 256 << 20)),
    MAX_FRAME - (1 << 20),
)

#: write callback: (page_ids, k, v) -> awaitable; arrays [L, Hkv, n, ps, D]
WriteFn = Callable[[Sequence[int], np.ndarray, np.ndarray], Awaitable[None]]
#: device write callback: same contract but k/v are device (jax) arrays
DeviceWriteFn = Callable[[Sequence[int], object, object], Awaitable[None]]
#: G4 serve callback: (seq_hashes) -> awaitable of
#: (metas, k, v) | None with metas=[(seq_hash, parent, tokens)...]
FetchFn = Callable[[Sequence[int]], Awaitable[Optional[tuple]]]


def dtype_from_name(name: str) -> np.dtype:
    """Wire dtypes travel by NAME: bfloat16's numpy `.str` is '<V2' (void),
    which would silently corrupt the frame on decode."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


@dataclass
class TransferResult:
    request_id: str
    first_token: int
    num_pages: int


class RemotePrefillError(RuntimeError):
    """The prefill side declared this transfer PERMANENTLY failed (e.g.
    the request was dead-lettered after exhausting its redelivery cap):
    the decode side must error-finish the stream, not fall back to local
    prefill — a poison request would just poison again."""


class KvTransferServer:
    """Decode-side receiver: accepts page writes, lands them via write_fn,
    resolves per-request waiters."""

    def __init__(
        self,
        write_fn: WriteFn,
        host: str = "127.0.0.1",
        port: int = 0,
        device_write_fn: Optional[DeviceWriteFn] = None,
        fetch_fn: Optional[FetchFn] = None,
    ):
        self.write_fn = write_fn
        self.device_write_fn = device_write_fn
        self.fetch_fn = fetch_fn
        self.host = host
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._bulk: Optional[_BulkListener] = None
        self._waiters: dict[str, asyncio.Future] = {}
        #: transfers landed per strategy (observability: which plane ran)
        self.transfers = {"device": 0, "host": 0, "shm": 0, "bulk": 0}
        #: frames rejected by the codec's xxh3 check (bit-rot / corruption
        #: on the wire): the connection is dropped, the sender retries or
        #: falls back — corrupt KV bytes NEVER land in the pool
        self.corrupt_rejects = 0
        #: 2·k-block bytes, learned from the first serve — lets later
        #: fetches truncate the *requested* hashes before extraction
        self._fetch_block_bytes: Optional[int] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, limit=_STREAM_LIMIT
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if _bulk_enabled():
            try:
                self._bulk = _BulkListener(self.host)
            except OSError:
                logger.warning("bulk KV listener unavailable; inline TCP only")

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)

    def expect(self, request_id: str) -> asyncio.Future:
        """Register a waiter before enqueueing the remote prefill; await it
        for the TransferResult (or cancel on timeout/fallback)."""
        fut = asyncio.get_running_loop().create_future()
        self._waiters[request_id] = fut
        return fut

    def forget(self, request_id: str) -> None:
        self._waiters.pop(request_id, None)

    async def _handle(self, reader, writer) -> None:
        # sender-segment mappings, cached per shm name (segments are
        # reused across transfers) and dropped with THIS connection — a
        # server outliving many prefill clients must not pin their
        # unlinked segments' tmpfs pages forever. LRU-bounded: the
        # sender's pool evicts and re-mints segments as sizes drift, and
        # every stale map here would pin an unlinked segment's RAM for
        # the connection's (pooled, long) lifetime.
        shm_maps: dict[str, mmap.mmap] = {}
        try:
            while True:
                header, payload = await read_frame(reader)
                op = header.get("op")
                try:
                    if op == "write":
                        await self._on_write(header, payload, writer)
                    elif op == "write_bulk":
                        await self._on_write_bulk(header, writer)
                    elif op == "bulk_port":
                        port = self._bulk.port if self._bulk else 0
                        writer.write(
                            encode_frame({"op": "bulk_port", "port": port})
                        )
                        await writer.drain()
                    elif op == "write_shm":
                        await self._on_write_shm(header, writer, shm_maps)
                    elif op == "offer":
                        await self._on_offer(header, writer)
                    elif op == "fetch":
                        await self._on_fetch(header, writer)
                    elif op == "error":
                        await self._on_error(header, writer)
                    elif op == "close":
                        return
                    else:
                        logger.warning("transfer server: unknown op %r", op)
                except Exception:
                    # Malformed frame (missing key, shape/payload mismatch):
                    # nack fast so the sender fails instead of the decode
                    # side waiting out its transfer timeout.
                    logger.exception("transfer frame failed")
                    rid = header.get("request_id") if isinstance(header, dict) else None
                    await self._nack(writer, rid, "bad_frame")
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except CodecError:
            # A frame failed its xxh3 check: bytes rotted somewhere on the
            # wire. The stream is unrecoverable mid-frame — drop the
            # connection (the sender's pooled-connection error handling
            # retries or falls back) and count the rejection. The corrupt
            # payload never reached a landing callback.
            global transfer_corrupt_total
            self.corrupt_rejects += 1
            transfer_corrupt_total += 1
            logger.warning(
                "transfer connection dropped: frame checksum mismatch "
                "(corrupt KV payload rejected)"
            )
        finally:
            writer.close()
            for mm in shm_maps.values():
                try:
                    mm.close()
                except BufferError:  # a view outlived its handler
                    pass

    async def _on_error(self, header, writer) -> None:
        """A peer declaring this request's remote prefill permanently
        failed (dead-lettered): resolve the waiter with
        RemotePrefillError so the decode side error-finishes immediately
        instead of burning out its transfer timeout."""
        rid = header.get("request_id")
        fut = self._waiters.pop(rid, None)
        if fut is None:
            await self._nack(writer, rid, "no_waiter")
            return
        if not fut.done():
            fut.set_exception(
                RemotePrefillError(
                    header.get("message") or "remote prefill failed"
                )
            )
        writer.write(encode_frame({"op": "ack", "request_id": rid}))
        await writer.drain()

    async def _nack(self, writer, rid, reason: str) -> None:
        """Refusal with a machine-readable reason so the sender can decide
        whether a fallback strategy could still succeed ("no_plane",
        "pull_failed") or the request is dead on this side ("no_waiter",
        "land_failed") and retrying would only ship bytes to a second nack."""
        writer.write(
            encode_frame({"op": "nack", "request_id": rid, "reason": reason})
        )
        await writer.drain()

    async def _land(self, rid, header, land, writer, path: str) -> None:
        """Run the strategy-specific landing coroutine, then resolve the
        waiter and ack — shared tail of both transfer paths."""
        try:
            # fault-injection hook: an injected failure here nacks the
            # sender exactly like a real landing failure
            await faults.fire("transfer.land", request_id=rid)
            await land()
        except Exception as e:
            logger.exception("KV page %s-path landing failed for %s", path, rid)
            fut = self._waiters.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_exception(e)
            await self._nack(writer, rid, "land_failed")
            return
        self.transfers[path] += 1
        fut = self._waiters.pop(rid, None)
        if fut is not None and not fut.done():
            fut.set_result(
                TransferResult(
                    request_id=rid,
                    first_token=header["first_token"],
                    num_pages=len(header["page_ids"]),
                )
            )
        writer.write(encode_frame({"op": "ack", "request_id": rid}))
        await writer.drain()

    async def _on_write(self, header, payload: bytes, writer) -> None:
        rid = header["request_id"]
        if rid not in self._waiters:
            # Decode side gave up (timeout → pages freed and possibly
            # reallocated): landing this write would corrupt a live
            # request's KV. Refuse it.
            logger.warning("dropping KV write for %s: no waiter", rid)
            await self._nack(writer, rid, "no_waiter")
            return
        page_ids = header["page_ids"]
        shape = tuple(header["shape"])  # [L, Hkv, n, ps, Dk]
        # MLA caches are asymmetric (k = latent, v = rope key); symmetric
        # senders omit v_shape
        v_shape = tuple(header.get("v_shape") or shape)
        dtype = dtype_from_name(header["dtype"])
        nbytes_k = int(np.prod(shape)) * dtype.itemsize
        nbytes_v = int(np.prod(v_shape)) * dtype.itemsize
        k = np.frombuffer(payload[:nbytes_k], dtype=dtype).reshape(shape)
        v = np.frombuffer(
            payload[nbytes_k : nbytes_k + nbytes_v], dtype=dtype
        ).reshape(v_shape)
        await self._land(
            rid, header, lambda: self.write_fn(page_ids, k, v), writer, "host"
        )

    async def _on_write_bulk(self, header, writer) -> None:
        """Remote bulk path: the payload arrives on the side bulk socket
        (drained by a plain thread into a reusable buffer, checksummed
        off-loop); this control frame carries the metadata and the
        transfer uuid. The buffer is reused for the NEXT transfer on that
        bulk connection only after we ack — and write_fn commits the
        bytes (device put) before returning — so views are stable."""
        rid = header["request_id"]
        uuid = bytes.fromhex(header["uuid"])
        if self._bulk is None:
            await self._nack(writer, rid, "bulk_failed")
            return
        fut = self._bulk.expect(uuid)
        if rid not in self._waiters:
            logger.warning("dropping bulk KV write for %s: no waiter", rid)
            await self._nack(writer, rid, "no_waiter")
            return
        try:
            view, ok = await asyncio.wait_for(fut, timeout=60.0)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            self._bulk.waiters.pop(uuid, None)
            logger.warning("bulk KV payload for %s never arrived", rid)
            await self._nack(writer, rid, "bulk_failed")
            return
        self._bulk.waiters.pop(uuid, None)
        if not ok:
            await self._nack(writer, rid, "bad_frame")
            return
        shape = tuple(header["shape"])
        v_shape = tuple(header.get("v_shape") or shape)
        dtype = dtype_from_name(header["dtype"])
        nbytes_k = int(np.prod(shape)) * dtype.itemsize
        k = np.frombuffer(
            view, dtype=dtype, count=int(np.prod(shape))
        ).reshape(shape)
        v = np.frombuffer(
            view, dtype=dtype, count=int(np.prod(v_shape)), offset=nbytes_k
        ).reshape(v_shape)
        page_ids = header["page_ids"]
        await self._land(
            rid, header, lambda: self.write_fn(page_ids, k, v), writer,
            "bulk",
        )

    async def _on_write_shm(self, header, writer, shm_maps) -> None:
        """Same-host fast path: the payload sits in a sender-owned
        /dev/shm segment; map it (cached per name — senders reuse
        segments) and land zero-copy views. The sender only reuses the
        segment after our ack, so the views are stable until write_fn
        returns — write_fn must copy (device put) before returning, which
        the engine pool write does."""
        rid = header["request_id"]
        name = header.get("shm_name", "")
        if rid not in self._waiters:
            logger.warning("dropping shm KV write for %s: no waiter", rid)
            await self._nack(writer, rid, "no_waiter")
            return
        if not _SHM_NAME_RE.match(name):
            # names come off the wire: refuse anything that isn't exactly
            # a pool-generated name (no separators, no traversal)
            logger.warning("refusing shm name %r", name)
            await self._nack(writer, rid, "shm_failed")
            return
        mm = shm_maps.get(name)
        if mm is None or len(mm) < int(header["shm_size"]):
            try:
                fd = os.open(os.path.join(_SHM_DIR, name), os.O_RDONLY)
                try:
                    # ValueError: shm_size exceeds the file (truncated or
                    # version-skewed sender) — same remedy as a missing
                    # segment: let the sender fall back to TCP
                    mm = mmap.mmap(
                        fd, int(header["shm_size"]), prot=mmap.PROT_READ
                    )
                finally:
                    os.close(fd)
            except (OSError, ValueError):
                # not same-host after all (or the segment vanished):
                # tell the sender so it falls back to the TCP payload path
                logger.warning("cannot map shm segment %s", name)
                await self._nack(writer, rid, "shm_failed")
                return
            old = shm_maps.pop(name, None)
            if old is not None:
                try:
                    old.close()
                except BufferError:
                    pass
            shm_maps[name] = mm
            logger.info(
                "mapped KV shm segment %s (%d bytes)", name, len(mm)
            )
            while len(shm_maps) > _MAX_SHM_MAPS:
                # LRU evict (dict order = recency, see below): a name the
                # sender's pool retired would otherwise pin its unlinked
                # segment's tmpfs RAM for this connection's lifetime. If
                # the segment is still live, the next write re-maps it.
                stale = next(iter(shm_maps))
                try:
                    shm_maps.pop(stale).close()
                except BufferError:
                    pass
        else:
            # refresh recency so steady reuse never evicts the hot map
            shm_maps[name] = shm_maps.pop(name)
        shape = tuple(header["shape"])
        v_shape = tuple(header.get("v_shape") or shape)
        dtype = dtype_from_name(header["dtype"])
        nbytes_k = int(np.prod(shape)) * dtype.itemsize
        k = np.frombuffer(mm, dtype=dtype, count=int(np.prod(shape))).reshape(
            shape
        )
        v = np.frombuffer(
            mm, dtype=dtype, count=int(np.prod(v_shape)), offset=nbytes_k
        ).reshape(v_shape)
        page_ids = header["page_ids"]
        await self._land(
            rid, header, lambda: self.write_fn(page_ids, k, v), writer, "shm"
        )

    async def _on_offer(self, header, writer) -> None:
        """Device-path offer: pull the staged pages over the PjRt transfer
        fabric and land them without a host round-trip. Nack when this
        process has no device plane — the sender falls back to a write."""
        rid = header["request_id"]
        plane = DevicePlane.get()
        if plane is None:
            await self._nack(writer, rid, "no_plane")
            return
        if rid not in self._waiters:
            # Refuse BEFORE pulling: the staged arrays stay unconsumed on
            # the sender (bounded leak, see device_transfer.py docstring)
            # but no freed/reused decode pages get overwritten.
            logger.warning("dropping KV offer for %s: no waiter", rid)
            await self._nack(writer, rid, "no_waiter")
            return
        page_ids = header["page_ids"]
        logger.info(
            "device KV pull start for %s (%d pages from %s)",
            rid, len(page_ids), header["xfer_addr"],
        )
        try:
            k, v = await plane.pull(
                header["xfer_addr"], header["uuid"],
                tuple(header["shape"]),
                tuple(header.get("v_shape") or header["shape"]),
                dtype_from_name(header["dtype"]),
            )
        except Exception:
            # Pull never touched the pool: nack but KEEP the waiter — the
            # sender's host-path fallback can still land this request.
            logger.exception("device KV pull failed for %s", rid)
            await self._nack(writer, rid, "pull_failed")
            return
        if rid not in self._waiters:
            # Re-check after the pull: the decode side may have timed out
            # DURING the transfer and freed (possibly reallocated) the
            # pages — landing now would corrupt a live request's KV.
            logger.warning("dropping pulled KV for %s: waiter gone", rid)
            await self._nack(writer, rid, "no_waiter")
            return

        async def land():
            if self.device_write_fn is not None:
                await self.device_write_fn(page_ids, k, v)
            else:
                await self.write_fn(page_ids, np.asarray(k), np.asarray(v))

        await self._land(rid, header, land, writer, "device")

    async def _on_fetch(self, header, writer) -> None:
        """G4 remote-tier serve: export the longest locally-resident chain
        of the requested hashes (reference: export_local_blockset,
        block_manager.rs:121). Misses return found=0 so the peer's
        directory self-heals. Responses are capped at _FETCH_MAX_BYTES by
        truncating the chain — a chain prefix is always independently
        adoptable, so the peer lands what fits."""
        hashes = header.get("seq_hashes", [])
        if self._fetch_block_bytes:
            # Block size is known from an earlier serve: truncate the
            # *request* so the engine never extracts pages it can't ship.
            hashes = hashes[: max(1, _FETCH_MAX_BYTES // self._fetch_block_bytes)]
        served = None
        if self.fetch_fn is not None and hashes:
            try:
                served = await self.fetch_fn(hashes)
            except Exception:
                logger.exception("KV fetch serve failed")
        if not served:
            writer.write(encode_frame({"op": "fetch_ok", "found": 0}))
            await writer.drain()
            return
        metas, k, v = served
        n_blocks = int(k.shape[2])
        if n_blocks:
            per_block = (k.nbytes + v.nbytes) // n_blocks
            self._fetch_block_bytes = per_block
            fit = max(1, _FETCH_MAX_BYTES // per_block)
            if n_blocks > fit:
                logger.info(
                    "KV fetch: truncating served chain %d -> %d blocks "
                    "(%d bytes/block, cap %d)",
                    n_blocks, fit, per_block, _FETCH_MAX_BYTES,
                )
                metas = metas[:fit]
                k = k[:, :, :fit]
                v = v[:, :, :fit]
        writer.write(
            encode_frame(
                {
                    "op": "fetch_ok",
                    "found": len(metas),
                    "metas": [
                        [int(h), None if p is None else int(p), list(t)]
                        for h, p, t in metas
                    ],
                    "shape": list(k.shape),
                    "v_shape": list(v.shape),
                    "dtype": k.dtype.name,
                },
                k.tobytes() + v.tobytes(),
            )
        )
        await writer.drain()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._bulk is not None:
            self._bulk.close()
        for fut in self._waiters.values():
            if not fut.done():
                fut.cancel()
        self._waiters.clear()


class KvTransferClient:
    """Prefill-side sender; one connection per decode target, reused."""

    def __init__(self):
        self._conns: dict[tuple[str, int], tuple] = {}
        self._locks: dict[tuple[str, int], asyncio.Lock] = {}
        self._shm_pool = _ShmPool() if _shm_enabled() else None
        #: targets where the shm handshake failed (remote host / no shm
        #: support): don't re-attempt every transfer — but a single
        #: transient failure must not disable shm for the client's
        #: lifetime, so each entry only suppresses the next
        #: _SHM_RETRY_AFTER transfers to that target, then one retry.
        self._shm_bad: dict[tuple[str, int], int] = {}
        #: bulk-plane sockets per target; same suppression scheme. All
        #: bulk use of a target is serialized by _bulk_lock: payload
        #: bytes from concurrent writes must not interleave on the one
        #: socket, and the receiver's single reusable buffer must not be
        #: refilled before the previous transfer's ack (which _land only
        #: sends after write_fn committed the bytes).
        self._bulk_socks: dict[tuple[str, int], socket.socket] = {}
        self._bulk_bad: dict[tuple[str, int], int] = {}
        self._bulk_locks: dict[tuple[str, int], asyncio.Lock] = {}

    def _bulk_lock(self, key: tuple[str, int]) -> asyncio.Lock:
        lock = self._bulk_locks.get(key)
        if lock is None:
            lock = self._bulk_locks[key] = asyncio.Lock()
        return lock

    _SHM_RETRY_AFTER = 64

    @staticmethod
    def _suppressed(table: dict, key: tuple[str, int]) -> bool:
        """Countdown suppression: after a failure, skip the fast path for
        _SHM_RETRY_AFTER transfers, then retry once."""
        left = table.get(key)
        if left is None:
            return False
        if left <= 1:
            del table[key]  # budget spent: retry once
            return False
        table[key] = left - 1
        return True

    def _shm_suppressed(self, key: tuple[str, int]) -> bool:
        return self._suppressed(self._shm_bad, key)

    def _lock(self, key: tuple[str, int]) -> asyncio.Lock:
        # created synchronously, so concurrent writers share one lock
        lock = self._locks.get(key)
        if lock is None:
            lock = self._locks[key] = asyncio.Lock()
        return lock

    async def _conn(self, key: tuple[str, int]):
        """Must be called holding the key's lock."""
        conn = self._conns.get(key)
        if conn is not None and not conn[1].is_closing():
            return conn
        reader, writer = await asyncio.open_connection(
            *key, limit=_STREAM_LIMIT
        )
        self._conns[key] = (reader, writer)
        return reader, writer

    async def send(
        self,
        host: str,
        port: int,
        request_id: str,
        page_ids: Sequence[int],
        k,
        v,
        first_token: int,
    ) -> bool:
        """Ship pages by the best available strategy. k/v: canonical
        [L, Hkv, n, ps, D], ideally still DEVICE arrays — the device path
        stages them without a host copy; only a host-path fallback
        materializes numpy. True on decode-side ack."""
        # fault-injection hook (dynamo_tpu/testing/faults.py): a drop/
        # error here is a transfer that never left the prefill side
        await faults.fire("transfer.send", request_id=request_id)
        plane = DevicePlane.get()
        if plane is not None:
            try:
                uuid = plane.stage([k, v])
                resp, _ = await self._roundtrip(
                    (host, port),
                    {
                        "op": "offer",
                        "request_id": request_id,
                        "page_ids": list(page_ids),
                        "shape": list(k.shape),
                        "v_shape": list(v.shape),
                        "dtype": k.dtype.name,
                        "first_token": int(first_token),
                        "xfer_addr": plane.address,
                        "uuid": uuid,
                    },
                )
                if resp.get("op") == "ack":
                    return True
                reason = resp.get("reason", "")
                if reason in ("no_waiter", "land_failed"):
                    # The request is dead on the decode side (freed /
                    # timed out / landing already failed its waiter):
                    # materializing the device→host copy and shipping the
                    # multi-MB payload would only buy a second nack.
                    logger.info(
                        "device KV offer for %s nacked (%s); "
                        "skipping host-path fallback",
                        request_id, reason,
                    )
                    return False
                logger.info(
                    "device KV offer for %s nacked (%s); host-path fallback",
                    request_id, reason or "unspecified",
                )
            except Exception:
                logger.exception(
                    "device KV path failed for %s; host-path fallback",
                    request_id,
                )
        return await self.write(
            host, port, request_id, page_ids,
            np.asarray(k), np.asarray(v), first_token,
        )

    async def write(
        self,
        host: str,
        port: int,
        request_id: str,
        page_ids: Sequence[int],
        k: np.ndarray,
        v: np.ndarray,
        first_token: int,
    ) -> bool:
        """Host path: same-host targets ride a pooled /dev/shm segment
        (one warm memcpy; the control frame carries only the segment
        name), remote targets ship the page bytes in the frame payload as
        vectored writes. True on decode-side ack. k/v: [L, Hkv, n, ps, D]
        with n == len(page_ids)."""
        assert k.shape[2] == len(page_ids) and v.shape[2] == len(page_ids), (
            k.shape, len(page_ids),
        )
        k = np.ascontiguousarray(k)
        v = np.ascontiguousarray(v)
        header = {
            "op": "write",
            "request_id": request_id,
            "page_ids": list(page_ids),
            "shape": list(k.shape),
            "v_shape": list(v.shape),
            "dtype": k.dtype.name,
            "first_token": int(first_token),
        }
        key = (host, port)
        if (
            self._shm_pool is not None
            and not self._shm_suppressed(key)
            and await _is_local_host(host)
        ):
            seg = self._shm_pool.acquire(k.nbytes + v.nbytes)
            np.copyto(
                np.frombuffer(seg.mm, dtype=k.dtype, count=k.size).reshape(
                    k.shape
                ),
                k,
            )
            np.copyto(
                np.frombuffer(
                    seg.mm, dtype=v.dtype, count=v.size, offset=k.nbytes
                ).reshape(v.shape),
                v,
            )
            try:
                resp, _ = await self._roundtrip(
                    key,
                    {
                        **header,
                        "op": "write_shm",
                        "shm_name": seg.name,
                        "shm_size": seg.size,
                    },
                )
            except BaseException:
                # No ack ⇒ the receiver may STILL be reading this segment
                # (sender-side cancel races the landing) — reusing it would
                # hand a live reader torn bytes. Quarantine: unlink now
                # (existing maps stay valid, the name can't be reopened)
                # and drop it from the pool instead of releasing.
                self._shm_pool.discard(seg)
                raise
            self._shm_pool.release(seg)
            if resp.get("op") == "ack":
                return True
            if resp.get("reason") != "shm_failed":
                return False  # request-level refusal; TCP wouldn't help
            logger.info(
                "shm KV write to %s:%d refused; using TCP payload path",
                host, port,
            )
            self._shm_bad[key] = self._SHM_RETRY_AFTER
        # bf16 has no buffer protocol (numpy dtype 'E'); ship uint8 views
        kb = k.view(np.uint8)
        vb = v.view(np.uint8)
        if (
            _bulk_enabled()
            and kb.nbytes + vb.nbytes >= _BULK_MIN
            and not self._suppressed(self._bulk_bad, key)
        ):
            sent = await self._write_bulk(key, header, kb, vb)
            if sent is not None:
                return sent
            self._bulk_bad[key] = self._SHM_RETRY_AFTER
            logger.info(
                "bulk KV write to %s:%d unavailable; inline TCP payload",
                host, port,
            )
        return await self._control(host, port, header, parts=[kb, vb])

    async def _bulk_sock(
        self, key: tuple[str, int]
    ) -> Optional[socket.socket]:
        """Discover the target's bulk port (once per connection) and open
        the blocking side socket. None when the target has no bulk plane."""
        sock = self._bulk_socks.get(key)
        if sock is not None:
            return sock
        resp, _ = await asyncio.wait_for(
            self._roundtrip(key, {"op": "bulk_port"}), timeout=10.0
        )
        port = resp.get("port", 0) if resp.get("op") == "bulk_port" else 0
        if not port:
            return None
        sock = await asyncio.to_thread(
            socket.create_connection, (key[0], port), 10.0
        )
        # drop the connect timeout: sendall treats a socket timeout as a
        # TOTAL transfer deadline, which a big payload on a slow link
        # would trip mid-stream
        sock.settimeout(None)
        _tune_bulk_socket(sock)
        self._bulk_socks[key] = sock
        return sock

    async def _write_bulk(
        self, key, header, kb: np.ndarray, vb: np.ndarray
    ) -> Optional[bool]:
        """Ship the payload over the blocking bulk socket (sendall +
        off-loop xxh3 in a worker thread — ~5x the inline asyncio path's
        loopback bandwidth), then the metadata control frame. Serialized
        per target by _bulk_lock (see __init__). Returns None when the
        bulk plane should be abandoned for this target (caller falls
        back to the inline payload path)."""
        async with self._bulk_lock(key):
            return await self._write_bulk_locked(key, header, kb, vb)

    async def _write_bulk_locked(
        self, key, header, kb: np.ndarray, vb: np.ndarray
    ) -> Optional[bool]:
        try:
            sock = await self._bulk_sock(key)
        except (OSError, asyncio.TimeoutError, CodecError):
            return None
        if sock is None:
            return None
        uuid = _uuid.uuid4()

        summed = _bulk_summed()

        def _send():
            sock.sendall(
                uuid.bytes
                + struct.pack("<QB", kb.nbytes + vb.nbytes, 1 if summed else 0)
            )
            h = xxhash.xxh3_64() if summed else None
            for part in (kb, vb):
                mv = memoryview(part).cast("B")
                if h is None:
                    # unsummed: one sendall per part — the C loop moves
                    # the whole view with the GIL released
                    sock.sendall(mv)
                    continue
                for off in range(0, len(mv), _BULK_CHUNK):
                    c = mv[off : off + _BULK_CHUNK]
                    h.update(c)
                    sock.sendall(c)
            trailer = uuid.bytes
            if h is not None:
                trailer += struct.pack("<Q", h.intdigest())
            sock.sendall(trailer)

        try:
            await asyncio.to_thread(_send)
            resp, _ = await self._roundtrip(
                key, {**header, "op": "write_bulk", "uuid": uuid.hex}
            )
        except (OSError, ConnectionError, CodecError, asyncio.TimeoutError):
            # mid-stream I/O failure desynchronizes the bulk connection:
            # drop it (the receiver's partial recv sees EOF and exits)
            # and let the caller retry this transfer inline
            self._drop_bulk(key)
            return None
        except BaseException:
            # cancellation (caller timeout) — drop the connection so the
            # next attempt reconnects clean, and propagate
            self._drop_bulk(key)
            raise
        if resp.get("op") == "ack":
            return True
        reason = resp.get("reason")
        if reason in ("bulk_failed", "bad_frame"):
            # payload never arrived / checksum failed: the bulk channel
            # is suspect — drop it and let the caller fall back inline
            self._drop_bulk(key)
            return None
        return False  # request-level refusal (no_waiter etc.)

    def _drop_bulk(self, key: tuple[str, int]) -> None:
        sock = self._bulk_socks.pop(key, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    async def fetch(
        self, host: str, port: int, seq_hashes: Sequence[int]
    ) -> Optional[tuple]:
        """G4 onboard pull: ask a peer for the longest chain of
        `seq_hashes` it can serve. Returns (metas, k, v) or None."""
        key = (host, port)
        resp, payload = await self._roundtrip(
            key, {"op": "fetch", "seq_hashes": [int(h) for h in seq_hashes]}
        )
        if resp.get("op") != "fetch_ok" or not resp.get("found"):
            return None
        shape = tuple(resp["shape"])
        v_shape = tuple(resp.get("v_shape") or shape)
        dtype = dtype_from_name(resp["dtype"])
        nbytes_k = int(np.prod(shape)) * dtype.itemsize
        nbytes_v = int(np.prod(v_shape)) * dtype.itemsize
        k = np.frombuffer(payload[:nbytes_k], dtype=dtype).reshape(shape)
        v = np.frombuffer(
            payload[nbytes_k : nbytes_k + nbytes_v], dtype=dtype
        ).reshape(v_shape)
        metas = [(h, p, tuple(t)) for h, p, t in resp["metas"]]
        return metas, k, v

    async def send_error(
        self, host: str, port: int, request_id: str, message: str
    ) -> bool:
        """Declare a request's remote prefill permanently failed: the
        decode side resolves its waiter with RemotePrefillError and
        error-finishes the stream (dead-letter path). True on ack."""
        resp, _ = await self._roundtrip(
            (host, port),
            {"op": "error", "request_id": request_id, "message": message},
        )
        return resp.get("op") == "ack"

    async def _roundtrip(
        self,
        key: tuple[str, int],
        header: dict,
        payload: bytes = b"",
        parts=None,
    ) -> tuple[dict, bytes]:
        """One request/response on the pooled connection. Bulk payloads go
        as `parts` (vectored, streaming-checksummed — no concatenation
        copy). Any failure — including cancellation (a caller's wait_for
        timeout) mid-read — closes and evicts the connection: reusing it
        would read the previous exchange's frame and desynchronize every
        later call."""
        async with self._lock(key):
            reader, writer = await self._conn(key)
            try:
                if parts is not None and not faults.wants_corrupt(
                    "transfer.send"
                ):
                    await write_frame(writer, header, parts)
                else:
                    # chaos corrupt rules (testing/faults.py `corrupt`
                    # kind) flip a byte of the ENCODED frame — after the
                    # codec computed its checksums — so tests can prove
                    # the receiver rejects rotten KV bytes instead of
                    # landing them. The parts fast path pre-flattens only
                    # when a corrupt rule is actually armed.
                    if parts is not None:
                        payload = b"".join(
                            bytes(memoryview(p).cast("B")) for p in parts
                        )
                    buf = faults.corrupt_bytes(
                        "transfer.send",
                        encode_frame(header, payload),
                        op=header.get("op"),
                        request_id=header.get("request_id"),
                    )
                    writer.write(buf)
                    await writer.drain()
                return await read_frame(reader)
            except BaseException:
                writer.close()
                self._conns.pop(key, None)
                raise

    async def _control(
        self, host: str, port: int, header: dict, payload: bytes = b"",
        parts=None,
    ) -> bool:
        resp, _ = await self._roundtrip((host, port), header, payload, parts)
        return resp.get("op") == "ack"

    def close(self) -> None:
        for _, writer in self._conns.values():
            writer.close()
        self._conns.clear()
        for key in list(self._bulk_socks):
            self._drop_bulk(key)
        if self._shm_pool is not None:
            self._shm_pool.close()

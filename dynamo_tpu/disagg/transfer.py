"""KV page transfer plane: prefill → decode bulk KV movement.

The decode worker runs a KvTransferServer next to its engine; a prefill
worker connects and streams the prompt's KV pages, addressed by the decode
worker's reserved page ids. Pages ride the checksummed two-part framing
(header: page ids + dtype/shape; payload: raw k‖v bytes), then an async
write callback scatters them into the decode engine's device pool and the
request's waiter fires with the first sampled token.

This is the reference's NIXL RDMA KV write (dynamo_flow.md:36-38,
block_manager/storage/nixl.rs) re-designed for TPU: no verbs — pages move
device→host→TCP→host→device today, with the same interface ready to back
onto ICI remote DMA (Pallas) intra-slice or DCN streams across slices.
Metadata rendezvous (who listens where) rides the lease store exactly like
the reference's nixl.py:58-86 etcd pattern: the transfer address is
published in the worker's instance metadata.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional, Sequence

import numpy as np

from dynamo_tpu.runtime.codec import encode_frame, read_frame

logger = logging.getLogger(__name__)

#: write callback: (page_ids, k, v) -> awaitable; arrays [L, Hkv, n, ps, D]
WriteFn = Callable[[Sequence[int], np.ndarray, np.ndarray], Awaitable[None]]


@dataclass
class TransferResult:
    request_id: str
    first_token: int
    num_pages: int


class KvTransferServer:
    """Decode-side receiver: accepts page writes, lands them via write_fn,
    resolves per-request waiters."""

    def __init__(self, write_fn: WriteFn, host: str = "127.0.0.1", port: int = 0):
        self.write_fn = write_fn
        self.host = host
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._waiters: dict[str, asyncio.Future] = {}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)

    def expect(self, request_id: str) -> asyncio.Future:
        """Register a waiter before enqueueing the remote prefill; await it
        for the TransferResult (or cancel on timeout/fallback)."""
        fut = asyncio.get_running_loop().create_future()
        self._waiters[request_id] = fut
        return fut

    def forget(self, request_id: str) -> None:
        self._waiters.pop(request_id, None)

    async def _handle(self, reader, writer) -> None:
        try:
            while True:
                header, payload = await read_frame(reader)
                op = header.get("op")
                try:
                    if op == "write":
                        await self._on_write(header, payload, writer)
                    elif op == "close":
                        return
                    else:
                        logger.warning("transfer server: unknown op %r", op)
                except Exception:
                    # Malformed frame (missing key, shape/payload mismatch):
                    # nack fast so the sender fails instead of the decode
                    # side waiting out its transfer timeout.
                    logger.exception("transfer frame failed")
                    rid = header.get("request_id") if isinstance(header, dict) else None
                    writer.write(encode_frame({"op": "nack", "request_id": rid}))
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _on_write(self, header, payload: bytes, writer) -> None:
        rid = header["request_id"]
        if rid not in self._waiters:
            # Decode side gave up (timeout → pages freed and possibly
            # reallocated): landing this write would corrupt a live
            # request's KV. Refuse it.
            logger.warning("dropping KV write for %s: no waiter", rid)
            writer.write(encode_frame({"op": "nack", "request_id": rid}))
            await writer.drain()
            return
        page_ids = header["page_ids"]
        shape = tuple(header["shape"])  # [L, Hkv, n, ps, D]
        dtype = np.dtype(header["dtype"])
        nbytes = int(np.prod(shape)) * dtype.itemsize
        k = np.frombuffer(payload[:nbytes], dtype=dtype).reshape(shape)
        v = np.frombuffer(payload[nbytes : 2 * nbytes], dtype=dtype).reshape(shape)
        try:
            await self.write_fn(page_ids, k, v)
        except Exception as e:
            logger.exception("KV page write failed for %s", rid)
            fut = self._waiters.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_exception(e)
            writer.write(encode_frame({"op": "nack", "request_id": rid}))
            await writer.drain()
            return
        fut = self._waiters.pop(rid, None)
        if fut is not None and not fut.done():
            fut.set_result(
                TransferResult(
                    request_id=rid,
                    first_token=header["first_token"],
                    num_pages=len(page_ids),
                )
            )
        writer.write(encode_frame({"op": "ack", "request_id": rid}))
        await writer.drain()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for fut in self._waiters.values():
            if not fut.done():
                fut.cancel()
        self._waiters.clear()


class KvTransferClient:
    """Prefill-side sender; one connection per decode target, reused."""

    def __init__(self):
        self._conns: dict[tuple[str, int], tuple] = {}
        self._locks: dict[tuple[str, int], asyncio.Lock] = {}

    def _lock(self, key: tuple[str, int]) -> asyncio.Lock:
        # created synchronously, so concurrent writers share one lock
        lock = self._locks.get(key)
        if lock is None:
            lock = self._locks[key] = asyncio.Lock()
        return lock

    async def _conn(self, key: tuple[str, int]):
        """Must be called holding the key's lock."""
        conn = self._conns.get(key)
        if conn is not None and not conn[1].is_closing():
            return conn
        reader, writer = await asyncio.open_connection(*key)
        self._conns[key] = (reader, writer)
        return reader, writer

    async def write(
        self,
        host: str,
        port: int,
        request_id: str,
        page_ids: Sequence[int],
        k: np.ndarray,
        v: np.ndarray,
        first_token: int,
    ) -> bool:
        """Ship pages; True on decode-side ack. k/v: [L, Hkv, n, ps, D]
        with n == len(page_ids)."""
        assert k.shape == v.shape and k.shape[2] == len(page_ids), (
            k.shape, len(page_ids),
        )
        key = (host, port)
        async with self._lock(key):
            reader, writer = await self._conn(key)
            writer.write(
                encode_frame(
                    {
                        "op": "write",
                        "request_id": request_id,
                        "page_ids": list(page_ids),
                        "shape": list(k.shape),
                        "dtype": k.dtype.str,
                        "first_token": int(first_token),
                    },
                    k.tobytes() + v.tobytes(),
                )
            )
            await writer.drain()
            header, _ = await read_frame(reader)
        return header.get("op") == "ack"

    def close(self) -> None:
        for _, writer in self._conns.values():
            writer.close()
        self._conns.clear()

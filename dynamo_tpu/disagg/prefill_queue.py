"""Shared durable prefill queue over the fabric work queue.

Prefill workers are stateless competing consumers: any of them can pop any
item, and un-acked items are redelivered if a worker dies mid-prefill
(reference: PrefillQueue over NATS JetStream —
examples/llm/utils/prefill_queue.py:24, transports/nats.rs NatsQueue :345).
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.disagg.protocol import RemotePrefillRequest

DEFAULT_QUEUE = "prefill_queue"


class PrefillQueue:
    def __init__(self, fabric, name: str = DEFAULT_QUEUE):
        self.fabric = fabric
        self.name = name

    async def push(self, req: RemotePrefillRequest) -> None:
        await self.fabric.queue_push(
            self.name, {"request_id": req.request_id}, req.pack()
        )

    async def pop(
        self, timeout: Optional[float] = None
    ) -> Optional[tuple[str, RemotePrefillRequest]]:
        """Returns (item_id, request); ack(item_id) when the transfer lands,
        nack(item_id) to redeliver. Broker-counted redeliveries (a consumer
        died mid-prefill and the item came back) fold into req.attempts so
        the poison-item cap sees BOTH failure modes — explicit requeues and
        death-redeliveries."""
        item = await self.fabric.queue_pop(self.name, timeout=timeout)
        if item is None:
            return None
        req = RemotePrefillRequest.unpack(item.payload)
        try:
            redelivered = int((item.header or {}).get("redeliveries", 0))
        except (TypeError, ValueError):
            redelivered = 0
        req.attempts = max(req.attempts, redelivered)
        return item.item_id, req

    async def dead_letter(self, req: RemotePrefillRequest) -> None:
        """Park a poison item on the `<name>.dead` queue (never consumed
        automatically; depth shows in the fabric's queue stats) so it
        stops cycling through the fleet."""
        await self.fabric.queue_push(
            f"{self.name}.dead",
            {"request_id": req.request_id, "attempts": req.attempts},
            req.pack(),
        )

    async def ack(self, item_id: str) -> None:
        await self.fabric.queue_ack(self.name, item_id)

    async def nack(self, item_id: str) -> None:
        await self.fabric.queue_nack(self.name, item_id)

    async def depth(self) -> int:
        return await self.fabric.queue_len(self.name)

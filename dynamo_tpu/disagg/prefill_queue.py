"""Shared durable prefill queue over the fabric work queue.

Prefill workers are stateless competing consumers: any of them can pop any
item, and un-acked items are redelivered if a worker dies mid-prefill
(reference: PrefillQueue over NATS JetStream —
examples/llm/utils/prefill_queue.py:24, transports/nats.rs NatsQueue :345).
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.disagg.protocol import RemotePrefillRequest

DEFAULT_QUEUE = "prefill_queue"


class PrefillQueue:
    def __init__(self, fabric, name: str = DEFAULT_QUEUE):
        self.fabric = fabric
        self.name = name

    async def push(self, req: RemotePrefillRequest) -> None:
        await self.fabric.queue_push(
            self.name, {"request_id": req.request_id}, req.pack()
        )

    async def pop(
        self, timeout: Optional[float] = None
    ) -> Optional[tuple[str, RemotePrefillRequest]]:
        """Returns (item_id, request); ack(item_id) when the transfer lands,
        nack(item_id) to redeliver."""
        item = await self.fabric.queue_pop(self.name, timeout=timeout)
        if item is None:
            return None
        return item.item_id, RemotePrefillRequest.unpack(item.payload)

    async def ack(self, item_id: str) -> None:
        await self.fabric.queue_ack(self.name, item_id)

    async def nack(self, item_id: str) -> None:
        await self.fabric.queue_nack(self.name, item_id)

    async def depth(self) -> int:
        return await self.fabric.queue_len(self.name)

"""Prefill worker: stateless competing consumer of the shared prefill queue.

Pops a RemotePrefillRequest, runs the prompt through its local engine (one
sampled token, pages held), ships the KV pages to the decode worker's
transfer server, releases, acks. Any number of these can run; un-acked
items redeliver if one dies mid-prefill (reference:
examples/llm/components/prefill_worker.py:139 prefill_queue_handler).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.disagg.protocol import RemotePrefillRequest
from dynamo_tpu.disagg.transfer import KvTransferClient
from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.async_engine import AsyncEngineRunner
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.engine.request import SamplingParams
from dynamo_tpu.runtime import DistributedRuntime

logger = logging.getLogger(__name__)


class PrefillWorker:
    def __init__(
        self,
        runtime: DistributedRuntime,
        engine_config: EngineConfig,
        namespace: str = "dynamo",
        component: str = "prefill",
        queue_name: str = "prefill_queue",
        max_concurrent: int = 4,
        checkpoint_path: Optional[str] = None,
        runner: Optional[AsyncEngineRunner] = None,
        advertise_host: str = "127.0.0.1",
        register: bool = True,
    ):
        from dynamo_tpu.disagg import device_transfer

        # the prefill side STAGES pages; peers pull from this address
        device_transfer.configure(advertise_host)
        self.runtime = runtime
        self.engine_config = engine_config
        self.namespace = namespace
        self.component = component
        self.queue = PrefillQueue(runtime.fabric, queue_name)
        self.transfer = KvTransferClient()
        self.max_concurrent = max_concurrent
        self.checkpoint_path = checkpoint_path
        self.runner = runner
        #: embedded mode (Worker.flip_role): the host Worker owns the
        #: runner AND the registration — this instance only consumes the
        #: queue. stop() then leaves the borrowed runner running.
        self._own_runner = runner is None
        self._register = register
        self.registration = None
        self.instance_id = ""
        self.prefills_done = 0
        #: poison items parked on the dead-letter queue (redelivery cap)
        self.dead_letters = 0
        #: deadline-expired items dropped without prefilling
        self.deadline_drops = 0
        self._task: Optional[asyncio.Task] = None
        self._flush_sub = None
        self._flush_task: Optional[asyncio.Task] = None
        self._sem = asyncio.Semaphore(max_concurrent)

    async def start(self) -> None:
        if self.runner is None:
            # off-loop: engine init blocks for seconds and would starve the
            # fabric lease keepalives (see Worker.start)
            engine = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: JaxEngine(
                    self.engine_config, checkpoint_path=self.checkpoint_path
                ),
            )
            self.runner = AsyncEngineRunner(engine)
            self.runner.start()
        # Register for liveness/planner visibility (no ingress: work arrives
        # via the queue, not pushed RPC). Embedded mode (register=False):
        # the host Worker registers the prefill endpoint itself, under its
        # own instance id and dialable ingress address.
        if self._register:
            ep = (
                self.runtime.namespace(self.namespace)
                .component(self.component)
                .endpoint("prefill")
            )
            self.registration = await ep.register(
                "127.0.0.1", 0, metadata={"model": self.engine_config.model}
            )
            self.instance_id = self.registration.instance.instance_id
        loop = asyncio.get_running_loop()
        self._task = loop.create_task(self._consume_loop())
        # No ingress here — admin flush arrives as a fabric broadcast.
        from dynamo_tpu.subjects import FLUSH_SUBJECT

        self._flush_sub = await self.runtime.fabric.subscribe(FLUSH_SUBJECT)
        self._flush_task = loop.create_task(self._flush_loop())
        logger.info("prefill worker %s consuming %s", self.instance_id, self.queue.name)

    async def _flush_loop(self) -> None:
        async for _ in self._flush_sub:
            try:
                n = await self.runner.submit(
                    lambda eng: eng.allocator.clear_cache()
                )
                logger.info("admin flush: cleared %d cached pages", n)
            except Exception:
                logger.exception("admin flush failed")

    MAX_ATTEMPTS = 3

    async def _consume_loop(self) -> None:
        while True:
            try:
                popped = await self.queue.pop(timeout=1.0)
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("prefill queue pop failed; retrying")
                await asyncio.sleep(0.5)
                continue
            if popped is None:
                continue
            await self._sem.acquire()
            task = asyncio.get_running_loop().create_task(self._handle(*popped))
            task.add_done_callback(
                lambda t: t.cancelled() or t.exception()  # observe, never raise
            )

    async def _dead_letter(self, item_id: str, req: RemotePrefillRequest) -> None:
        """Redelivery cap hit (a poison item that keeps killing its
        consumer, or a decode target that nacks every attempt): park it
        on `<queue>.dead` and error-finish the decode side so its waiter
        stops burning the transfer timeout (docs/operations.md)."""
        logger.error(
            "dead-lettering prefill %s after %d attempts",
            req.request_id, req.attempts,
        )
        self.dead_letters += 1
        try:
            await self.queue.dead_letter(req)
        except Exception:
            logger.exception("dead-letter push for %s failed", req.request_id)
        try:
            await self.transfer.send_error(
                req.transfer_host, req.transfer_port, req.request_id,
                f"remote prefill dead-lettered after {req.attempts} attempts",
            )
        except Exception:
            # decode side may be long gone (its waiter timed out) —
            # the dead-letter parking is what matters
            logger.warning(
                "dead-letter notify for %s failed", req.request_id,
                exc_info=True,
            )
        await self.queue.ack(item_id)

    @staticmethod
    def _expired(req: RemotePrefillRequest) -> bool:
        import time

        return bool(req.deadline) and time.time() > float(req.deadline)

    async def _handle(self, item_id: str, req: RemotePrefillRequest) -> None:
        if req.attempts >= self.MAX_ATTEMPTS:
            try:
                await self._dead_letter(item_id, req)
            except Exception:
                logger.exception("dead-letter of %s failed", req.request_id)
            finally:
                self._sem.release()
            return
        if self._expired(req):
            # the client's deadline already passed: never spend prefill
            # flops on it — and TELL the decode side, whose waiter would
            # otherwise sit out the whole transfer timeout holding its
            # page reservation and the client connection
            self.deadline_drops += 1
            logger.info(
                "dropping expired prefill %s (deadline passed)",
                req.request_id,
            )
            try:
                try:
                    await self.transfer.send_error(
                        req.transfer_host, req.transfer_port,
                        req.request_id,
                        "remote prefill dropped: deadline expired",
                    )
                except Exception:
                    logger.warning(
                        "expiry notify for %s failed", req.request_id,
                        exc_info=True,
                    )
                await self.queue.ack(item_id)
            except Exception:
                logger.exception("ack of expired %s failed", req.request_id)
            finally:
                self._sem.release()
            return
        try:
            from dynamo_tpu import telemetry

            # parented on the decode worker's disagg span via the queue
            # item's trace context; a fresh trace when absent/off
            with telemetry.span(
                "disagg.prefill", service="prefill",
                parent=req.trace or None,
                attrs={"request_id": req.request_id,
                       "isl_tokens": len(req.token_ids),
                       "attempt": req.attempts},
            ):
                await self._prefill_and_transfer(req)
            await self.queue.ack(item_id)
            self.prefills_done += 1
        except Exception:
            logger.exception("remote prefill %s failed", req.request_id)
            # Bounded retry: requeue a fresh copy with attempts+1 and ack the
            # original, so a permanently-failing item (dead decode worker,
            # config skew) can't cycle through the fleet forever — at the
            # cap it dead-letters WITH an error finish to the decode side.
            try:
                req.attempts += 1
                if req.attempts < self.MAX_ATTEMPTS:
                    await self.queue.push(req)
                    await self.queue.ack(item_id)
                else:
                    await self._dead_letter(item_id, req)
            except Exception:
                logger.exception("requeue of %s failed", req.request_id)
        finally:
            self._sem.release()

    async def _prefill_and_transfer(self, req: RemotePrefillRequest) -> None:
        rid = req.request_id
        runner = self.runner
        if req.model and req.model != self.engine_config.model:
            raise RuntimeError(
                f"model mismatch: request for {req.model!r}, this prefill "
                f"worker serves {self.engine_config.model!r}"
            )
        s = req.sampling
        sampling = SamplingParams(
            temperature=float(s.get("temperature", 0.0)),
            top_p=float(s.get("top_p", 1.0)),
            top_k=int(s.get("top_k", 0)),
            seed=s.get("seed"),
            max_tokens=1,
            ignore_eos=True,  # always produce the one token; decode applies stops
        )
        out_q = runner.watch_request(rid)

        def _add(eng):
            r = eng.add_request(rid, req.token_ids, sampling)
            r.hold_pages = True
            return r

        await runner.submit(_add)
        first_token: Optional[int] = None
        try:
            while True:
                item = await out_q.get()
                if item is None:
                    break
                if "error" in item:
                    raise RuntimeError(item["error"])
                if item.get("token_ids"):
                    first_token = item["token_ids"][0]
        finally:
            runner.unwatch_request(rid)
        if first_token is None:
            raise RuntimeError(f"prefill of {rid} produced no token")

        def _extract(eng):
            pages = eng.scheduler.held.get(rid)
            if pages is None:
                raise RuntimeError(f"held pages for {rid} missing")
            # decode reserved ceil((len+1)/ps) pages; we transfer the prompt
            # KV — the first-token page slot is recomputed decode-side.
            # DEVICE arrays: the device transfer path stages them directly;
            # only a host-path fallback pays the device→host copy.
            return pages, eng.extract_pages_async(pages)

        pages, (k, v) = await runner.submit(_extract)
        try:
            if len(pages) != len(req.page_ids):
                raise RuntimeError(
                    f"page count mismatch: prefill {len(pages)} vs decode "
                    f"{len(req.page_ids)} (page_size/config skew?)"
                )
            ok = await self.transfer.send(
                req.transfer_host, req.transfer_port, rid, req.page_ids,
                k, v, first_token,
            )
            if not ok:
                raise RuntimeError("decode side nacked the KV write")
        finally:
            await runner.submit(lambda eng: eng.scheduler.release_held(rid))

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if getattr(self, "_flush_task", None) is not None:
            self._flush_sub.close()
            self._flush_task.cancel()
        self.transfer.close()
        if self.registration is not None:
            await self.registration.deregister()
        if self.runner is not None and self._own_runner:
            self.runner.stop()

"""Device-path KV transfer plane: XLA transfer-server pull over ICI/DCN.

The host path (transfer.py) moves pages device→host→TCP→host→device. This
module is the NIXL-RDMA equivalent the reference uses for bulk KV movement
(/root/reference lib/llm/src/block_manager/block/transfer.rs:83-111,
storage/nixl.rs:231), re-designed for TPU: the prefill process STAGES its
KV pages (still device-resident jax arrays) on an XLA transfer server and
the decode process PULLS them directly into its own device memory — the
bulk bytes ride the PjRt transfer fabric (ICI intra-slice, DCN across
hosts), never the Python host path. Only a tiny "offer" control frame rides
the existing TCP channel, mirroring the reference's metadata-rendezvous
pattern (examples/llm/utils/nixl.py:58-86).

Strategy selection (DYN_KV_TRANSFER):
  auto   — device plane on the TPU backend; host path elsewhere. The CPU
           backend's transfer server only has an IN-process bulk
           transport: a cross-process pull fatally aborts the sender
           (`LocalBulkTransportFactory::RecvBulkTransport` CHECK), so auto
           never risks it off-TPU. Per-transfer fallback to the host path
           on nack or pull failure.
  host   — force the host TCP path (payload frames).
  device — device plane on any backend (tests use this for in-process CPU
           pulls; do NOT set it on multi-process CPU clusters).

Staged arrays that are never pulled (decode nacked or died before pulling)
are dropped only when the transfer server shuts down — bounded by failed
transfers, same trade the reference accepts for un-consumed NIXL
registrations.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
from typing import Optional, Sequence

logger = logging.getLogger(__name__)

_MODE_ENV = "DYN_KV_TRANSFER"
#: bind/advertise host for the transfer server (must be routable from
#: peers in multi-host deployments; default binds all interfaces and
#: advertises what PjRt reports)
_ADDR_ENV = "DYN_TRANSFER_HOST"

_advertise_host: Optional[str] = None

_uuid_lock = threading.Lock()
_uuid_next = 1


def configure(advertise_host: Optional[str]) -> None:
    """Set the host peers should PULL from BEFORE the plane first starts
    (workers call this with their --host/advertise address). Loopback
    stays unset — the default bind already serves same-host peers."""
    global _advertise_host
    if advertise_host and advertise_host not in ("127.0.0.1", "localhost"):
        _advertise_host = advertise_host


def _next_uuid() -> int:
    """Unique per transfer-server (one server per process): pid-salted so a
    restarted sender can't collide with an old uuid a peer still holds."""
    global _uuid_next
    with _uuid_lock:
        n = _uuid_next
        _uuid_next += 1
    return ((os.getpid() & 0x3FFFFF) << 40) | (n & ((1 << 40) - 1))


def mode() -> str:
    m = os.environ.get(_MODE_ENV, "auto").lower()
    return m if m in ("auto", "host", "device") else "auto"


def available() -> bool:
    """True when this jax build ships the transfer-server module the
    device plane is built on (jax.experimental.transfer). Some builds —
    including the baked CPU toolchain in CI containers — omit it; tests
    that force DYN_KV_TRANSFER=device gate on this instead of failing
    collection-deep with an ImportError."""
    import importlib.util

    try:
        return importlib.util.find_spec("jax.experimental.transfer") is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


class DevicePlane:
    """Process-wide wrapper around jax.experimental.transfer.

    Sender: stage(arrays) -> (address, uuid); receiver: pull(address, uuid,
    specs) -> arrays on this process's default device. One server and one
    connection-per-peer are shared by all transfers in the process.
    """

    _singleton: Optional["DevicePlane"] = None
    _failed = False
    _lock = threading.Lock()

    def __init__(self):
        import jax
        from jax.experimental import transfer as jax_transfer

        self._jax = jax
        client = jax.devices()[0].client
        host = os.environ.get(_ADDR_ENV) or _advertise_host
        if host:
            if ":" in host and not host.startswith("["):
                host = f"[{host}]"  # IPv6 literals need brackets
            self._server = jax_transfer.start_transfer_server(
                client, address=f"{host}:0"
            )
        else:
            self._server = jax_transfer.start_transfer_server(client)
        self._address = self._server.address()
        self._conns: dict[str, object] = {}
        self._conn_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def get(cls) -> Optional["DevicePlane"]:
        """The process's device plane, or None when unsupported/disabled."""
        m = mode()
        if m == "host":
            return None
        if m == "auto":
            import jax

            if jax.default_backend() != "tpu":
                return None
        with cls._lock:
            if cls._singleton is not None:
                return cls._singleton
            if cls._failed:
                return None
            try:
                cls._singleton = cls()
            except Exception:
                if mode() == "device":
                    raise
                logger.info("device KV plane unavailable; using host path",
                            exc_info=True)
                cls._failed = True
                return None
            logger.info(
                "device KV transfer plane up at %s", cls._singleton._address
            )
            return cls._singleton

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._singleton = None
            cls._failed = False

    @property
    def address(self) -> str:
        return self._address

    # -- sender ------------------------------------------------------------

    def stage(self, arrays: Sequence) -> int:
        """Schedule device arrays for one remote pull; returns the uuid the
        peer must pull with."""
        uuid = _next_uuid()
        self._server.await_pull(uuid, list(arrays))
        return uuid

    # -- receiver ----------------------------------------------------------

    def _connection(self, address: str):
        with self._conn_lock:
            conn = self._conns.get(address)
            if conn is None:
                conn = self._server.connect(address)
                self._conns[address] = conn
            return conn

    def _pull_sync(self, address: str, uuid: int, k_shape, v_shape,
                   dtype) -> tuple:
        import jax
        from jax.sharding import SingleDeviceSharding

        sharding = SingleDeviceSharding(jax.devices()[0])
        specs = [
            jax.ShapeDtypeStruct(tuple(k_shape), dtype, sharding=sharding),
            jax.ShapeDtypeStruct(tuple(v_shape), dtype, sharding=sharding),
        ]
        conn = self._connection(address)
        k, v = conn.pull(uuid, specs)
        return k, v

    async def pull(self, address: str, uuid: int, k_shape, v_shape,
                   dtype) -> tuple:
        """Pull (k, v) staged under uuid from the peer at address; arrays
        land on this process's default device. k and v carry their OWN
        shapes (MLA caches are asymmetric: latent vs rope-key widths).
        Blocking PjRt call runs in the default executor so the event loop
        stays live."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._pull_sync, address, uuid, k_shape, v_shape, dtype
        )

"""Disaggregated prefill/decode serving.

Long prompts stall decode batches: one chunked prefill shares engine steps
with every decoding sequence. Disaggregation moves qualifying prefills to
dedicated prefill workers: the decode worker reserves KV pages, enqueues a
RemotePrefillRequest on a shared durable queue, any prefill worker computes
the prompt KV and writes it straight into the reserved pages through the
KV transfer plane, and decode continues from the first sampled token
(capability parity with the reference's disagg serving —
/root/reference lib/llm/src/disagg_router.rs, examples/llm prefill_queue.py
+ prefill_worker.py, docs dynamo_flow.md:12-44 — with the NIXL RDMA write
replaced by an explicit page-transfer service; on TPU the same interface
can ride ICI collectives intra-slice or DCN streams across slices).
"""

from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.disagg.protocol import RemotePrefillRequest
from dynamo_tpu.disagg.router import DisaggConfig, DisaggregatedRouter
from dynamo_tpu.disagg.transfer import KvTransferClient, KvTransferServer

__all__ = [
    "DisaggConfig",
    "DisaggregatedRouter",
    "KvTransferClient",
    "KvTransferServer",
    "PrefillQueue",
    "RemotePrefillRequest",
]

"""Conditional-disaggregation policy.

Remote prefill pays a queue hop plus a page transfer, so it only wins when
the prefill is long (after prefix-cache credit) and the prefill fleet has
headroom. The policy is a live config watched from the fabric, so operators
can retune thresholds on a running system without restarts (reference:
DisaggregatedRouter — /root/reference lib/llm/src/disagg_router.rs:242,
etcd-watched config :38).
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger(__name__)

DISAGG_CONFIG_KEY = "v1/config/disagg"


@dataclass
class DisaggConfig:
    #: prefills at or below this many uncached tokens stay local
    max_local_prefill_length: int = 512
    #: skip remote when the shared queue is already this deep
    max_prefill_queue_size: int = 8
    #: give up on a transfer and prefill locally after this long
    transfer_timeout_s: float = 30.0

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_json(data: bytes) -> "DisaggConfig":
        d = json.loads(data)
        return DisaggConfig(
            **{k: v for k, v in d.items() if k in DisaggConfig.__dataclass_fields__}
        )


class DisaggregatedRouter:
    def __init__(self, fabric, config: Optional[DisaggConfig] = None):
        self.fabric = fabric
        self.config = config or DisaggConfig()
        self._task: Optional[asyncio.Task] = None
        self._watch = None

    async def start(self) -> None:
        """Load the fabric-stored config (if any) and follow updates."""
        data = await self.fabric.get(DISAGG_CONFIG_KEY)
        if data:
            self.config = DisaggConfig.from_json(data)
        self._watch = await self.fabric.watch_prefix(DISAGG_CONFIG_KEY)
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        async for ev in self._watch:
            if ev.kind == "put":
                try:
                    self.config = DisaggConfig.from_json(ev.value)
                    logger.info("disagg config updated: %s", self.config)
                except Exception:
                    logger.exception("bad disagg config update")

    def prefill_remote(
        self, prefill_length: int, prefix_hit_length: int, queue_depth: int
    ) -> bool:
        """Remote iff the *uncached* prefill exceeds the local threshold and
        the queue is not overloaded."""
        uncached = prefill_length - prefix_hit_length
        return (
            uncached > self.config.max_local_prefill_length
            and queue_depth < self.config.max_prefill_queue_size
        )

    async def stop(self) -> None:
        if self._watch is not None:
            self._watch.close()
        if self._task is not None:
            self._task.cancel()


async def publish_disagg_config(fabric, config: DisaggConfig) -> None:
    await fabric.put(DISAGG_CONFIG_KEY, config.to_json())

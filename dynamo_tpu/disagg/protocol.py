"""Wire types for the disaggregation planes."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import msgpack


@dataclass
class RemotePrefillRequest:
    """One unit of work on the shared prefill queue (reference:
    RemotePrefillRequest — examples/llm/utils/protocol.py:30-105).

    page_ids are the *decode* worker's reserved pages; the prefill worker
    maps its computed pages onto them 1:1 in the transfer write."""

    request_id: str
    token_ids: list[int]
    page_ids: list[int]
    transfer_host: str
    transfer_port: int
    #: sampling for the first token (the prefill worker samples it)
    sampling: dict[str, Any] = field(default_factory=dict)
    model: str = ""
    #: delivery attempts so far; requeued with +1 on failure, dropped at cap
    attempts: int = 0
    #: trace context ({"trace_id", "span_id"}) so the prefill worker's
    #: spans stitch under the decode worker's disagg span; empty when
    #: tracing is off (telemetry/trace.py)
    trace: dict[str, Any] = field(default_factory=dict)
    #: end-to-end deadline (epoch seconds; None = none): a prefill
    #: worker drops expired items instead of spending flops on a client
    #: that already gave up (docs/operations.md)
    deadline: Any = None

    def pack(self) -> bytes:
        return msgpack.packb(dict(self.__dict__), use_bin_type=True)

    @staticmethod
    def unpack(data: bytes) -> "RemotePrefillRequest":
        return RemotePrefillRequest(**msgpack.unpackb(data, raw=False))

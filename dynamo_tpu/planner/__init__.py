"""Planner — the autoscaler (reference: components/planner, SURVEY.md #40).

Watches the worker load plane (MetricsAggregator snapshots + the disagg
prefill-queue depth) and adjusts the decode/prefill fleet:

- `LoadPlanner` — threshold + hysteresis on KV usage and queue pressure
  (reference utils/planner_core.py:31-120).
- `SlaPlanner` — predicts the request rate (load predictors) and sizes the
  fleet from offline perf-interpolation tables so predicted TTFT/ITL stay
  inside targets (reference planner_sla.py + utils/perf_interpolation.py).
- `ClosedLoopPlanner` + `ControlRunner` — the live closed loop: scales on
  the fleet's OBSERVED SLO burn/attainment (worker SLO sketches merged by
  the telemetry plane) with hysteresis bands, per-role cooldowns, a
  per-tick action clamp, and role FLIPS through the drain + re-register
  path (docs/operations.md "Closed-loop autoscaling & role flips").

Actuation goes through a `Connector`: `LocalConnector` spawns/stops worker
processes on this host (reference's circus LocalConnector,
local_connector.py:105); `RecordingConnector` is the test double. A k8s
connector maps to editing DynamoGraphDeployment replicas (deploy/ manifests)
and is intentionally out of process scope here.
"""

from dynamo_tpu.planner.load_predictor import (
    ArPredictor,
    ConstantPredictor,
    HoltWintersPredictor,
    MovingAveragePredictor,
    TrendPredictor,
    make_predictor,
)
from dynamo_tpu.planner.perf_model import PerfInterpolator
from dynamo_tpu.planner.planner import (
    Actions,
    ClosedLoopPlanner,
    Connector,
    ControlConfig,
    ControlRunner,
    LoadPlanner,
    LocalConnector,
    PlannerConfig,
    RecordingConnector,
    SlaPlanner,
)

__all__ = [
    "ArPredictor",
    "ConstantPredictor",
    "HoltWintersPredictor",
    "MovingAveragePredictor",
    "TrendPredictor",
    "make_predictor",
    "PerfInterpolator",
    "PlannerConfig",
    "ControlConfig",
    "Actions",
    "LoadPlanner",
    "SlaPlanner",
    "ClosedLoopPlanner",
    "ControlRunner",
    "Connector",
    "LocalConnector",
    "RecordingConnector",
]

"""Planner service wiring: observe the live fleet through the fabric.

FleetObserver assembles a FleetState from three sources:
- lease discovery (InstanceSource) — who is alive, decode vs prefill
- the worker metrics plane (MetricsAggregator) — KV usage, queue depth
- the disagg prefill queue — backlog depth
and derives request_rate from the fleet-wide requests_received counter
(reference: the planner scrapes Prometheus frontend counters,
utils/prometheus.py; here the worker metrics plane carries it directly).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.kv_router.metrics_aggregator import MetricsAggregator
from dynamo_tpu.planner.planner import FleetState
from dynamo_tpu.runtime.component import InstanceSource

logger = logging.getLogger(__name__)


class FleetObserver:
    def __init__(
        self,
        runtime,
        namespace: str = "dynamo",
        decode_component: str = "backend",
        decode_endpoint: str = "generate",
        prefill_component: str = "prefill",
        prefill_endpoint: str = "prefill",
        queue_name: str = "prefill_queue",
    ):
        fabric = runtime.fabric
        self._decode_src = InstanceSource(
            fabric, namespace, decode_component, decode_endpoint
        )
        self._prefill_src = InstanceSource(
            fabric, namespace, prefill_component, prefill_endpoint
        )
        self.metrics = MetricsAggregator(fabric, decode_component)
        self.queue = PrefillQueue(fabric, queue_name)
        #: per-instance last requests_received — rate sums per-instance
        #: deltas, so a worker leaving the fleet doesn't read as negative
        #: load (its counter simply stops contributing)
        self._last_received: dict[str, int] = {}
        self._last_ts: float = 0.0
        self._have_baseline = False

    async def start(self) -> None:
        await self._decode_src.start()
        await self._prefill_src.start()
        await self.metrics.start()

    async def stop(self) -> None:
        await self._decode_src.stop()
        await self._prefill_src.stop()
        await self.metrics.stop()

    async def observe(self) -> FleetState:
        decode = self._decode_src.list()
        prefill = self._prefill_src.list()
        snap = self.metrics.snapshot()
        usages = [m.get("kv_usage", 0.0) for m in snap.values()]
        waiting = sum(int(m.get("num_waiting", 0)) for m in snap.values())
        now = time.monotonic()
        delta = 0
        current: dict[str, int] = {}
        for iid, m in snap.items():
            count = int(m.get("requests_received", 0))
            current[iid] = count
            prev = self._last_received.get(iid)
            if prev is not None:
                # Per-instance: restarts (count < prev) floor at 0; a fresh
                # instance contributes from its next sample.
                delta += max(0, count - prev)
        rate = 0.0
        if self._have_baseline and now > self._last_ts:
            rate = delta / (now - self._last_ts)
        self._last_received = current
        self._last_ts = now
        self._have_baseline = True
        try:
            depth = await self.queue.depth()
        except Exception:
            logger.debug("prefill queue depth unavailable", exc_info=True)
            depth = 0
        # Observed-SLA input (fleet telemetry plane): fold the SLO
        # sketches riding the worker metrics frames into live TTFT/ITL
        # p95s + attainment. Optional by design — a fleet without
        # fleet_telemetry (or a garbage wire) leaves the fields None and
        # the planner keeps running on its offline tables.
        ttft_p95 = itl_p95 = attain = burn = None
        try:
            from dynamo_tpu.telemetry import slo as slo_mod

            wires = [
                m["slo"]
                for m in snap.values()
                if isinstance(m.get("slo"), dict)
            ]
            if wires:
                merged = slo_mod.merge_trackers(wires)
                if merged.sources:
                    ttft_p95 = merged.sketches["ttft_ms"].quantile(0.95)
                    itl_p95 = merged.sketches["itl_ms"].quantile(0.95)
                    # SLIDING-WINDOW attainment, not lifetime: the
                    # control signal must recover once the fleet does —
                    # a lifetime ratio would carry one bad burst forever
                    # and block every later scale-down. Empty windows
                    # (idle fleet) leave it None, which the planner
                    # treats as unconstrained.
                    attains = [
                        merged.attainment(w)
                        for w, (n, _) in merged.windows.items()
                        if n > 0
                    ]
                    attain = min(attains) if attains else None
                    # worst (shortest-window) burn — the paging signal
                    # the closed-loop planner scales up on
                    burns = [
                        merged.burn_rate(w)
                        for w, (n, _) in merged.windows.items()
                        if n > 0
                    ]
                    if burns:
                        burn = max(burns)
        except Exception:
            logger.debug("observed-SLA fold failed", exc_info=True)
        return FleetState(
            num_decode=len(decode),
            num_prefill=len(prefill),
            kv_usage=sum(usages) / len(usages) if usages else 0.0,
            num_waiting=waiting,
            prefill_queue_depth=depth,
            request_rate=rate,
            observed_ttft_p95_ms=ttft_p95,
            observed_itl_p95_ms=itl_p95,
            sla_attainment=attain,
            burn_rate=burn,
        )


class FleetFlipper:
    """Actuates a role flip on a live worker: picks the least-busy
    flippable instance of the source role and calls its `flip` ingress
    op (Worker._flip_handler). Only workers that advertise
    `flippable: true` in their registration metadata qualify — plain
    PrefillWorker processes have no ingress and can't flip."""

    def __init__(self, observer: FleetObserver):
        self.observer = observer
        self.flips = 0

    def _source(self, role: str):
        return (
            self.observer._decode_src
            if role == "decode"
            else self.observer._prefill_src
        )

    async def __call__(self, from_role: str, to_role: str) -> bool:
        from dynamo_tpu.handover import call_ingress

        candidates = [
            inst
            for inst in self._source(from_role).list()
            if inst.metadata.get("flippable") and inst.port
        ]
        if not candidates:
            return False
        snap = self.observer.metrics.snapshot()
        victim = min(
            candidates,
            key=lambda i: (
                int(snap.get(i.instance_id, {}).get("num_running", 0) or 0),
                i.instance_id,
            ),
        )
        # one-shot direct call to the victim's ingress `flip` op — the
        # worker acks immediately and winds the flip down in background
        try:
            await call_ingress(
                victim.host, victim.port, "flip", {"role": to_role},
                timeout=5.0, request_id=f"flip-{self.flips}",
            )
        except Exception:
            logger.warning(
                "flip call to %s failed", victim.instance_id, exc_info=True
            )
            return False
        self.flips += 1
        logger.info(
            "flip %s->%s dispatched to %s", from_role, to_role,
            victim.instance_id,
        )
        return True


class FleetHandover:
    """Actuates a worker handover (docs/operations.md "Rolling upgrades &
    worker handover"): picks the least-busy flippable instance of the
    role (or the named victim), calls its `handover` ingress op — the
    worker migrates its KV to a peer it picks itself, finishes/severs
    its streams and exits 0. Used by ControlRunner's scale-down path and
    the rolling-upgrade sweep."""

    def __init__(self, observer: FleetObserver, economy=None):
        self.observer = observer
        #: optional FleetKvEconomy: when set, a victim whose resident KV
        #: prices BELOW the migration threshold is not handed over —
        #: kill+recompute is cheaper than shipping its pages (the same
        #: worth_it() the router uses per-prefix, at worker granularity)
        self.economy = economy
        self.handovers = 0

    def _source(self, role: str):
        return (
            self.observer._decode_src
            if role == "decode"
            else self.observer._prefill_src
        )

    async def __call__(
        self,
        role: str,
        victim_id: Optional[str] = None,
        successor_id: Optional[str] = None,
    ) -> bool:
        from dynamo_tpu.handover import call_ingress

        candidates = [
            inst
            for inst in self._source(role).list()
            if inst.metadata.get("flippable")
            and inst.port
            and (victim_id is None or inst.instance_id == victim_id)
        ]
        if len(self._source(role).list()) < 2 or not candidates:
            # a lone worker has no successor; retiring it would drop the
            # pool to zero — refuse, the caller falls back to kill/spawn
            return False
        snap = self.observer.metrics.snapshot()
        victim = min(
            candidates,
            key=lambda i: (
                int(snap.get(i.instance_id, {}).get("num_running", 0) or 0),
                i.instance_id,
            ),
        )
        if self.economy is not None and not self.economy.retire_worth_it(
            victim.instance_id
        ):
            return False
        try:
            await call_ingress(
                victim.host, victim.port, "handover",
                {"successor": successor_id},
                timeout=5.0, request_id=f"handover-{self.handovers}",
            )
        except Exception:
            logger.warning(
                "handover call to %s failed", victim.instance_id,
                exc_info=True,
            )
            return False
        self.handovers += 1
        logger.info(
            "handover dispatched to %s (%s)", victim.instance_id, role
        )
        return True


class FleetKvEconomy:
    """Actuator-side KV economy for the planner (docs/operations.md
    "The KV economy"): the SAME CostModel the router and bench consult
    prices the planner's worker-granularity moves, so flip-with-warm-KV,
    whole-worker handover, and per-prefix migration are one primitive at
    three sizes:

    - scale-down: `retire_worth_it` — is the victim's resident KV worth
      shipping (handover), or is kill+recompute cheaper? FleetHandover
      asks before dispatching; a "no" falls through to the connector.
    - scale-up: `prewarm` — warm the coldest worker of the role from
      the hottest one via `migrate_prefix {auto}` (the donor picks its
      own deepest chain). ControlRunner schedules one after each
      scale-up actuation, so a newcomer's first requests can land warm.
    """

    def __init__(
        self,
        observer: FleetObserver,
        cost_model,
        prewarm_blocks: int = 32,
        call_timeout_s: float = 30.0,
    ):
        self.observer = observer
        self.cost_model = cost_model
        self.prewarm_blocks = prewarm_blocks
        self.call_timeout_s = call_timeout_s
        self.prewarms = 0
        self.prewarm_failures = 0
        self.handovers_skipped = 0

    def _source(self, role: str):
        return (
            self.observer._decode_src
            if role == "decode"
            else self.observer._prefill_src
        )

    def _blocks(self, snap: dict, instance_id: str) -> int:
        return int(
            snap.get(instance_id, {}).get("kv_active_pages", 0) or 0
        )

    def retire_worth_it(self, instance_id: str) -> bool:
        """Price the victim's resident KV as one big migration: a
        handover ships every registered page; a kill recomputes them."""
        blocks = self._blocks(
            self.observer.metrics.snapshot(), instance_id
        )
        ok = self.cost_model.worth_it(self.cost_model.price(blocks))
        if not ok:
            self.handovers_skipped += 1
        return ok

    async def prewarm(self, role: str) -> bool:
        """One hot-to-cold prefix migration inside `role`: donor = the
        worker with the most resident pages, target = the one with the
        fewest (a just-registered newcomer has zero). No-op unless the
        warmth gap prices above the shared migration threshold."""
        from dynamo_tpu.handover import call_ingress

        insts = [i for i in self._source(role).list() if i.port]
        if len(insts) < 2:
            return False
        snap = self.observer.metrics.snapshot()
        donor = max(
            insts, key=lambda i: (self._blocks(snap, i.instance_id),
                                  i.instance_id),
        )
        cold = min(
            insts, key=lambda i: (self._blocks(snap, i.instance_id),
                                  i.instance_id),
        )
        if donor.instance_id == cold.instance_id:
            return False
        gap = (
            self._blocks(snap, donor.instance_id)
            - self._blocks(snap, cold.instance_id)
        )
        if not self.cost_model.should_migrate(
            min(gap, self.prewarm_blocks)
        ):
            return False
        try:
            reply = await call_ingress(
                donor.host, donor.port, "migrate_prefix",
                {
                    "auto": True,
                    "max_blocks": self.prewarm_blocks,
                    "dest": {
                        "instance_id": cold.instance_id,
                        "host": cold.host,
                        "port": cold.port,
                    },
                },
                timeout=self.call_timeout_s,
                request_id=f"prewarm-{self.prewarms}",
            )
        except Exception:
            self.prewarm_failures += 1
            logger.warning(
                "prewarm migrate call to %s failed", donor.instance_id,
                exc_info=True,
            )
            return False
        if not (isinstance(reply, dict) and reply.get("migrated")):
            self.prewarm_failures += 1
            return False
        self.prewarms += 1
        logger.info(
            "prewarm: %s -> %s (%s blocks)", donor.instance_id,
            cold.instance_id, reply.get("blocks"),
        )
        return True


async def rolling_upgrade(
    observer: FleetObserver,
    connector,
    handover: FleetHandover,
    roles=("decode", "prefill"),
    cooldown_s: float = 5.0,
    step_timeout_s: float = 120.0,
    status_cb=None,
) -> dict:
    """Replace every worker in the fleet, one at a time, with zero
    dropped streams (docs/operations.md "Rolling upgrades & worker
    handover" — the `dynamo planner --rolling-upgrade` sweep):

    for each worker of each role, oldest-first:
      1. spawn a replacement (connector.scale to n+1) and wait for it to
         register — capacity never dips below steady state;
      2. hand the victim over (its KV migrates to a peer, its streams
         continue there via replay) and wait for it to deregister;
      3. flip-style cooldown before the next victim.

    Workers that appear DURING the sweep (the replacements) are not
    re-upgraded — the victim set is snapshotted per role up front.
    Returns a summary dict: upgraded / failed instance ids per role."""
    summary: dict = {}
    for role in roles:
        src = (
            observer._decode_src if role == "decode" else observer._prefill_src
        )
        victims = [i.instance_id for i in src.list()]
        done: list[str] = []
        failed: list[str] = []
        summary[role] = {"planned": list(victims), "upgraded": done,
                         "failed": failed}
        async def shed_spare(n0: int) -> None:
            """A victim we failed to retire keeps serving while its
            replacement is already up: scale the role back to n0 (the
            connector stops the youngest child = the spare). Without
            this, --rolling-upgrade one-shot mode — which exits after
            the sweep, no steady-state loop behind it — would leave the
            fleet one worker larger per failure, compounding."""
            cur = len(src.list())
            if cur > n0:
                await connector.scale(role, n0, cur)

        for victim in victims:
            n0 = len(src.list())
            if victim not in {i.instance_id for i in src.list()}:
                continue  # already gone (crashed / externally retired)
            if status_cb is not None:
                await status_cb(
                    {"phase": "spawn", "role": role, "victim": victim}
                )
            # 1. replacement first: the fleet never runs a worker short
            await connector.scale(role, n0 + 1, n0)
            deadline = time.monotonic() + step_timeout_s
            while time.monotonic() < deadline and len(src.list()) < n0 + 1:
                await asyncio.sleep(0.25)
            if len(src.list()) < n0 + 1:
                logger.warning(
                    "rolling upgrade: replacement for %s never registered; "
                    "skipping this victim", victim,
                )
                failed.append(victim)
                continue
            # baseline refresh (no-op delta): tell the connector the
            # replacement REGISTERED. LocalConnector retires a spawned
            # child's pending-capacity credit only when the observed
            # count rises between its scale() calls — and in a 1-for-1
            # rolling sweep the count returns to n0 before the next
            # call, so without this the credit never retires and every
            # later victim's replacement spawn is silently suppressed
            # (found by the live CLI drive, 2026-08-04).
            await connector.scale(role, n0 + 1, n0 + 1)
            # 2. retire the victim via handover (falls back to drain
            # inside the worker; either way it deregisters and exits 0)
            if status_cb is not None:
                await status_cb(
                    {"phase": "handover", "role": role, "victim": victim}
                )
            ok = await handover(role, victim_id=victim)
            if not ok:
                logger.warning(
                    "rolling upgrade: handover call to %s failed", victim
                )
                failed.append(victim)
                await shed_spare(n0)
                continue
            deadline = time.monotonic() + step_timeout_s
            while time.monotonic() < deadline and victim in {
                i.instance_id for i in src.list()
            }:
                await asyncio.sleep(0.25)
            if victim in {i.instance_id for i in src.list()}:
                logger.warning(
                    "rolling upgrade: %s still registered after its "
                    "handover budget", victim,
                )
                failed.append(victim)
                await shed_spare(n0)
                continue
            done.append(victim)
            logger.info(
                "rolling upgrade: %s replaced (%d/%d %s)",
                victim, len(done), len(victims), role,
            )
            # 3. fleet-wide cooldown between victims (flip-style)
            await asyncio.sleep(cooldown_s)
    return summary

"""Planner cores + actuation connectors.

`LoadPlanner.tick()` / `SlaPlanner.tick()` are pure decision functions over
an observed state snapshot — the async runner (`run()`) just samples state on
an interval and applies decisions through the connector. Pure cores keep the
whole policy unit-testable with no processes or clocks (the reference tests
its planner the same way, components/planner/test/).
"""

from __future__ import annotations

import asyncio
import logging
import subprocess
import time
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from dynamo_tpu.planner.load_predictor import make_predictor
from dynamo_tpu.planner.perf_model import PerfInterpolator

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class PlannerConfig:
    interval_s: float = 10.0
    min_decode: int = 1
    max_decode: int = 8
    min_prefill: int = 0
    max_prefill: int = 4
    #: scale decode UP when mean kv usage crosses this...
    kv_usage_high: float = 0.85
    #: ...and DOWN when it stays under this for `down_stable_ticks`
    kv_usage_low: float = 0.4
    #: scale decode UP when total queued requests per worker crosses this
    waiting_per_worker_high: float = 4.0
    #: scale prefill UP when queue depth per prefill worker crosses this
    prefill_queue_per_worker_high: float = 2.0
    #: consecutive calm ticks required before any scale-down (hysteresis)
    down_stable_ticks: int = 3
    #: at most this many replicas added/removed per tick
    max_step: int = 1


@dataclass(frozen=True)
class FleetState:
    """One observation of the world, assembled by the runner."""

    num_decode: int
    num_prefill: int
    #: mean KV pool usage over live decode workers (0..1)
    kv_usage: float
    #: total requests waiting in decode schedulers
    num_waiting: int
    #: disagg prefill queue depth (0 when disagg is off)
    prefill_queue_depth: int
    #: request arrivals observed this interval (SLA planner)
    request_rate: float = 0.0
    #: OBSERVED SLA inputs (fleet telemetry plane, merged worker SLO
    #: sketches — docs/observability.md "Fleet view & SLO accounting").
    #: None when no worker published SLO frames yet; the planner's
    #: control loop today still runs on the perf-interpolation tables
    #: (ROADMAP item 4 closes the loop on these).
    observed_ttft_p95_ms: Optional[float] = None
    observed_itl_p95_ms: Optional[float] = None
    sla_attainment: Optional[float] = None


@dataclass(frozen=True)
class Decision:
    target_decode: int
    target_prefill: int

    def delta(self, state: FleetState) -> tuple[int, int]:
        return (
            self.target_decode - state.num_decode,
            self.target_prefill - state.num_prefill,
        )


class Connector(Protocol):
    async def scale(self, role: str, target: int, observed: int) -> None:
        """Move the fleet toward `target` given `observed` live (registered)
        workers — the connector may own only part of the fleet."""
        ...


class RecordingConnector:
    """Test double: records every scale call."""

    def __init__(self):
        self.calls: list[tuple[str, int, int]] = []

    async def scale(self, role: str, target: int, observed: int) -> None:
        self.calls.append((role, target, observed))


class LocalConnector:
    """Spawn/stop worker processes on this host (reference: circus
    local_connector.py:105 add_component / :197 remove_component).

    `spawn_cmd(role) -> argv` builds the worker command line (typically
    `python -m dynamo_tpu.cli.run run in=dyn out=jax --role <role> ...`).
    Deltas are computed against the OBSERVED registered count, not this
    connector's children, so externally started workers are part of the
    arithmetic; children that are alive but not yet registered (engines take
    seconds to init) count as pending capacity so ticks during startup don't
    over-spawn. Scale-down stops the youngest owned processes (graceful
    TERM; leases expire and routers prune them — SURVEY.md §5.3); workers
    this connector doesn't own can't be stopped and are logged instead."""

    def __init__(
        self,
        spawn_cmd: Callable[[str], list[str]],
        startup_grace_s: float = 30.0,
    ):
        self.spawn_cmd = spawn_cmd
        #: children spawned within this window count as pending capacity
        #: (engine init takes seconds before the lease registers)
        self.startup_grace_s = startup_grace_s
        #: per role: [proc, spawn_time, seen] — `seen` flips once the
        #: observed count rises, crediting the registration to the oldest
        #: unseen child so it stops counting as pending
        self._procs: dict[str, list[list]] = {}
        self._last_observed: dict[str, int] = {}

    def alive(self, role: str) -> int:
        procs = self._procs.setdefault(role, [])
        procs[:] = [e for e in procs if e[0].poll() is None]
        return len(procs)

    def _pending(self, role: str) -> int:
        now = time.monotonic()
        return sum(
            1
            for _, t, seen in self._procs.get(role, ())
            if not seen and now - t < self.startup_grace_s
        )

    async def scale(self, role: str, target: int, observed: int) -> None:
        self.alive(role)  # reap
        procs = self._procs[role]
        # Registrations since last tick retire pending credits, oldest first
        # (a child that both spawned AND registered must not count twice —
        # once in `observed` and once in pending).
        newly_seen = max(0, observed - self._last_observed.get(role, observed))
        self._last_observed[role] = observed
        for entry in sorted(procs, key=lambda e: e[1]):
            if newly_seen <= 0:
                break
            if not entry[2]:
                entry[2] = True
                newly_seen -= 1
        delta = target - observed
        if delta > 0:
            # Unseen children inside their startup grace are capacity the
            # observation hasn't caught up with — don't duplicate them.
            for _ in range(max(0, delta - self._pending(role))):
                argv = self.spawn_cmd(role)
                logger.info("planner: spawning %s worker: %s", role, argv)
                procs.append([subprocess.Popen(argv), time.monotonic(), False])
        elif delta < 0:
            to_stop = min(-delta, len(procs))
            for _ in range(to_stop):
                victim = procs.pop()[0]
                logger.info(
                    "planner: stopping %s worker pid=%s", role, victim.pid
                )
                victim.terminate()
            if to_stop < -delta:
                logger.warning(
                    "planner: want %d fewer %s workers but own only %d — "
                    "externally started workers must be stopped externally",
                    -delta, role, to_stop,
                )

    def stop_all(self) -> None:
        for procs in self._procs.values():
            for entry in procs:
                if entry[0].poll() is None:
                    entry[0].terminate()


def _clamp(v: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, v))


class LoadPlanner:
    """Threshold + hysteresis scaling on KV usage / queue pressure."""

    def __init__(self, config: PlannerConfig):
        self.config = config
        self._calm_ticks = 0
        self._prefill_calm_ticks = 0

    def tick(self, state: FleetState) -> Decision:
        c = self.config
        decode, prefill = state.num_decode, state.num_prefill

        waiting_pw = state.num_waiting / max(1, decode)
        pressure = (
            state.kv_usage >= c.kv_usage_high
            or waiting_pw >= c.waiting_per_worker_high
        )
        calm = state.kv_usage <= c.kv_usage_low and state.num_waiting == 0

        if pressure:
            self._calm_ticks = 0
            decode += c.max_step
        elif calm:
            self._calm_ticks += 1
            if self._calm_ticks >= c.down_stable_ticks:
                decode -= c.max_step
                self._calm_ticks = 0
        else:
            self._calm_ticks = 0

        queue_pw = state.prefill_queue_depth / max(1, state.num_prefill)
        if queue_pw >= c.prefill_queue_per_worker_high:
            self._prefill_calm_ticks = 0
            prefill += c.max_step
        elif state.prefill_queue_depth == 0 and state.num_prefill > c.min_prefill:
            # Same hysteresis as decode: prefill engines also take seconds to
            # come back, and the queue legitimately drains between ticks.
            self._prefill_calm_ticks += 1
            if self._prefill_calm_ticks >= c.down_stable_ticks:
                prefill -= c.max_step
                self._prefill_calm_ticks = 0
        else:
            self._prefill_calm_ticks = 0

        return Decision(
            target_decode=_clamp(decode, c.min_decode, c.max_decode),
            target_prefill=_clamp(prefill, c.min_prefill, c.max_prefill),
        )


@dataclass(frozen=True)
class SlaTargets:
    ttft_ms: float = 200.0
    itl_ms: float = 20.0


class SlaPlanner:
    """Predict next-interval request rate, size the decode fleet so each
    worker's share of the load keeps interpolated TTFT/ITL within targets.

    `ttft_vs_rate` / `itl_vs_rate` are per-worker tables: metric as a
    function of requests/s handled by ONE worker (from the offline profiler,
    benchmarks/profile_sla.py)."""

    def __init__(
        self,
        config: PlannerConfig,
        targets: SlaTargets,
        ttft_vs_rate: PerfInterpolator,
        itl_vs_rate: PerfInterpolator,
        predictor: str = "trend",
        predictor_window: int = 8,
        predictor_season: int = 0,
    ):
        self.config = config
        self.targets = targets
        self.ttft_vs_rate = ttft_vs_rate
        self.itl_vs_rate = itl_vs_rate
        self.predictor = make_predictor(
            predictor, predictor_window, season_length=predictor_season
        )
        #: prefill scaling rides the same queue policy as LoadPlanner
        self._load = LoadPlanner(config)

    def tick(self, state: FleetState) -> Decision:
        c = self.config
        self.predictor.observe(state.request_rate)
        predicted = self.predictor.predict()

        per_worker_cap = min(
            self.ttft_vs_rate.max_load_within(self.targets.ttft_ms),
            self.itl_vs_rate.max_load_within(self.targets.itl_ms),
        )
        if per_worker_cap <= 0:
            # No load level meets the SLA — pin the fleet at max and complain.
            logger.warning(
                "SLA targets unreachable at any load; scaling decode to max"
            )
            needed = c.max_decode
        else:
            needed = -(-predicted // per_worker_cap) if predicted > 0 else c.min_decode
        prefill = self._load.tick(state).target_prefill
        return Decision(
            target_decode=_clamp(int(needed), c.min_decode, c.max_decode),
            target_prefill=prefill,
        )


class PlannerRunner:
    """Samples FleetState on an interval and actuates decisions.

    `observe()` is injected (async () -> FleetState) so the runner is
    agnostic to where state comes from — MetricsAggregator + PrefillQueue in
    production, a stub in tests."""

    def __init__(
        self,
        planner,
        connector: Connector,
        observe,
        interval_s: Optional[float] = None,
    ):
        self.planner = planner
        self.connector = connector
        self.observe = observe
        self.interval_s = interval_s or planner.config.interval_s
        self._task: Optional[asyncio.Task] = None

    async def step(self) -> Decision:
        state = await self.observe()
        decision = self.planner.tick(state)
        d_decode, d_prefill = decision.delta(state)
        if d_decode:
            logger.info(
                "planner: decode %d -> %d", state.num_decode, decision.target_decode
            )
            await self.connector.scale(
                "decode", decision.target_decode, state.num_decode
            )
        if d_prefill:
            logger.info(
                "planner: prefill %d -> %d", state.num_prefill, decision.target_prefill
            )
            await self.connector.scale(
                "prefill", decision.target_prefill, state.num_prefill
            )
        return decision

    async def run(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("planner tick failed")
            await asyncio.sleep(self.interval_s)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

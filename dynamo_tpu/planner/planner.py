"""Planner cores + actuation connectors.

`LoadPlanner.tick()` / `SlaPlanner.tick()` are pure decision functions over
an observed state snapshot — the async runner (`run()`) just samples state on
an interval and applies decisions through the connector. Pure cores keep the
whole policy unit-testable with no processes or clocks (the reference tests
its planner the same way, components/planner/test/).
"""

from __future__ import annotations

import asyncio
import logging
import subprocess
import time
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from dynamo_tpu.planner.load_predictor import make_predictor
from dynamo_tpu.planner.perf_model import PerfInterpolator

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class PlannerConfig:
    interval_s: float = 10.0
    min_decode: int = 1
    max_decode: int = 8
    min_prefill: int = 0
    max_prefill: int = 4
    #: scale decode UP when mean kv usage crosses this...
    kv_usage_high: float = 0.85
    #: ...and DOWN when it stays under this for `down_stable_ticks`
    kv_usage_low: float = 0.4
    #: scale decode UP when total queued requests per worker crosses this
    waiting_per_worker_high: float = 4.0
    #: scale prefill UP when queue depth per prefill worker crosses this
    prefill_queue_per_worker_high: float = 2.0
    #: consecutive calm ticks required before any scale-down (hysteresis)
    down_stable_ticks: int = 3
    #: at most this many replicas added/removed per tick
    max_step: int = 1


@dataclass(frozen=True)
class FleetState:
    """One observation of the world, assembled by the runner."""

    num_decode: int
    num_prefill: int
    #: mean KV pool usage over live decode workers (0..1)
    kv_usage: float
    #: total requests waiting in decode schedulers
    num_waiting: int
    #: disagg prefill queue depth (0 when disagg is off)
    prefill_queue_depth: int
    #: request arrivals observed this interval (SLA planner)
    request_rate: float = 0.0
    #: OBSERVED SLA inputs (fleet telemetry plane, merged worker SLO
    #: sketches — docs/observability.md "Fleet view & SLO accounting").
    #: None when no worker published SLO frames yet; the closed-loop
    #: planner (ClosedLoopPlanner) drives on these and falls back to the
    #: queue/KV signals above until they arrive.
    observed_ttft_p95_ms: Optional[float] = None
    observed_itl_p95_ms: Optional[float] = None
    sla_attainment: Optional[float] = None
    #: worst (shortest-window) fleet SLO burn rate — >1 means the fleet
    #: is spending its error budget faster than the objective allows
    burn_rate: Optional[float] = None


@dataclass(frozen=True)
class Decision:
    target_decode: int
    target_prefill: int

    def delta(self, state: FleetState) -> tuple[int, int]:
        return (
            self.target_decode - state.num_decode,
            self.target_prefill - state.num_prefill,
        )


class Connector(Protocol):
    async def scale(self, role: str, target: int, observed: int) -> None:
        """Move the fleet toward `target` given `observed` live (registered)
        workers — the connector may own only part of the fleet."""
        ...


class RecordingConnector:
    """Test double: records every scale call."""

    def __init__(self):
        self.calls: list[tuple[str, int, int]] = []

    async def scale(self, role: str, target: int, observed: int) -> None:
        self.calls.append((role, target, observed))


class LocalConnector:
    """Spawn/stop worker processes on this host (reference: circus
    local_connector.py:105 add_component / :197 remove_component).

    `spawn_cmd(role) -> argv` builds the worker command line (typically
    `python -m dynamo_tpu.cli.run run in=dyn out=jax --role <role> ...`).
    Deltas are computed against the OBSERVED registered count, not this
    connector's children, so externally started workers are part of the
    arithmetic; children that are alive but not yet registered (engines take
    seconds to init) count as pending capacity so ticks during startup don't
    over-spawn. Scale-down stops the youngest owned processes (graceful
    TERM; leases expire and routers prune them — SURVEY.md §5.3); workers
    this connector doesn't own can't be stopped and are logged instead."""

    def __init__(
        self,
        spawn_cmd: Callable[[str], list[str]],
        startup_grace_s: float = 30.0,
    ):
        self.spawn_cmd = spawn_cmd
        #: children spawned within this window count as pending capacity
        #: (engine init takes seconds before the lease registers)
        self.startup_grace_s = startup_grace_s
        #: per role: [proc, spawn_time, seen] — `seen` flips once the
        #: observed count rises, crediting the registration to the oldest
        #: unseen child so it stops counting as pending
        self._procs: dict[str, list[list]] = {}
        self._last_observed: dict[str, int] = {}

    def alive(self, role: str) -> int:
        procs = self._procs.setdefault(role, [])
        procs[:] = [e for e in procs if e[0].poll() is None]
        return len(procs)

    def _pending(self, role: str) -> int:
        now = time.monotonic()
        return sum(
            1
            for _, t, seen in self._procs.get(role, ())
            if not seen and now - t < self.startup_grace_s
        )

    async def scale(self, role: str, target: int, observed: int) -> None:
        self.alive(role)  # reap
        procs = self._procs[role]
        # Registrations since last tick retire pending credits, oldest first
        # (a child that both spawned AND registered must not count twice —
        # once in `observed` and once in pending).
        newly_seen = max(0, observed - self._last_observed.get(role, observed))
        self._last_observed[role] = observed
        for entry in sorted(procs, key=lambda e: e[1]):
            if newly_seen <= 0:
                break
            if not entry[2]:
                entry[2] = True
                newly_seen -= 1
        delta = target - observed
        if delta > 0:
            # Unseen children inside their startup grace are capacity the
            # observation hasn't caught up with — don't duplicate them.
            for _ in range(max(0, delta - self._pending(role))):
                argv = self.spawn_cmd(role)
                logger.info("planner: spawning %s worker: %s", role, argv)
                procs.append([subprocess.Popen(argv), time.monotonic(), False])
        elif delta < 0:
            to_stop = min(-delta, len(procs))
            for _ in range(to_stop):
                victim = procs.pop()[0]
                logger.info(
                    "planner: stopping %s worker pid=%s", role, victim.pid
                )
                victim.terminate()
            if to_stop < -delta:
                logger.warning(
                    "planner: want %d fewer %s workers but own only %d — "
                    "externally started workers must be stopped externally",
                    -delta, role, to_stop,
                )

    def stop_all(self) -> None:
        for procs in self._procs.values():
            for entry in procs:
                if entry[0].poll() is None:
                    entry[0].terminate()


def _clamp(v: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, v))


class LoadPlanner:
    """Threshold + hysteresis scaling on KV usage / queue pressure."""

    def __init__(self, config: PlannerConfig):
        self.config = config
        self._calm_ticks = 0
        self._prefill_calm_ticks = 0

    def tick(self, state: FleetState) -> Decision:
        c = self.config
        decode, prefill = state.num_decode, state.num_prefill

        waiting_pw = state.num_waiting / max(1, decode)
        pressure = (
            state.kv_usage >= c.kv_usage_high
            or waiting_pw >= c.waiting_per_worker_high
        )
        calm = state.kv_usage <= c.kv_usage_low and state.num_waiting == 0

        if pressure:
            self._calm_ticks = 0
            decode += c.max_step
        elif calm:
            self._calm_ticks += 1
            if self._calm_ticks >= c.down_stable_ticks:
                decode -= c.max_step
                self._calm_ticks = 0
        else:
            self._calm_ticks = 0

        queue_pw = state.prefill_queue_depth / max(1, state.num_prefill)
        if queue_pw >= c.prefill_queue_per_worker_high:
            self._prefill_calm_ticks = 0
            prefill += c.max_step
        elif state.prefill_queue_depth == 0 and state.num_prefill > c.min_prefill:
            # Same hysteresis as decode: prefill engines also take seconds to
            # come back, and the queue legitimately drains between ticks.
            self._prefill_calm_ticks += 1
            if self._prefill_calm_ticks >= c.down_stable_ticks:
                prefill -= c.max_step
                self._prefill_calm_ticks = 0
        else:
            self._prefill_calm_ticks = 0

        return Decision(
            target_decode=_clamp(decode, c.min_decode, c.max_decode),
            target_prefill=_clamp(prefill, c.min_prefill, c.max_prefill),
        )


@dataclass(frozen=True)
class SlaTargets:
    ttft_ms: float = 200.0
    itl_ms: float = 20.0


class SlaPlanner:
    """Predict next-interval request rate, size the decode fleet so each
    worker's share of the load keeps interpolated TTFT/ITL within targets.

    `ttft_vs_rate` / `itl_vs_rate` are per-worker tables: metric as a
    function of requests/s handled by ONE worker (from the offline profiler,
    benchmarks/profile_sla.py)."""

    def __init__(
        self,
        config: PlannerConfig,
        targets: SlaTargets,
        ttft_vs_rate: PerfInterpolator,
        itl_vs_rate: PerfInterpolator,
        predictor: str = "trend",
        predictor_window: int = 8,
        predictor_season: int = 0,
    ):
        self.config = config
        self.targets = targets
        self.ttft_vs_rate = ttft_vs_rate
        self.itl_vs_rate = itl_vs_rate
        self.predictor = make_predictor(
            predictor, predictor_window, season_length=predictor_season
        )
        #: prefill scaling rides the same queue policy as LoadPlanner
        self._load = LoadPlanner(config)

    def tick(self, state: FleetState) -> Decision:
        c = self.config
        self.predictor.observe(state.request_rate)
        predicted = self.predictor.predict()

        per_worker_cap = min(
            self.ttft_vs_rate.max_load_within(self.targets.ttft_ms),
            self.itl_vs_rate.max_load_within(self.targets.itl_ms),
        )
        if per_worker_cap <= 0:
            # No load level meets the SLA — pin the fleet at max and complain.
            logger.warning(
                "SLA targets unreachable at any load; scaling decode to max"
            )
            needed = c.max_decode
        else:
            needed = -(-predicted // per_worker_cap) if predicted > 0 else c.min_decode
        prefill = self._load.tick(state).target_prefill
        return Decision(
            target_decode=_clamp(int(needed), c.min_decode, c.max_decode),
            target_prefill=prefill,
        )


# -- closed-loop control (ROADMAP item 4: the live-SLO control loop) --------


@dataclass(frozen=True)
class ControlConfig(PlannerConfig):
    """ClosedLoopPlanner knobs on top of the shared pool bounds.

    The loop is a setpoint controller on the LIVE SLO plane (worker SLO
    sketches merged by the fleet telemetry plane) with three
    anti-oscillation mechanisms, each pinned by injected-clock tests:

    - hysteresis band: burn above `burn_high` scales up, but scale-down
      eligibility needs burn below `burn_low` (the dead band between
      them HOLDS — a signal that noisily crosses one threshold cannot
      alternate decisions);
    - calm streak: scale-down additionally needs `down_stable_ticks`
      consecutive calm observations (inherited knob);
    - cooldowns (enforced by ControlRunner's clock): after any scale
      action on a role, further actions on that role wait `cooldown_s`;
      flips wait `flip_cooldown_s` — a flip is a double-sided action.
    """

    #: scale up when the worst-window burn rate crosses this...
    burn_high: float = 1.0
    #: ...and scale-down only becomes eligible below this
    burn_low: float = 0.25
    #: observed p95 pressure thresholds (same semantics as SlaTargets)
    ttft_target_ms: float = 2000.0
    itl_target_ms: float = 200.0
    #: attainment below this blocks scale-down regardless of burn
    attainment_setpoint: float = 0.99
    #: seconds between scale actions on one role (ControlRunner clock)
    cooldown_s: float = 30.0
    #: seconds between role flips fleet-wide
    flip_cooldown_s: float = 60.0
    #: hard per-tick actuation clamp (scale steps + flips combined)
    max_actions_per_tick: int = 2
    #: prefer flipping an idle worker between roles over kill+spawn
    allow_flips: bool = True


@dataclass(frozen=True)
class Actions:
    """One tick's intent: pool targets plus role flips. Flips move an
    EXISTING worker between roles through its drain + re-register path
    (hot KV pages survive; see docs/operations.md "Closed-loop
    autoscaling & role flips"), so one flip is both a -1 and a +1."""

    target_decode: int
    target_prefill: int
    #: (from_role, to_role) pairs, at most one per tick in practice
    flips: tuple = ()
    reason: str = ""

    def delta(self, state: FleetState) -> tuple[int, int]:
        return (
            self.target_decode - state.num_decode,
            self.target_prefill - state.num_prefill,
        )


class ClosedLoopPlanner:
    """Pure setpoint controller over the live SLO plane.

    Pressure attribution mirrors the disaggregated split: ITL p95 /
    burn / decode-queue pressure sizes the DECODE pool, TTFT p95 /
    prefill-queue pressure sizes the PREFILL pool. When one pool is hot
    while the other has slack, the decision is a role FLIP instead of a
    kill+spawn — the flipped worker keeps its KV pages (served/adopted
    over the existing G4 hand-off) so prefix routing stays warm.

    Pure function of (state, internal streak counters): no clocks, no
    I/O — cooldown/clamp timing lives in ControlRunner where a clock can
    be injected."""

    def __init__(self, config: Optional[ControlConfig] = None):
        self.config = config or ControlConfig()
        self._calm_ticks = 0

    # -- signal extraction -------------------------------------------------

    def _decode_pressure(self, state: FleetState) -> Optional[str]:
        c = self.config
        if (
            state.observed_itl_p95_ms is not None
            and state.observed_itl_p95_ms > c.itl_target_ms
        ):
            return f"itl_p95 {state.observed_itl_p95_ms:.0f}ms > {c.itl_target_ms:.0f}ms"
        if state.burn_rate is not None and state.burn_rate > c.burn_high:
            return f"burn {state.burn_rate:.2f} > {c.burn_high}"
        # load fallbacks keep the loop closed before SLO wires arrive
        waiting_pw = state.num_waiting / max(1, state.num_decode)
        if waiting_pw >= c.waiting_per_worker_high:
            return f"waiting/worker {waiting_pw:.1f}"
        if state.kv_usage >= c.kv_usage_high:
            return f"kv_usage {state.kv_usage:.2f}"
        return None

    def _prefill_pressure(self, state: FleetState) -> Optional[str]:
        c = self.config
        queue_pw = state.prefill_queue_depth / max(1, state.num_prefill)
        if queue_pw >= c.prefill_queue_per_worker_high:
            return f"prefill queue/worker {queue_pw:.1f}"
        if (
            state.num_prefill > 0
            and state.observed_ttft_p95_ms is not None
            and state.observed_ttft_p95_ms > c.ttft_target_ms
            and state.prefill_queue_depth > 0
        ):
            return f"ttft_p95 {state.observed_ttft_p95_ms:.0f}ms with queue backlog"
        return None

    def _calm(self, state: FleetState) -> bool:
        c = self.config
        if state.burn_rate is not None and state.burn_rate > c.burn_low:
            return False
        if (
            state.sla_attainment is not None
            and state.sla_attainment < c.attainment_setpoint
        ):
            return False
        return (
            state.kv_usage <= c.kv_usage_low
            and state.num_waiting == 0
            and state.prefill_queue_depth == 0
        )

    # -- the decision ------------------------------------------------------

    def tick(self, state: FleetState) -> Actions:
        c = self.config
        decode, prefill = state.num_decode, state.num_prefill
        flips: list[tuple[str, str]] = []
        reason = "steady"

        d_hot = self._decode_pressure(state)
        p_hot = self._prefill_pressure(state)

        if d_hot:
            self._calm_ticks = 0
            decode += c.max_step
            reason = f"decode hot ({d_hot})"
            # an idle prefill pool is warm capacity: ALSO propose a flip
            # — the runner prefers it when it lands (the flipped roles
            # skip their scale step that tick), and falls back to the
            # spawn path on flip cooldown/failure so a big capacity gap
            # still closes at max_step per tick
            if (
                c.allow_flips
                and prefill > c.min_prefill
                and state.prefill_queue_depth == 0
                and not p_hot
            ):
                flips.append(("prefill", "decode"))
                reason = f"decode hot ({d_hot}); flipping idle prefill"
        if p_hot:
            prefill += c.max_step
            reason = f"prefill hot ({p_hot})"
            # same both-paths shape as decode-hot: the flip is preferred
            # when it lands, but the scale step must exist as the
            # fallback — a fleet with no flippable workers (or inside
            # the flip cooldown) still has to grow the hot pool
            if (
                c.allow_flips
                and not d_hot
                and decode > c.min_decode
                and state.num_waiting == 0
                and state.kv_usage <= c.kv_usage_low
            ):
                flips.append(("decode", "prefill"))
                reason = f"prefill hot ({p_hot}); flipping idle decode"

        if not d_hot and not p_hot:
            if self._calm(state):
                self._calm_ticks += 1
                if self._calm_ticks >= c.down_stable_ticks:
                    self._calm_ticks = 0
                    # shed from the larger-slack pool first
                    if prefill > c.min_prefill and state.prefill_queue_depth == 0:
                        prefill -= c.max_step
                        reason = "calm; prefill down"
                    elif decode > c.min_decode:
                        decode -= c.max_step
                        reason = "calm; decode down"
            else:
                self._calm_ticks = 0

        return Actions(
            target_decode=_clamp(decode, c.min_decode, c.max_decode),
            target_prefill=_clamp(prefill, c.min_prefill, c.max_prefill),
            flips=tuple(flips),
            reason=reason,
        )


class ControlRunner:
    """Clock-aware actuation around a pure planner core.

    Enforces per-role cooldowns, the fleet-wide flip cooldown, and the
    max-actions-per-tick clamp; actuates scales through the Connector
    and flips through an injected async `flipper(from_role, to_role) ->
    bool`; publishes a status frame (`status_fn`) each tick so the
    metrics service can serve `dynamo_tpu_planner_*` and the planner
    section of /v1/fleet (scripts/doctor.py's planner-oscillation and
    sla-unrecovered rules read it). `now_fn` is injectable so the
    anti-oscillation behavior is unit-testable without real time."""

    RECENT = 32

    def __init__(
        self,
        planner,
        connector: Connector,
        observe,
        flipper=None,
        interval_s: Optional[float] = None,
        now_fn=time.monotonic,
        status_fn=None,
        handover=None,
        degraded_fn=None,
        prewarm=None,
    ):
        self.planner = planner
        self.connector = connector
        self.observe = observe
        self.flipper = flipper
        #: () -> bool: True while the control plane is DEGRADED (no
        #: broker answering past the budget — docs/operations.md
        #: "Control-plane HA"). The planner then HOLDs: its signals are
        #: frozen snapshots and its actuation (spawn/flip/handover all
        #: need the fabric) would act on a world it cannot see.
        self.degraded_fn = degraded_fn
        #: async (role) -> bool: retire one worker of `role` via live KV
        #: handover (docs/operations.md "Rolling upgrades & worker
        #: handover"). When set, scale-DOWN steps try it first — the
        #: victim's hot pages migrate to a peer and its in-flight
        #: streams continue there — and fall back to connector.scale
        #: (kill/terminate) when it fails.
        self.handover = handover
        #: async (role) -> bool: one hot-to-cold prefix migration inside
        #: `role` (FleetKvEconomy.prewarm — docs/operations.md "The KV
        #: economy"). After a scale-UP actuation the runner queues one
        #: and fires it on the NEXT tick, when the spawned worker has
        #: had an interval to register: its first requests land warm
        #: instead of cold-prefilling the fleet's hottest prefix.
        self.prewarm = prewarm
        self._prewarm_pending: list[str] = []
        self.interval_s = interval_s or planner.config.interval_s
        self.now_fn = now_fn
        self.status_fn = status_fn
        self.decisions = {
            "scale_up": 0, "scale_down": 0, "flip": 0, "hold": 0,
            "handover": 0, "prewarm": 0,
        }
        self.actions_clamped = 0
        self.cooldown_holds = 0
        self.degraded_holds = 0
        #: consecutive ticks with burn above the band while the decode
        #: target sits at max_decode — the "scaled to the ceiling and
        #: still burning" signal doctor's sla-unrecovered rule fires on
        self.burn_high_ticks = 0
        self.recent: list[dict] = []
        self._last_action: dict[str, float] = {}
        self._last_flip: float = float("-inf")
        self._task: Optional[asyncio.Task] = None

    def _record(self, action: str, role: Optional[str], **extra) -> None:
        self.decisions[action] = self.decisions.get(action, 0) + 1
        rec = {"ts": round(self.now_fn(), 3), "action": action,
               "role": role, **extra}
        self.recent.append(rec)
        del self.recent[: -self.RECENT]
        # fleet event timeline: every actuated decision is an annotation
        # on the dashboards and a joinable moment for slow traces
        # (GET /v1/fleet/events); holds are deliberately not events
        from dynamo_tpu.telemetry import events

        events.record(
            "planner_decision", source="planner", action=action,
            **({"role": role} if role else {}),
            **{k: v for k, v in extra.items() if isinstance(v, (int, str))},
        )

    async def step(self) -> Actions:
        c = self.planner.config
        state = await self.observe()
        if self.degraded_fn is not None and self.degraded_fn():
            # control plane degraded: every signal is a frozen snapshot
            # and every actuator needs the fabric — HOLD until a broker
            # answers instead of scaling blind. Checked BEFORE
            # planner.tick(): feeding the same frozen state through the
            # planner every held tick would advance its hysteresis
            # counters / predictor history on outage data and poison
            # the first post-recovery decision.
            self.decisions["hold"] += 1
            self.degraded_holds += 1
            from dynamo_tpu.telemetry import events

            events.record(
                "degraded", severity="warning", source="planner",
                coalesce_s=60.0, action="planner_hold",
            )
            logger.warning(
                "planner HOLD: control plane degraded (no broker "
                "answering) — signals frozen, actuation suspended"
            )
            return Actions(
                target_decode=state.num_decode,
                target_prefill=state.num_prefill,
                reason="hold: control plane degraded",
            )
        if self.prewarm is not None and self._prewarm_pending:
            # queued by last tick's scale-up: the newcomer has had one
            # interval to register. Prewarm is a warmth optimization,
            # not a capacity change — it doesn't consume action budget.
            pending, self._prewarm_pending = self._prewarm_pending, []
            for prole in pending:
                warmed = False
                try:
                    warmed = bool(await self.prewarm(prole))
                except Exception:
                    logger.exception("planner: %s prewarm failed", prole)
                if warmed:
                    self._record("prewarm", prole)
        acts = self.planner.tick(state)
        now = self.now_fn()
        budget = getattr(c, "max_actions_per_tick", 1)
        flipped_roles: set[str] = set()

        for src, dst in acts.flips:
            if budget <= 0:
                self.actions_clamped += 1
                continue
            if now - self._last_flip < getattr(c, "flip_cooldown_s", 0.0):
                self.cooldown_holds += 1
                continue
            if self.flipper is None:
                break
            ok = False
            try:
                ok = bool(await self.flipper(src, dst))
            except Exception:
                logger.exception("planner: flip %s->%s failed", src, dst)
            if ok:
                budget -= 1
                self._last_flip = now
                # a flip IS a scale action on both roles — start their
                # cooldowns so a scale step can't pile on the same tick
                self._last_action[src] = now
                self._last_action[dst] = now
                flipped_roles.update((src, dst))
                self._record("flip", None, src=src, dst=dst)
                logger.info("planner: flipped a %s worker to %s", src, dst)

        acted = bool(flipped_roles)
        for role, target, observed in (
            ("decode", acts.target_decode, state.num_decode),
            ("prefill", acts.target_prefill, state.num_prefill),
        ):
            delta = target - observed
            if delta == 0 or role in flipped_roles:
                continue
            cooldown = getattr(c, "cooldown_s", 0.0)
            if now - self._last_action.get(role, float("-inf")) < cooldown:
                self.cooldown_holds += 1
                continue
            if budget <= 0:
                self.actions_clamped += 1
                continue
            step = max(-c.max_step, min(c.max_step, delta))
            step_target = observed + step
            logger.info(
                "planner: %s %d -> %d (%s)", role, observed, step_target,
                acts.reason,
            )
            handed = 0
            if step < 0 and self.handover is not None:
                # scale-down prefers handover over kill: each retired
                # worker ships its hot KV to a peer and exits 0 — same
                # capacity change, none of the recompute. Partial
                # success (k of |step|) shrinks the kill fallback.
                for _ in range(-step):
                    ok = False
                    try:
                        ok = bool(await self.handover(role))
                    except Exception:
                        logger.exception(
                            "planner: %s handover failed", role
                        )
                    if not ok:
                        break
                    handed += 1
                if handed:
                    self._record(
                        "handover", role,
                        **{"from": observed, "to": observed - handed},
                    )
                    logger.info(
                        "planner: retired %d %s worker(s) by handover",
                        handed, role,
                    )
            if handed < abs(step):
                # the handed-over workers are ALREADY exiting; only the
                # remainder (or a scale-up) goes through the connector
                await self.connector.scale(
                    role, step_target + handed, observed,
                )
                self._record(
                    "scale_up" if step > 0 else "scale_down", role,
                    **{"from": observed, "to": step_target},
                )
                if step > 0 and self.prewarm is not None:
                    self._prewarm_pending.append(role)
            budget -= 1
            acted = True
            self._last_action[role] = now
        if not acted:
            self.decisions["hold"] += 1

        burn = state.burn_rate
        at_max = acts.target_decode >= c.max_decode
        if (
            burn is not None
            and burn > getattr(c, "burn_high", 1.0)
            and at_max
        ):
            self.burn_high_ticks += 1
        else:
            self.burn_high_ticks = 0

        if self.status_fn is not None:
            try:
                await self.status_fn(self.status(state, acts))
            except Exception:
                logger.warning("planner status publish failed", exc_info=True)
        return acts

    def status(self, state: FleetState, acts: Actions) -> dict:
        c = self.planner.config
        return {
            "mode": type(self.planner).__name__,
            "targets": {"decode": acts.target_decode,
                        "prefill": acts.target_prefill},
            "observed": {"decode": state.num_decode,
                         "prefill": state.num_prefill},
            "limits": {"min_decode": c.min_decode, "max_decode": c.max_decode,
                       "min_prefill": c.min_prefill,
                       "max_prefill": c.max_prefill},
            "setpoint": {
                "attainment": getattr(c, "attainment_setpoint", None),
                "burn_high": getattr(c, "burn_high", None),
                "burn_low": getattr(c, "burn_low", None),
                "ttft_ms": getattr(c, "ttft_target_ms", None),
                "itl_ms": getattr(c, "itl_target_ms", None),
                "cooldown_s": getattr(c, "cooldown_s", None),
                "flip_cooldown_s": getattr(c, "flip_cooldown_s", None),
            },
            "signals": {
                "burn_rate": state.burn_rate,
                "sla_attainment": state.sla_attainment,
                "observed_ttft_p95_ms": state.observed_ttft_p95_ms,
                "observed_itl_p95_ms": state.observed_itl_p95_ms,
                "kv_usage": round(state.kv_usage, 4),
                "num_waiting": state.num_waiting,
                "prefill_queue_depth": state.prefill_queue_depth,
                "request_rate": round(state.request_rate, 3),
            },
            "reason": acts.reason,
            "decisions_total": dict(self.decisions),
            "flips_total": self.decisions.get("flip", 0),
            "actions_clamped_total": self.actions_clamped,
            "cooldown_holds_total": self.cooldown_holds,
            "degraded_holds_total": self.degraded_holds,
            "burn_high_ticks": self.burn_high_ticks,
            "at_max": acts.target_decode >= c.max_decode,
            "recent_decisions": list(self.recent),
        }

    async def run(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("planner tick failed")
            await asyncio.sleep(self.interval_s)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


class PlannerRunner:
    """Samples FleetState on an interval and actuates decisions.

    `observe()` is injected (async () -> FleetState) so the runner is
    agnostic to where state comes from — MetricsAggregator + PrefillQueue in
    production, a stub in tests."""

    def __init__(
        self,
        planner,
        connector: Connector,
        observe,
        interval_s: Optional[float] = None,
    ):
        self.planner = planner
        self.connector = connector
        self.observe = observe
        self.interval_s = interval_s or planner.config.interval_s
        self._task: Optional[asyncio.Task] = None

    async def step(self) -> Decision:
        state = await self.observe()
        decision = self.planner.tick(state)
        d_decode, d_prefill = decision.delta(state)
        if d_decode:
            logger.info(
                "planner: decode %d -> %d", state.num_decode, decision.target_decode
            )
            await self.connector.scale(
                "decode", decision.target_decode, state.num_decode
            )
        if d_prefill:
            logger.info(
                "planner: prefill %d -> %d", state.num_prefill, decision.target_prefill
            )
            await self.connector.scale(
                "prefill", decision.target_prefill, state.num_prefill
            )
        return decision

    async def run(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("planner tick failed")
            await asyncio.sleep(self.interval_s)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

"""Kubernetes planner connector: actuate scaling by editing the
DynamoGraphDeployment CR that owns the fleet.

Reference parity: the planner's KubernetesConnector patches CRD replica
counts and lets the operator reconcile them into Deployments
(/root/reference components/planner kube.py; our operator is
dynamo_tpu/operator). The division of labor is identical: the planner
decides targets, the CR records desired state, the operator converges the
cluster — so a planner crash never leaves half-applied Deployments, and
`kubectl get dgd` always shows the current desired fleet.

The connector is kube-client-agnostic (InMemoryKube in tests,
InClusterKube in a pod)."""

from __future__ import annotations

import logging
from typing import Any, Mapping

logger = logging.getLogger(__name__)


class KubeConnector:
    def __init__(
        self,
        kube: Any,
        cr_name: str,
        namespace: str = "default",
        role_services: Mapping[str, str] | None = None,
    ):
        """role_services maps planner roles ("decode", "prefill") to the
        CR's service names (e.g. {"decode": "Worker",
        "prefill": "PrefillWorkerService"})."""
        self.kube = kube
        self.cr_name = cr_name
        self.namespace = namespace
        self.role_services = dict(role_services or {})

    async def scale(self, role: str, target: int, observed: int) -> None:
        import asyncio

        service = self.role_services.get(role, role)
        # Preferred path: the component CR's /scale subresource — one
        # conflict-free PATCH that only the scale plane writes, exactly
        # the reference's DynamoComponentDeployment scale mechanism
        # (dynamocomponentdeployment_types.go). No read-modify-write, no
        # 409 retry loop, and the graph CR is never rewritten. Kube calls
        # are blocking HTTP — keep them off the planner's event loop.
        from dynamo_tpu.operator.reconciler import component_name

        dcd_name = component_name(self.cr_name, service)
        dcd = await asyncio.to_thread(
            self.kube.get, "DynamoComponentDeployment", self.namespace,
            dcd_name,
        )
        if dcd is not None:
            if dcd.get("spec", {}).get("replicas") == target:
                return  # idempotent: no API churn on a no-op tick
            result = await asyncio.to_thread(
                self.kube.patch_scale, "DynamoComponentDeployment",
                self.namespace, dcd_name, target,
            )
            if result is not None:
                logger.info(
                    "planner: %s (%s) scaled to %d via /scale (observed %d)",
                    role, dcd_name, target, observed,
                )
                return
            # the DCD vanished between get and patch: fall through
        # Legacy fallback (pre-component operators): rewrite the graph
        # CR's replicas with a 409 retry loop — the operator's status
        # patches bump resourceVersion between our get and replace.
        for attempt in range(4):
            cr = await asyncio.to_thread(
                self.kube.get, "DynamoGraphDeployment", self.namespace,
                self.cr_name,
            )
            if cr is None:
                logger.warning(
                    "planner: CR %s/%s not found; cannot scale %s",
                    self.namespace, self.cr_name, role,
                )
                return
            for svc in cr.get("spec", {}).get("services", []):
                if svc.get("name") == service:
                    break
            else:
                logger.warning(
                    "planner: CR %s has no service %r for role %r",
                    self.cr_name, service, role,
                )
                return
            current = svc.get("replicas", 1)
            if current == target:
                return
            svc["replicas"] = target
            try:
                result = await asyncio.to_thread(
                    self.kube.replace, "DynamoGraphDeployment",
                    self.namespace, self.cr_name, cr,
                )
            except Exception as e:  # HTTPError 409 = lost the write race
                if getattr(e, "code", None) == 409 and attempt < 3:
                    continue
                raise
            if result is None:  # 404: the CR vanished mid-write
                logger.warning(
                    "planner: CR %s/%s disappeared during scale of %s",
                    self.namespace, self.cr_name, role,
                )
                return
            logger.info(
                "planner: %s (%s) replicas %d -> %d (observed %d)",
                role, service, current, target, observed,
            )
            return

"""Load predictors: observe a scalar series, predict the next interval.

The reference ships constant / ARIMA / Prophet predictors
(components/planner/utils/load_predictor.py:62-132). Heavy statistical
deps aren't available here (and are overkill at serving timescales), so the
trend predictor is a windowed least-squares slope — the piece of ARIMA that
actually matters for scale-ahead decisions.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional


class ConstantPredictor:
    """Next value == last observed (the reference's default)."""

    def __init__(self):
        self._last: float = 0.0

    def observe(self, value: float) -> None:
        self._last = float(value)

    def predict(self) -> float:
        return self._last


class MovingAveragePredictor:
    def __init__(self, window: int = 6):
        if window < 1:
            raise ValueError("window must be >= 1")
        self._buf: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._buf.append(float(value))

    def predict(self) -> float:
        return sum(self._buf) / len(self._buf) if self._buf else 0.0


class TrendPredictor:
    """Least-squares extrapolation one step ahead over a recent window.

    Scale-ahead: a rising ramp is forecast above its last sample, so capacity
    arrives before the load does. Never predicts below zero.
    """

    def __init__(self, window: int = 8):
        if window < 2:
            raise ValueError("window must be >= 2")
        self._buf: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._buf.append(float(value))

    def predict(self) -> float:
        n = len(self._buf)
        if n == 0:
            return 0.0
        if n == 1:
            return self._buf[0]
        xs = range(n)
        mean_x = (n - 1) / 2
        mean_y = sum(self._buf) / n
        cov = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, self._buf))
        var = sum((x - mean_x) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))  # x = n is "next"


def make_predictor(kind: str, window: int = 8):
    if kind == "constant":
        return ConstantPredictor()
    if kind == "moving_average":
        return MovingAveragePredictor(window)
    if kind == "trend":
        return TrendPredictor(window)
    raise ValueError(f"unknown predictor {kind!r}")

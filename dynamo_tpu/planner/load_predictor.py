"""Load predictors: observe a scalar series, predict the next interval.

The reference ships constant / ARIMA / Prophet predictors
(components/planner/utils/load_predictor.py:62-132). The statistical
packages themselves aren't available here, so the ARIMA-family models are
implemented directly, dependency-free:

- TrendPredictor     — windowed least-squares slope (cheap default)
- ArPredictor        — AR(p) on the (optionally first-differenced)
                       series, fit by numpy least squares: the
                       ARIMA(p,d,0) family the reference auto-fits
- HoltWintersPredictor — additive level/trend/seasonal exponential
                       smoothing: the Prophet role (trend + seasonality)
                       at serving timescales
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional


class ConstantPredictor:
    """Next value == last observed (the reference's default)."""

    def __init__(self):
        self._last: float = 0.0

    def observe(self, value: float) -> None:
        self._last = float(value)

    def predict(self) -> float:
        return self._last


class MovingAveragePredictor:
    def __init__(self, window: int = 6):
        if window < 1:
            raise ValueError("window must be >= 1")
        self._buf: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._buf.append(float(value))

    def predict(self) -> float:
        return sum(self._buf) / len(self._buf) if self._buf else 0.0


class TrendPredictor:
    """Least-squares extrapolation one step ahead over a recent window.

    Scale-ahead: a rising ramp is forecast above its last sample, so capacity
    arrives before the load does. Never predicts below zero.
    """

    def __init__(self, window: int = 8):
        if window < 2:
            raise ValueError("window must be >= 2")
        self._buf: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._buf.append(float(value))

    def predict(self) -> float:
        n = len(self._buf)
        if n == 0:
            return 0.0
        if n == 1:
            return self._buf[0]
        xs = range(n)
        mean_x = (n - 1) / 2
        mean_y = sum(self._buf) / n
        cov = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, self._buf))
        var = sum((x - mean_x) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))  # x = n is "next"


class ArPredictor:
    """ARIMA(p, d, 0) one-step forecast, coefficients re-fit by ordinary
    least squares over a sliding window each predict() call.

    d=1 (default) models the DIFFERENCED series — the standard treatment
    for non-stationary load curves (ramps): the AR part then captures
    momentum/oscillation in the increments and the forecast is
    last + predicted_increment. Falls back to trend-free behavior until
    enough samples accumulate. Mirrors the reference's auto-fit ARIMA
    (load_predictor.py:62-132) without the statsmodels dependency.
    """

    def __init__(self, window: int = 32, p: int = 3, d: int = 1):
        if p < 1:
            raise ValueError("p must be >= 1")
        if d not in (0, 1):
            raise ValueError("d must be 0 or 1")
        if window < p + d + 2:
            raise ValueError("window too small for the requested order")
        self.p, self.d = p, d
        self._buf: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._buf.append(float(value))

    def _series(self) -> List[float]:
        ys = list(self._buf)
        if self.d == 1:
            ys = [b - a for a, b in zip(ys, ys[1:])]
        return ys

    def predict(self) -> float:
        if not self._buf:
            return 0.0
        last = self._buf[-1]
        ys = self._series()
        # need at least p+1 rows for a meaningful fit
        if len(ys) < self.p + 2:
            return max(0.0, last)
        import numpy as np

        y = np.asarray(ys[self.p:], dtype=np.float64)
        rows = [
            [ys[t - j] for j in range(1, self.p + 1)] + [1.0]
            for t in range(self.p, len(ys))
        ]
        x = np.asarray(rows, dtype=np.float64)
        coef, *_ = np.linalg.lstsq(x, y, rcond=None)
        nxt = float(
            sum(c * v for c, v in zip(coef[:-1], ys[::-1])) + coef[-1]
        )
        return max(0.0, last + nxt if self.d == 1 else nxt)


class HoltWintersPredictor:
    """Additive Holt-Winters (level + trend + optional seasonality),
    the role Prophet plays in the reference: periodic load patterns
    (diurnal cycles at ops timescales, batch cadence at bench timescales)
    forecast one interval ahead.

    season_length=0 degrades to double exponential smoothing (Holt).
    Seasonal components initialize from the first full season.
    """

    def __init__(
        self,
        alpha: float = 0.4,
        beta: float = 0.1,
        gamma: float = 0.3,
        season_length: int = 0,
    ):
        for name, v in (("alpha", alpha), ("beta", beta), ("gamma", gamma)):
            if not 0.0 < v <= 1.0:
                raise ValueError(f"{name} must be in (0, 1]")
        if season_length < 0:
            raise ValueError("season_length must be >= 0")
        self.alpha, self.beta, self.gamma = alpha, beta, gamma
        self.m = season_length
        self._level: Optional[float] = None
        self._trend = 0.0
        self._season: List[float] = []
        self._warmup: List[float] = []
        self._t = 0

    def observe(self, value: float) -> None:
        y = float(value)
        if self.m and len(self._season) < self.m:
            # collect one full season, then de-mean it into indices
            self._warmup.append(y)
            if len(self._warmup) == self.m:
                mean = sum(self._warmup) / self.m
                self._season = [v - mean for v in self._warmup]
                self._level = mean
            self._t += 1
            return
        if self._level is None:
            self._level = y
            self._t += 1
            return
        if self.m:
            idx = self._t % self.m
            s = self._season[idx]
            prev_level = self._level
            self._level = self.alpha * (y - s) + (1 - self.alpha) * (
                self._level + self._trend
            )
            self._trend = self.beta * (self._level - prev_level) + (
                1 - self.beta
            ) * self._trend
            self._season[idx] = self.gamma * (y - self._level) + (
                1 - self.gamma
            ) * s
        else:
            prev_level = self._level
            self._level = self.alpha * y + (1 - self.alpha) * (
                self._level + self._trend
            )
            self._trend = self.beta * (self._level - prev_level) + (
                1 - self.beta
            ) * self._trend
        self._t += 1

    def predict(self) -> float:
        if self._level is None:
            return self._warmup[-1] if self._warmup else 0.0
        y = self._level + self._trend
        if self.m and self._season:
            y += self._season[self._t % self.m]
        return max(0.0, y)


def make_predictor(kind: str, window: int = 8, season_length: int = 0):
    if kind == "constant":
        return ConstantPredictor()
    if kind == "moving_average":
        return MovingAveragePredictor(window)
    if kind == "trend":
        return TrendPredictor(window)
    if kind == "arima":
        return ArPredictor(window=max(window, 8))
    if kind == "holt_winters":
        return HoltWintersPredictor(season_length=season_length)
    raise ValueError(f"unknown predictor {kind!r}")

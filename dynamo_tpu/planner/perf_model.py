"""Offline perf interpolation tables for SLA planning.

The reference profiles each parallel config offline and interpolates
TTFT/ITL against load (benchmarks/profiler/profile_sla.py + utils/
perf_interpolation.py:20-116). Same idea: feed (load, metric) samples from
the benchmark harness (benchmarks/profile_sla.py here), then ask either
"metric at load" or "max load that keeps metric under target".
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Sequence


class PerfInterpolator:
    """Piecewise-linear y(x) over sorted sample points, clamped at the ends
    (monotone x; y need not be monotone, but SLA metrics in practice are)."""

    def __init__(self, xs: Sequence[float], ys: Sequence[float]):
        if len(xs) != len(ys) or len(xs) == 0:
            raise ValueError("need equal, non-empty xs/ys")
        pairs = sorted(zip(map(float, xs), map(float, ys)))
        self.xs = [p[0] for p in pairs]
        self.ys = [p[1] for p in pairs]

    def at(self, x: float) -> float:
        xs, ys = self.xs, self.ys
        if x <= xs[0]:
            return ys[0]
        if x >= xs[-1]:
            return ys[-1]
        i = bisect_left(xs, x)
        x0, x1, y0, y1 = xs[i - 1], xs[i], ys[i - 1], ys[i]
        t = (x - x0) / (x1 - x0)
        return y0 + t * (y1 - y0)

    def max_load_within(self, target_y: float) -> float:
        """Largest x with y(x) <= target (y non-decreasing in x assumed).
        Returns 0.0 if even the lightest load misses the target."""
        if self.ys[0] > target_y:
            return 0.0
        best = self.xs[0]
        # walk segments; within a segment solve the linear crossing
        for (x0, y0), (x1, y1) in zip(
            zip(self.xs, self.ys), zip(self.xs[1:], self.ys[1:])
        ):
            if y1 <= target_y:
                best = x1
                continue
            if y0 <= target_y < y1:
                t = (target_y - y0) / (y1 - y0)
                best = x0 + t * (x1 - x0)
            break
        return best


def sla_feasible_rate(table: dict, ttft_ms: float, itl_ms: float) -> float:
    """Highest profiled req/s at which BOTH metrics stay within target
    (0.0 when no profiled load qualifies). `table` carries
    ttft_vs_rate/itl_vs_rate rows as [[req_s, ms], ...]."""
    rates = []
    for rows, target in (
        (table["ttft_vs_rate"], ttft_ms),
        (table["itl_vs_rate"], itl_ms),
    ):
        if not rows:
            return 0.0
        rates.append(PerfInterpolator(*zip(*rows)).max_load_within(target))
    return max(0.0, min(rates))


def select_parallel_config(
    configs: Sequence[dict], ttft_ms: float, itl_ms: float
) -> dict:
    """The ONE selection policy for (tp, dp) perf-table configs, shared by
    the offline profiler sweep and the planner's load-time re-selection:
    score each config by SLA-feasible rate PER CHIP, prefer feasible ones,
    fall back to the best-scoring config when nothing meets the targets
    (reference: profiler picks the config meeting TTFT/ITL,
    profile_sla.py:81-84)."""
    scored = [
        (sla_feasible_rate(c, ttft_ms, itl_ms) / (c["tp"] * c["dp"]), c)
        for c in configs
    ]
    feasible = [s for s in scored if s[0] > 0]
    return max(feasible or scored, key=lambda s: s[0])[1]

from dynamo_tpu.frontend.service import ModelManager, ModelPipeline, ModelWatcher
from dynamo_tpu.frontend.http import HttpService

__all__ = ["ModelManager", "ModelPipeline", "ModelWatcher", "HttpService"]

"""OpenAI-compatible HTTP frontend (aiohttp).

Routes (reference: http/service/openai.rs:765-834, service_v2.rs):
  POST /v1/chat/completions   (stream + non-stream)
  POST /v1/completions
  GET  /v1/models
  GET  /health, /live, /ready
  GET  /metrics               (Prometheus text)
  POST /clear_kv_blocks       (admin; forwards to workers' flush endpoint)

SSE streaming with a disconnect monitor: a closed client connection
cancels the request context all the way into the engine (openai.rs:678).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from typing import Optional

from aiohttp import web

from dynamo_tpu.frontend.metrics import FrontendMetrics
from dynamo_tpu.frontend.service import ModelManager
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    CompletionChoice,
    CompletionResponse,
    EmbeddingRequest,
    ModelInfo,
    ModelList,
    SSE_DONE,
    aggregate_chat_stream,
    now,
    sse_event,
)
from dynamo_tpu.runtime.context import Context

logger = logging.getLogger(__name__)


class HttpService:
    def __init__(
        self,
        manager: ModelManager,
        host: str = "0.0.0.0",
        port: int = 8080,
        metrics: Optional[FrontendMetrics] = None,
    ):
        self.manager = manager
        self.host = host
        self.port = port
        self.metrics = metrics or FrontendMetrics()
        self.app = web.Application()
        self.app.add_routes(
            [
                web.post("/v1/chat/completions", self.chat_completions),
                web.post("/v1/completions", self.completions),
                web.post("/v1/embeddings", self.embeddings),
                web.get("/v1/models", self.models),
                web.get("/health", self.health),
                web.get("/live", self.health),
                web.get("/ready", self.health),
                web.get("/metrics", self.metrics_handler),
                web.post("/clear_kv_blocks", self.clear_kv_blocks),
            ]
        )
        self._runner: Optional[web.AppRunner] = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:  # resolve ephemeral port
            self.port = s.getsockname()[1]
            break
        logger.info("http frontend on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # -- handlers ----------------------------------------------------------

    async def health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "models": self.manager.list_models()}
        )

    async def models(self, request: web.Request) -> web.Response:
        listing = ModelList(
            data=[ModelInfo(id=m, created=now()) for m in self.manager.list_models()]
        )
        return web.json_response(listing.model_dump())

    async def metrics_handler(self, request: web.Request) -> web.Response:
        return web.Response(
            text=self.metrics.expose(), content_type="text/plain"
        )

    async def clear_kv_blocks(self, request: web.Request) -> web.Response:
        # Engine workers expose cache flush via their admin endpoint; the
        # frontend acknowledges and the flush fans out through the fabric.
        return web.json_response({"status": "accepted"})

    async def embeddings(self, request: web.Request) -> web.Response:
        t0 = time.time()
        try:
            body = await request.json()
            req = EmbeddingRequest.model_validate(body)
        except Exception as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            self.metrics.request_done(
                req.model, "embedding", "404", time.time() - t0
            )
            return web.json_response(
                {"error": f"model {req.model!r} not found"}, status=404
            )
        with self.metrics.inflight_guard(req.model):
            try:
                resp = await pipeline.embed(req)
            except ValueError as e:
                self.metrics.request_done(
                    req.model, "embedding", "400", time.time() - t0
                )
                return web.json_response({"error": str(e)}, status=400)
            except Exception as e:
                logger.exception("embedding request failed")
                self.metrics.request_done(
                    req.model, "embedding", "500", time.time() - t0
                )
                return web.json_response({"error": str(e)}, status=500)
        self.metrics.request_done(
            req.model, "embedding", "200", time.time() - t0,
            input_tokens=resp.usage.prompt_tokens,
        )
        return web.json_response(resp.model_dump())

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, kind="chat")

    async def completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, kind="completion")

    async def _serve(self, request: web.Request, kind: str) -> web.StreamResponse:
        t0 = time.time()
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        try:
            if kind == "chat":
                req = ChatCompletionRequest.model_validate(body)
            else:
                req = CompletionRequest.model_validate(body)
        except Exception as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            self.metrics.request_done(req.model, kind, "404", time.time() - t0)
            return web.json_response(
                {"error": f"model {req.model!r} not found"}, status=404
            )

        ctx = Context()
        stream_fn = (
            pipeline.chat_stream if kind == "chat" else pipeline.completion_stream
        )
        with self.metrics.inflight_guard(req.model):
            try:
                if req.stream:
                    return await self._stream(
                        request, req, stream_fn(req, ctx), ctx, kind, t0
                    )
                return await self._unary(req, stream_fn(req, ctx), kind, t0)
            except ValueError as e:
                self.metrics.request_done(req.model, kind, "400", time.time() - t0)
                return web.json_response({"error": str(e)}, status=400)
            except Exception as e:
                logger.exception("request failed")
                ctx.cancel()
                self.metrics.request_done(req.model, kind, "500", time.time() - t0)
                return web.json_response({"error": str(e)}, status=500)

    async def _unary(self, req, chunk_stream, kind: str, t0: float) -> web.Response:
        chunks = [c async for c in chunk_stream]
        rid = chunks[0].id if chunks else "unknown"
        resp = aggregate_chat_stream(chunks, req.model, rid)
        usage = resp.usage
        self.metrics.request_done(
            req.model, kind, "200", time.time() - t0,
            input_tokens=usage.prompt_tokens if usage else 0,
            output_tokens=usage.completion_tokens if usage else 0,
        )
        if kind == "completion":
            comp = CompletionResponse(
                id=resp.id, created=resp.created, model=req.model,
                choices=[
                    CompletionChoice(
                        text=resp.choices[0].message.content or "",
                        finish_reason=resp.choices[0].finish_reason,
                    )
                ],
                usage=usage,
            )
            return web.json_response(comp.model_dump(exclude_none=True))
        return web.json_response(resp.model_dump(exclude_none=True))

    async def _stream(
        self, http_request: web.Request, req, chunk_stream, ctx: Context,
        kind: str, t0: float,
    ) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        await resp.prepare(http_request)
        ttft = None
        last_t = None
        itl: list[float] = []
        ntokens = 0
        status = "200"
        try:
            async for chunk in chunk_stream:
                t = time.time()
                if any(c.delta.content for c in chunk.choices):
                    ntokens += 1
                    if ttft is None:
                        ttft = t - t0
                    elif last_t is not None:
                        itl.append(t - last_t)
                    last_t = t
                await resp.write(sse_event(chunk))
            await resp.write(SSE_DONE)
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: cancel into the engine (disconnect monitor)
            ctx.cancel()
            status = "499"
        finally:
            self.metrics.request_done(
                req.model, kind, status, time.time() - t0,
                output_tokens=ntokens, ttft_s=ttft, itl_s=itl,
            )
        with contextlib.suppress(Exception):
            await resp.write_eof()
        return resp

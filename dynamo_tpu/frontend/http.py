"""OpenAI-compatible HTTP frontend (aiohttp).

Routes (reference: http/service/openai.rs:765-834, service_v2.rs):
  POST /v1/chat/completions   (stream + non-stream)
  POST /v1/completions
  GET  /v1/models
  GET  /health, /live, /ready
  GET  /metrics               (Prometheus text)
  POST /clear_kv_blocks       (admin; forwards to workers' flush endpoint)

SSE streaming with a disconnect monitor: a closed client connection
cancels the request context all the way into the engine (openai.rs:678).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import time
from typing import Optional

from aiohttp import web

from dynamo_tpu.frontend.metrics import FrontendMetrics
from dynamo_tpu.frontend.service import ModelManager
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    CompletionChoice,
    CompletionResponse,
    EmbeddingRequest,
    ModelInfo,
    ModelList,
    ResponseOutputMessage,
    ResponseOutputText,
    ResponsesRequest,
    ResponsesResponse,
    ResponsesUsage,
    SSE_DONE,
    aggregate_chat_stream,
    new_request_id,
    now,
    sse_event,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.overload import OverloadedError
from dynamo_tpu import telemetry

logger = logging.getLogger(__name__)


def _legacy_completion_chunk(chunk, text_offsets: dict[int, int]) -> dict:
    """/v1/completions streams `text_completion` objects, not chat chunks:
    choices carry `text` and the legacy parallel-array logprobs shape.
    text_offsets accumulates emitted text length per choice so logprob
    offsets stay absolute across the stream."""
    choices = []
    for c in chunk.choices:
        text = c.delta.content or ""
        choice: dict = {
            "index": c.index,
            "text": text,
            "finish_reason": c.finish_reason,
        }
        if c.logprobs is not None:
            entries = c.logprobs.content
            offsets = []
            pos = text_offsets.get(c.index, 0)
            for e in entries:
                offsets.append(pos)
                pos += len(e.token)
            choice["logprobs"] = {
                "tokens": [e.token for e in entries],
                "token_logprobs": [e.logprob for e in entries],
                "top_logprobs": [
                    {t.token: t.logprob for t in e.top_logprobs}
                    for e in entries
                ],
                "text_offset": offsets,
            }
        text_offsets[c.index] = text_offsets.get(c.index, 0) + len(text)
        choices.append(choice)
    out = {
        "id": chunk.id,
        "object": "text_completion",
        "created": chunk.created,
        "model": chunk.model,
        "choices": choices,
    }
    if chunk.usage is not None:
        out["usage"] = chunk.usage.model_dump()
    return out


class HttpService:
    def __init__(
        self,
        manager: ModelManager,
        host: str = "0.0.0.0",
        port: int = 8080,
        metrics: Optional[FrontendMetrics] = None,
        max_inflight: Optional[int] = None,
        shed_burn_threshold: Optional[float] = None,
        request_timeout_s: Optional[float] = None,
    ):
        from dynamo_tpu.frontend.admission import AdmissionController

        self.manager = manager
        self.host = host
        self.port = port
        self.metrics = metrics or FrontendMetrics()
        #: overload plane (docs/operations.md "Overload & draining"):
        #: inflight cap + SLO-burn shedder, both default-off
        self.admission = AdmissionController(
            self.metrics,
            max_inflight=max_inflight,
            burn_threshold=shed_burn_threshold,
        )
        #: server-default end-to-end deadline (seconds; None = none) —
        #: per-request `x-request-timeout` overrides it
        self.request_timeout_s = request_timeout_s
        self.app = web.Application()
        self.app.add_routes(
            [
                web.post("/v1/chat/completions", self.chat_completions),
                web.post("/v1/completions", self.completions),
                web.post("/v1/embeddings", self.embeddings),
                web.post("/v1/responses", self.responses),
                web.get("/v1/models", self.models),
                web.get("/health", self.health),
                web.get("/live", self.health),
                web.get("/ready", self.health),
                web.get("/metrics", self.metrics_handler),
                web.get("/v1/traces", self.traces_list),
                web.get("/v1/traces/{trace_id}", self.trace_get),
                web.get("/v1/debug/flight", self.debug_flight),
                web.get("/v1/debug/programs", self.debug_programs),
                web.get("/v1/debug/memory", self.debug_memory),
                web.get("/v1/debug/mesh", self.debug_mesh),
                web.get("/v1/debug/stalls", self.debug_stalls),
                web.post("/v1/debug/profile", self.debug_profile),
                web.post("/v1/admin/drain", self.admin_drain),
                web.post("/v1/admin/handover", self.admin_handover),
                web.post("/clear_kv_blocks", self.clear_kv_blocks),
            ]
        )
        self._runner: Optional[web.AppRunner] = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:  # resolve ephemeral port
            self.port = s.getsockname()[1]
            break
        logger.info("http frontend on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # -- handlers ----------------------------------------------------------

    async def health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "models": self.manager.list_models()}
        )

    async def models(self, request: web.Request) -> web.Response:
        listing = ModelList(
            data=[ModelInfo(id=m, created=now()) for m in self.manager.list_models()]
        )
        return web.json_response(listing.model_dump())

    async def metrics_handler(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry import openmetrics

        # content negotiation: Prometheus asks for OpenMetrics by
        # default and gets exemplars; classic scrapers keep the 0.0.4
        # text they can parse (exemplar syntax would fail their scrape)
        if openmetrics.negotiate(request.headers.get("Accept")):
            return web.Response(
                text=self.metrics.expose(openmetrics=True),
                content_type=openmetrics.CONTENT_TYPE,
            )
        return web.Response(
            text=self.metrics.expose(), content_type="text/plain"
        )

    # -- request tracing (docs/observability.md) ---------------------------

    async def traces_list(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.http_api import traces_payload

        body, status = traces_payload(request.query.get("limit"))
        return web.json_response(body, status=status)

    async def trace_get(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.http_api import trace_payload

        body, status = trace_payload(
            request.match_info["trace_id"], request.query.get("format")
        )
        return web.json_response(body, status=status)

    # -- debug plane (docs/observability.md "Debugging a slow or stuck
    # worker"): the flight ring / program cost model / stall diagnoses /
    # jax.profiler trigger of any engine living IN THIS PROCESS (the
    # single-process `in=http out=jax` topology). Remote workers'
    # windows are served by the metrics service from their frames. -----

    async def debug_flight(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.debug import flight_payload

        body, status = flight_payload(request.query.get("n"))
        return web.json_response(body, status=status)

    async def debug_programs(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.debug import programs_payload

        body, status = programs_payload()
        return web.json_response(body, status=status)

    async def debug_memory(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.debug import memory_payload

        body, status = memory_payload()
        return web.json_response(body, status=status)

    async def debug_mesh(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.debug import mesh_payload

        body, status = mesh_payload()
        return web.json_response(body, status=status)

    async def debug_stalls(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.debug import stalls_payload

        body, status = stalls_payload()
        return web.json_response(body, status=status)

    async def debug_profile(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.debug import profile_payload

        try:
            body = await request.json()
        except Exception:
            body = {}
        payload, status = profile_payload(body)
        return web.json_response(payload, status=status)

    # -- overload & draining (docs/operations.md) --------------------------

    def _deadline_from(self, request: web.Request) -> Optional[float]:
        """Absolute epoch deadline from `x-request-timeout` (seconds) or
        the server default; None when neither is set. A malformed header
        is ignored (logged), never a 400 — degrading to 'no deadline' is
        safer than rejecting live traffic on a client typo."""
        timeout = self.request_timeout_s
        raw = request.headers.get("x-request-timeout")
        if raw is not None:
            try:
                parsed = float(raw)
            except (TypeError, ValueError):
                logger.warning("ignoring malformed x-request-timeout %r", raw)
            else:
                if parsed > 0:
                    timeout = parsed
                else:
                    # 0/negative reads as "no timeout", not "1ms" — a
                    # guaranteed 504 would reject live traffic silently
                    logger.warning(
                        "ignoring non-positive x-request-timeout %r", raw
                    )
                    timeout = None
        return time.time() + timeout if timeout else None

    @staticmethod
    def _reject_429(message: str, retry_after_s: Optional[float]) -> web.Response:
        headers = {}
        if retry_after_s is not None:
            import math

            headers["Retry-After"] = str(max(1, math.ceil(retry_after_s)))
        return web.json_response(
            {"error": message}, status=429, headers=headers
        )

    def _check_admission(
        self, request: web.Request, model: str, kind: str, t0: float
    ) -> Optional[web.Response]:
        """Frontend admission gates; a Response = reject with 429."""
        if not self.admission.enabled:
            return None
        decision = self.admission.check(
            kind, self.admission.priority_from(request.headers)
        )
        if decision is None:
            return None
        self.metrics.request_done(model, kind, "429", time.time() - t0)
        return self._reject_429(decision.message, decision.retry_after_s)

    async def _admin_worker_op(
        self, request: web.Request, op: str, fn_attr: str,
        call,
    ) -> web.Response:
        """Shared body of the one-worker admin ops (drain / handover):
        parse {"instance_id", "model", ...}, resolve the pipeline, and
        dispatch through its `fn_attr` callable — 400 on a missing id,
        404 on an unresolvable model, 501 on an in-process pipeline,
        502 when the worker call fails. `call(fn, instance_id, body)`
        performs the op-specific invocation."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        instance_id = body.get("instance_id")
        if not instance_id:
            return web.json_response(
                {"error": "instance_id is required"}, status=400
            )
        models = self.manager.list_models()
        name = body.get("model") or (models[0] if len(models) == 1 else None)
        pipeline = self.manager.get(name) if name else None
        if pipeline is None:
            return web.json_response(
                {"error": f"model {name!r} not found (pass \"model\")"},
                status=404,
            )
        fn = getattr(pipeline, fn_attr, None)
        if fn is None:
            return web.json_response(
                {"error": f"{op} requires a distributed pipeline "
                          "(in=http out=dyn); in-process engines stop "
                          "with the server"},
                status=501,
            )
        try:
            reply = await call(fn, instance_id, body)
        except Exception as e:
            logger.exception("%s of %s failed", op, instance_id)
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response(
            {"status": "ok", "instance_id": instance_id, **(reply or {})}
        )

    async def admin_drain(self, request: web.Request) -> web.Response:
        """POST /v1/admin/drain {"instance_id": ..., "model": ...}:
        flip one worker into graceful drain — it deregisters, finishes
        in-flight requests within its drain budget, then exits 0
        (equivalently: SIGTERM the worker process). `/v1/fleet` shows
        state=draining while it winds down."""
        return await self._admin_worker_op(
            request, "drain", "drain_fn",
            lambda fn, iid, body: fn(iid),
        )

    async def admin_handover(self, request: web.Request) -> web.Response:
        """POST /v1/admin/handover {"instance_id": ..., "successor":
        optional, "model": optional}: retire one worker by LIVE KV
        migration (docs/operations.md "Rolling upgrades & worker
        handover") — it stops admissions, ships its hot KV pages to a
        successor over the transfer plane, lets in-flight streams
        continue there via replay (warm, no prompt recompute), then
        exits 0. Any failure degrades to the plain drain."""
        return await self._admin_worker_op(
            request, "handover", "handover_fn",
            lambda fn, iid, body: fn(iid, body.get("successor")),
        )

    async def clear_kv_blocks(self, request: web.Request) -> web.Response:
        """Flush reusable (cached, unreferenced) KV pages on every worker
        of every attached model (reference: /clear_kv_blocks fan-out)."""
        results: dict[str, int] = {}
        for name in self.manager.list_models():
            pipeline = self.manager.get(name)
            if pipeline is None or pipeline.flush_fn is None:
                continue
            try:
                results[name] = await pipeline.flush_fn()
            except Exception as e:
                logger.warning("flush for %s failed: %s", name, e)
                results[name] = -1
        return web.json_response(
            {"status": "ok", "cleared_pages": results}
        )

    async def embeddings(self, request: web.Request) -> web.Response:
        t0 = time.time()
        try:
            body = await request.json()
            req = EmbeddingRequest.model_validate(body)
        except Exception as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            self.metrics.request_done(
                req.model, "embedding", "404", time.time() - t0
            )
            return web.json_response(
                {"error": f"model {req.model!r} not found"}, status=404
            )
        with self.metrics.inflight_guard(req.model):
            try:
                resp = await pipeline.embed(req)
            except ValueError as e:
                self.metrics.request_done(
                    req.model, "embedding", "400", time.time() - t0
                )
                return web.json_response({"error": str(e)}, status=400)
            except Exception as e:
                logger.exception("embedding request failed")
                self.metrics.request_done(
                    req.model, "embedding", "500", time.time() - t0
                )
                return web.json_response({"error": str(e)}, status=500)
        self.metrics.request_done(
            req.model, "embedding", "200", time.time() - t0,
            input_tokens=resp.usage.prompt_tokens,
        )
        return web.json_response(resp.model_dump())

    async def responses(self, request: web.Request) -> web.StreamResponse:
        """OpenAI Responses API over the chat pipeline (reference serves
        /v1/responses alongside chat — http/service/openai.rs)."""
        t0 = time.time()
        try:
            body = await request.json()
            req = ResponsesRequest.model_validate(body)
        except Exception as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            self.metrics.request_done(
                req.model, "responses", "404", time.time() - t0
            )
            return web.json_response(
                {"error": f"model {req.model!r} not found"}, status=404
            )
        rejected = self._check_admission(request, req.model, "responses", t0)
        if rejected is not None:
            return rejected
        ctx = Context(deadline=self._deadline_from(request))
        rid = new_request_id("resp")
        with self.metrics.inflight_guard(req.model):
            try:
                chunk_stream = pipeline.responses_stream(req, ctx)
                if req.stream:
                    return await self._responses_stream(
                        request, req, rid, chunk_stream, ctx, t0
                    )
                chunks = [c async for c in chunk_stream]
                if self._deadline_error_finish(ctx, chunks):
                    raise RuntimeError("request deadline exceeded")
            except ValueError as e:
                self.metrics.request_done(
                    req.model, "responses", "400", time.time() - t0
                )
                return web.json_response({"error": str(e)}, status=400)
            except OverloadedError as e:
                self.metrics.shed("worker_queue_full")
                self.metrics.request_done(
                    req.model, "responses", "429", time.time() - t0
                )
                return self._reject_429(str(e), e.retry_after_s)
            except Exception as e:
                logger.exception("responses request failed")
                ctx.cancel()
                if ctx.deadline and time.time() >= ctx.deadline:
                    self.metrics.request_done(
                        req.model, "responses", "504", time.time() - t0
                    )
                    return web.json_response(
                        {"error": "request deadline exceeded"}, status=504
                    )
                self.metrics.request_done(
                    req.model, "responses", "500", time.time() - t0
                )
                return web.json_response({"error": str(e)}, status=500)
        agg = aggregate_chat_stream(chunks, req.model, rid)
        usage = agg.usage
        resp = self._make_responses_body(req, rid, agg)
        self.metrics.request_done(
            req.model, "responses", "200", time.time() - t0,
            input_tokens=usage.prompt_tokens if usage else 0,
            output_tokens=usage.completion_tokens if usage else 0,
        )
        return web.json_response(resp.model_dump())

    @staticmethod
    def _make_responses_body(req, rid: str, agg) -> ResponsesResponse:
        usage = agg.usage
        text = agg.choices[0].message.content or "" if agg.choices else ""
        return ResponsesResponse(
            id=rid,
            created_at=now(),
            model=req.model,
            status="completed",
            output=[
                ResponseOutputMessage(
                    id=rid + "-msg0",
                    content=[ResponseOutputText(text=text)],
                )
            ],
            usage=ResponsesUsage(
                input_tokens=usage.prompt_tokens if usage else 0,
                output_tokens=usage.completion_tokens if usage else 0,
                total_tokens=usage.total_tokens if usage else 0,
            ),
        )

    async def _responses_stream(
        self, http_request, req, rid: str, chunk_stream, ctx: Context,
        t0: float,
    ) -> web.StreamResponse:
        """Responses streaming: typed SSE events (response.created,
        response.output_text.delta, response.completed). The first
        chunk is pulled before the SSE prepares, so a pre-output
        failure (overloaded, deadline burned) propagates to the JSON
        handler's real HTTP status instead of a 200 event stream."""
        chunk_stream = await self._pull_first(chunk_stream)
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            },
        )
        await resp.prepare(http_request)

        async def emit(event: str, data: dict) -> None:
            body = json.dumps({"type": event, **data})
            await resp.write(
                f"event: {event}\ndata: {body}\n\n".encode()
            )

        await emit(
            "response.created",
            {"response": {"id": rid, "object": "response",
                          "status": "in_progress", "model": req.model}},
        )
        chunks = []
        status = "200"
        ntokens = 0
        try:
            async for chunk in chunk_stream:
                chunks.append(chunk)
                for c in chunk.choices:
                    if c.delta.content:
                        ntokens += 1
                        await emit(
                            "response.output_text.delta",
                            {"item_id": rid + "-msg0", "output_index": 0,
                             "delta": c.delta.content},
                        )
            agg = aggregate_chat_stream(chunks, req.model, rid)
            await emit(
                "response.completed",
                {"response": self._make_responses_body(req, rid, agg).model_dump()},
            )
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.cancel()
            status = "499"
        except Exception as e:  # the stream is already prepared: emit a
            # typed failure event instead of letting the error escape to a
            # JSON handler (and double-count the request)
            logger.exception("responses stream failed")
            ctx.cancel()
            status = "500"
            with contextlib.suppress(Exception):
                await emit(
                    "response.failed",
                    {"response": {"id": rid, "object": "response",
                                  "status": "failed",
                                  "error": {"message": str(e)}}},
                )
        finally:
            self.metrics.request_done(
                req.model, "responses", status, time.time() - t0,
                output_tokens=ntokens,
            )
        with contextlib.suppress(Exception):
            await resp.write_eof()
        return resp

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, kind="chat")

    async def completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, kind="completion")

    async def _serve(self, request: web.Request, kind: str) -> web.StreamResponse:
        t0 = time.time()
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        try:
            if kind == "chat":
                req = ChatCompletionRequest.model_validate(body)
            else:
                req = CompletionRequest.model_validate(body)
        except Exception as e:
            return web.json_response(
                {"error": f"invalid request: {e}"}, status=400
            )
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            self.metrics.request_done(req.model, kind, "404", time.time() - t0)
            return web.json_response(
                {"error": f"model {req.model!r} not found"}, status=404
            )
        rejected = self._check_admission(request, req.model, kind, t0)
        if rejected is not None:
            return rejected

        ctx = Context(deadline=self._deadline_from(request))
        stream_fn = (
            pipeline.chat_stream if kind == "chat" else pipeline.completion_stream
        )
        # Root span of the distributed trace: parented on an incoming
        # traceparent / x-request-id, else a fresh trace. Everything the
        # request touches in this task (preprocess, router, local engine)
        # nests under it via the contextvar.
        parent = telemetry.context_from_headers(request.headers)
        with self.metrics.inflight_guard(req.model), telemetry.span(
            "http.request", service="frontend", parent=parent,
            attrs={"model": req.model, "endpoint": kind,
                   "stream": bool(req.stream)},
        ) as root:
            try:
                if req.stream:
                    return await self._stream(
                        request, req, stream_fn(req, ctx), ctx, kind, t0
                    )
                return await self._unary(
                    req, stream_fn(req, ctx), ctx, kind, t0
                )
            except ValueError as e:
                root.set_attr("http_status", 400)
                self.metrics.request_done(req.model, kind, "400", time.time() - t0)
                return web.json_response({"error": str(e)}, status=400)
            except OverloadedError as e:
                # every reachable worker's bounded admission refused
                # (or the local engine's queue is full): 429 with the
                # worker-supplied Retry-After hint
                self.metrics.shed("worker_queue_full")
                root.set_attr("http_status", 429)
                self.metrics.request_done(req.model, kind, "429", time.time() - t0)
                return self._reject_429(str(e), e.retry_after_s)
            except Exception as e:
                logger.exception("request failed")
                ctx.cancel()
                if ctx.deadline and time.time() >= ctx.deadline:
                    # the end-to-end deadline expired somewhere in the
                    # stack — the honest status is 504, not 500
                    root.set_attr("http_status", 504)
                    root.end(status="error")
                    self.metrics.request_done(
                        req.model, kind, "504", time.time() - t0
                    )
                    return web.json_response(
                        {"error": "request deadline exceeded"}, status=504
                    )
                root.set_attr("http_status", 500)
                root.end(status="error")
                self.metrics.request_done(req.model, kind, "500", time.time() - t0)
                return web.json_response({"error": str(e)}, status=500)

    @staticmethod
    async def _pull_first(chunk_stream):
        """Pull the FIRST chunk before preparing an SSE response: a
        failure that happens before any output (all workers overloaded
        -> 429, no instances -> 5xx, deadline already burned -> 504)
        surfaces as a real HTTP status the client's retry logic
        understands, instead of a 200 SSE stream carrying an error
        event. Errors after output still ride the SSE."""
        it = chunk_stream.__aiter__()
        try:
            first_chunk = await it.__anext__()
        except StopAsyncIteration:
            first_chunk = None

        async def chained():
            # close the UNDERLYING stream on any exit — an abandoned
            # wrapper (client disconnect closes this generator) must
            # still propagate the close (and its cancel frames) to the
            # engine stream, exactly as the unwrapped stream did
            try:
                if first_chunk is not None:
                    yield first_chunk
                async for c in it:
                    yield c
            finally:
                aclose = getattr(it, "aclose", None)
                if aclose is not None:
                    await aclose()

        return chained()

    @staticmethod
    def _deadline_error_finish(ctx: Context, chunks) -> bool:
        """True when the request's deadline expired and the engine
        error-finished the stream — the honest unary status is 504, not
        a 200 body wrapping an empty error finish."""
        return bool(
            ctx.deadline
            and time.time() >= ctx.deadline
            and any(
                c.finish_reason == "error"
                for chunk in chunks
                for c in chunk.choices
            )
        )

    async def _unary(
        self, req, chunk_stream, ctx: Context, kind: str, t0: float
    ) -> web.Response:
        chunks = [c async for c in chunk_stream]
        if self._deadline_error_finish(ctx, chunks):
            # the engine error-finished this stream because its deadline
            # expired: surface 504 via the handler above, not a 200 body
            raise RuntimeError("request deadline exceeded")
        rid = chunks[0].id if chunks else "unknown"
        resp = aggregate_chat_stream(chunks, req.model, rid)
        usage = resp.usage
        self.metrics.request_done(
            req.model, kind, "200", time.time() - t0,
            input_tokens=usage.prompt_tokens if usage else 0,
            output_tokens=usage.completion_tokens if usage else 0,
        )
        root = telemetry.current_span()
        if root is not None:
            root.set_attr("http_status", 200)
            root.set_attr("e2e_ms", round((time.time() - t0) * 1000.0, 3))
        if kind == "completion":
            from dynamo_tpu.protocols.openai import CompletionLogprobs

            def to_completion_choice(choice) -> CompletionChoice:
                lp = None
                if choice.logprobs is not None:
                    entries = choice.logprobs.content
                    offsets, pos = [], 0
                    for e in entries:
                        offsets.append(pos)
                        pos += len(e.token)
                    lp = CompletionLogprobs(
                        tokens=[e.token for e in entries],
                        token_logprobs=[e.logprob for e in entries],
                        top_logprobs=[
                            {t.token: t.logprob for t in e.top_logprobs}
                            for e in entries
                        ],
                        text_offset=offsets,
                    )
                return CompletionChoice(
                    index=choice.index,
                    text=choice.message.content or "",
                    logprobs=lp,
                    finish_reason=choice.finish_reason,
                )

            comp = CompletionResponse(
                id=resp.id, created=resp.created, model=req.model,
                choices=[to_completion_choice(c) for c in resp.choices],
                usage=usage,
            )
            return web.json_response(comp.model_dump(exclude_none=True))
        return web.json_response(resp.model_dump(exclude_none=True))

    async def _stream(
        self, http_request: web.Request, req, chunk_stream, ctx: Context,
        kind: str, t0: float,
    ) -> web.StreamResponse:
        chunk_stream = await self._pull_first(chunk_stream)
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        await resp.prepare(http_request)
        ttft = None
        last_t = None
        itl: list[float] = []
        ntokens = 0
        status = "200"
        text_offsets: dict[int, int] = {}  # per-choice, for legacy logprobs
        try:
            async for chunk in chunk_stream:
                t = time.time()
                if any(c.delta.content for c in chunk.choices):
                    ntokens += 1
                    if ttft is None:
                        ttft = t - t0
                    elif last_t is not None:
                        itl.append(t - last_t)
                    last_t = t
                payload = (
                    chunk
                    if kind == "chat"
                    else _legacy_completion_chunk(chunk, text_offsets)
                )
                await resp.write(sse_event(payload))
            await resp.write(SSE_DONE)
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: cancel into the engine (disconnect monitor)
            ctx.cancel()
            status = "499"
        except Exception as e:  # prepared stream: error rides the SSE
            logger.exception("chat stream failed")
            ctx.cancel()
            status = "500"
            with contextlib.suppress(Exception):
                await resp.write(sse_event({"error": {"message": str(e)}}))
                await resp.write(SSE_DONE)
        finally:
            self.metrics.request_done(
                req.model, kind, status, time.time() - t0,
                output_tokens=ntokens, ttft_s=ttft, itl_s=itl,
            )
            # outcome attrs on the trace root: the tail sampler's
            # slow/error signals (TTFT/e2e vs the live SLO p95s, HTTP
            # status) read straight off the assembled root span
            root = telemetry.current_span()
            if root is not None:
                root.set_attr("http_status", int(status))
                root.set_attr(
                    "e2e_ms", round((time.time() - t0) * 1000.0, 3)
                )
                if ttft is not None:
                    root.set_attr("ttft_ms", round(ttft * 1000.0, 3))
        with contextlib.suppress(Exception):
            await resp.write_eof()
        return resp

"""Prometheus text-format metrics for the HTTP service (hand-rolled
exposition; no client library in the image).

Metric names mirror the reference's HTTP service plane
(http/service/metrics.rs:104-111): requests_total, inflight_requests,
request_duration, input/output_sequence_tokens, time_to_first_token,
inter_token_latency.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Optional

PREFIX = "dynamo_tpu_http_service"

_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class Histogram:
    def __init__(self):
        self.counts = [0] * (len(_BUCKETS) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.total += v
        self.n += 1
        for i, b in enumerate(_BUCKETS):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def expose(self, name: str, labels: str) -> list[str]:
        out = []
        cum = 0
        for i, b in enumerate(_BUCKETS):
            cum += self.counts[i]
            out.append(f'{name}_bucket{{{labels},le="{b}"}} {cum}')
        cum += self.counts[-1]
        out.append(f'{name}_bucket{{{labels},le="+Inf"}} {cum}')
        out.append(f"{name}_sum{{{labels}}} {self.total}")
        out.append(f"{name}_count{{{labels}}} {self.n}")
        return out


class FrontendMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = defaultdict(int)  # (model, endpoint, status)
        self.inflight = defaultdict(int)  # model
        self.input_tokens = defaultdict(int)
        self.output_tokens = defaultdict(int)
        self.duration = defaultdict(Histogram)  # model
        self.ttft = defaultdict(Histogram)
        self.itl = defaultdict(Histogram)

    def request_done(
        self, model: str, endpoint: str, status: str, duration_s: float,
        input_tokens: int = 0, output_tokens: int = 0,
        ttft_s: Optional[float] = None, itl_s: Optional[list[float]] = None,
    ) -> None:
        with self._lock:
            self.requests_total[(model, endpoint, status)] += 1
            self.input_tokens[model] += input_tokens
            self.output_tokens[model] += output_tokens
            self.duration[model].observe(duration_s)
            if ttft_s is not None:
                self.ttft[model].observe(ttft_s)
            for v in itl_s or ():
                self.itl[model].observe(v)

    def inflight_guard(self, model: str) -> "InflightGuard":
        return InflightGuard(self, model)

    def expose(self) -> str:
        lines = []
        with self._lock:
            lines.append(f"# TYPE {PREFIX}_requests_total counter")
            for (model, ep, status), n in sorted(self.requests_total.items()):
                lines.append(
                    f'{PREFIX}_requests_total{{model="{model}",endpoint="{ep}",status="{status}"}} {n}'
                )
            lines.append(f"# TYPE {PREFIX}_inflight_requests gauge")
            for model, n in sorted(self.inflight.items()):
                lines.append(f'{PREFIX}_inflight_requests{{model="{model}"}} {n}')
            for name, table in (
                ("input_sequence_tokens", self.input_tokens),
                ("output_sequence_tokens", self.output_tokens),
            ):
                lines.append(f"# TYPE {PREFIX}_{name} counter")
                for model, n in sorted(table.items()):
                    lines.append(f'{PREFIX}_{name}{{model="{model}"}} {n}')
            for name, table in (
                ("request_duration_seconds", self.duration),
                ("time_to_first_token_seconds", self.ttft),
                ("inter_token_latency_seconds", self.itl),
            ):
                lines.append(f"# TYPE {PREFIX}_{name} histogram")
                for model, h in sorted(table.items()):
                    lines.extend(h.expose(f"{PREFIX}_{name}", f'model="{model}"'))
        return "\n".join(lines) + "\n"


class InflightGuard:
    """RAII inflight counter (reference: metrics.rs InflightGuard :41)."""

    def __init__(self, metrics: FrontendMetrics, model: str):
        self.metrics = metrics
        self.model = model

    def __enter__(self):
        with self.metrics._lock:
            self.metrics.inflight[self.model] += 1
        return self

    def __exit__(self, *exc):
        with self.metrics._lock:
            self.metrics.inflight[self.model] -= 1
        return False

"""Prometheus text-format metrics for the HTTP service (hand-rolled
exposition; no client library in the image).

Metric names mirror the reference's HTTP service plane
(http/service/metrics.rs:104-111): requests_total, inflight_requests,
request_duration, input/output_sequence_tokens, time_to_first_token,
inter_token_latency. Latency histograms use a seconds ladder (≤30 s);
sequence-token histograms use their own power-of-two ladder (8…32768) —
a p99 prompt length must land in a real bucket, not +Inf.

The exposition is linted in tests by telemetry/promlint.py — new
metrics must keep unique TYPE lines, escaped labels, `_total` counter
names, and monotonic histogram buckets.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Optional

PREFIX = "dynamo_tpu_http_service"

_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

#: token-count ladder for input/output_sequence_tokens (power of two up
#: to a 32k context)
_TOKEN_BUCKETS = (
    8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0, 2048.0, 4096.0,
    8192.0, 16384.0, 32768.0,
)


class Histogram:
    def __init__(self, buckets: tuple = _BUCKETS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.total += v
        self.n += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def expose(self, name: str, labels: str) -> list[str]:
        out = []
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self.counts[i]
            out.append(f'{name}_bucket{{{labels},le="{b}"}} {cum}')
        cum += self.counts[-1]
        out.append(f'{name}_bucket{{{labels},le="+Inf"}} {cum}')
        out.append(f"{name}_sum{{{labels}}} {self.total}")
        out.append(f"{name}_count{{{labels}}} {self.n}")
        return out


def _token_histogram() -> Histogram:
    return Histogram(buckets=_TOKEN_BUCKETS)


class FrontendMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = defaultdict(int)  # (model, endpoint, status)
        self.inflight = defaultdict(int)  # model
        #: per-request sequence-length distributions (token ladder); the
        #: _sum series still carries total tokens for rate() dashboards
        self.input_tokens = defaultdict(_token_histogram)
        self.output_tokens = defaultdict(_token_histogram)
        self.duration = defaultdict(Histogram)  # model
        self.ttft = defaultdict(Histogram)
        self.itl = defaultdict(Histogram)
        #: streaming SLO accounting per endpoint (telemetry/slo.py):
        #: TTFT/ITL/e2e quantile sketches + SLA-attainment, goodput and
        #: multi-window burn-rate gauges, exposed as dynamo_tpu_slo_*
        from dynamo_tpu.telemetry.slo import SloTracker

        self.slo: dict[str, SloTracker] = {}
        self._slo_factory = SloTracker
        #: load shedding (docs/operations.md "Overload & draining"):
        #: requests rejected with 429, by reason — exposed as
        #: dynamo_tpu_shed_total{reason}. Reasons: frontend_inflight
        #: (--max-inflight gate), burn (SLO burn-rate shedder),
        #: worker_queue_full (every worker's bounded admission refused)
        self.shed_total: dict[str, int] = defaultdict(int)

    def request_done(
        self, model: str, endpoint: str, status: str, duration_s: float,
        input_tokens: int = 0, output_tokens: int = 0,
        ttft_s: Optional[float] = None, itl_s: Optional[list[float]] = None,
    ) -> None:
        with self._lock:
            self.requests_total[(model, endpoint, status)] += 1
            # error paths (400/404/500) report no token counts; a zero
            # there is absence of data, not a zero-length sequence — it
            # must not drag the length distribution into the first bucket
            if input_tokens:
                self.input_tokens[model].observe(input_tokens)
            if output_tokens:
                self.output_tokens[model].observe(output_tokens)
            self.duration[model].observe(duration_s)
            if ttft_s is not None:
                self.ttft[model].observe(ttft_s)
            for v in itl_s or ():
                self.itl[model].observe(v)
            if status == "200":
                tr = self.slo.get(endpoint)
                if tr is None:
                    tr = self.slo[endpoint] = self._slo_factory()
                ttft_ms = ttft_s * 1000.0 if ttft_s is not None else None
                if ttft_ms is not None:
                    tr.observe("ttft_ms", ttft_ms)
                itl_ms = None
                if itl_s:
                    for v in itl_s:
                        tr.observe("itl_ms", v * 1000.0)
                    itl_ms = sum(itl_s) / len(itl_s) * 1000.0
                e2e_ms = duration_s * 1000.0
                tr.observe("e2e_ms", e2e_ms)
                tr.finish_request(
                    ttft_ms=ttft_ms, itl_ms=itl_ms, e2e_ms=e2e_ms,
                    tokens=output_tokens,
                )

    def shed(self, reason: str) -> None:
        """Count one load-shed 429 (the request_done 429 row is separate:
        shed_total answers "why", requests_total answers "how many").
        Also marks the fleet event timeline: per-request 429s coalesce
        into one shed EPISODE event per ~5 s burst (GET /v1/fleet/events
        + the Grafana annotation layer)."""
        with self._lock:
            self.shed_total[reason] += 1
        from dynamo_tpu.telemetry import events

        events.record(
            "shed", severity="warning", source=f"frontend:{reason}",
            coalesce_s=5.0, reason=reason,
        )

    def total_inflight(self) -> int:
        with self._lock:
            return sum(self.inflight.values())

    def retry_after_s(self, endpoint: str) -> float:
        """Retry-After hint for a frontend-side shed, priced from the
        endpoint's live SLO sketches (runtime/overload.py)."""
        from dynamo_tpu.runtime.overload import estimate_retry_after_s

        with self._lock:
            tracker = self.slo.get(endpoint)
        return estimate_retry_after_s(tracker)

    def inflight_guard(self, model: str) -> "InflightGuard":
        return InflightGuard(self, model)

    def expose(self, openmetrics: bool = False) -> str:
        """Classic Prometheus text by default; `openmetrics=True` is the
        negotiated rendering — OpenMetrics counter-family naming, the
        `# EOF` terminator, and phase-histogram EXEMPLARS (which the
        classic parser would reject, failing the whole scrape)."""
        lines = []
        with self._lock:
            lines.append(f"# TYPE {PREFIX}_requests_total counter")
            for (model, ep, status), n in sorted(self.requests_total.items()):
                lines.append(
                    f'{PREFIX}_requests_total{{model="{model}",endpoint="{ep}",status="{status}"}} {n}'
                )
            lines.append(f"# TYPE {PREFIX}_inflight_requests gauge")
            for model, n in sorted(self.inflight.items()):
                lines.append(f'{PREFIX}_inflight_requests{{model="{model}"}} {n}')
            if self.shed_total:
                lines.append("# TYPE dynamo_tpu_shed_total counter")
                for reason, n in sorted(self.shed_total.items()):
                    lines.append(
                        f'dynamo_tpu_shed_total{{reason="{reason}"}} {n}'
                    )
            for name, table in (
                ("input_sequence_tokens", self.input_tokens),
                ("output_sequence_tokens", self.output_tokens),
                ("request_duration_seconds", self.duration),
                ("time_to_first_token_seconds", self.ttft),
                ("inter_token_latency_seconds", self.itl),
            ):
                lines.append(f"# TYPE {PREFIX}_{name} histogram")
                for model, h in sorted(table.items()):
                    lines.extend(h.expose(f"{PREFIX}_{name}", f'model="{model}"'))
            if self.slo:
                from dynamo_tpu.telemetry import slo as slo_mod

                lines.extend(
                    slo_mod.expose_lines(
                        "dynamo_tpu_slo",
                        [
                            (f'endpoint="{ep}"', tr)
                            for ep, tr in sorted(self.slo.items())
                        ],
                    )
                )
        # per-phase latency histograms live process-global (telemetry
        # layer); whichever process hosts a phase shows it here
        from dynamo_tpu.telemetry import phases

        lines.extend(phases.expose_lines(exemplars=openmetrics))
        # stall-watchdog counters (telemetry/watchdog.py): also
        # process-global — the single-process topology hosts the engine
        # (and therefore its stalls) right here
        from dynamo_tpu.telemetry.watchdog import stall_counters

        lines.extend(stall_counters.expose_lines())
        # speculative-decoding counters + live acceptance-rate gauge:
        # process-global over in-process engines (single-process serving
        # exposes them here; the metrics service mirrors the families
        # for its own process — "both Prometheus surfaces")
        from dynamo_tpu.telemetry import debug as _debug

        lines.extend(_debug.spec_lines())  # fixed dynamo_tpu_spec_* name
        # on-device K-step decode windows (EngineConfig.decode_kstep)
        lines.extend(_debug.kstep_lines())
        # data-integrity rejections (disk-tier checksum misses, corrupt
        # transfer frames): process-global like the phase histograms
        lines.extend(_debug.integrity_lines())
        # control-plane HA: degraded gauge + outage/failover counters
        # for this process's fabric connection (zeros for local
        # pipelines, which have no broker to lose)
        lines.extend(_debug.control_plane_lines())
        # KV index health (gaps / resyncs / drift / stale subtrees): the
        # KV-aware router lives in this process in single-process
        # serving — docs/operations.md "KV index consistency"
        lines.extend(_debug.kv_index_lines())
        # HBM accounting plane (docs/observability.md "Reading the perf
        # plane"): per-device weights/kv_pool/scratch/free/peak bytes of
        # the in-process engines
        lines.extend(_debug.hbm_lines())
        text = "\n".join(lines) + "\n"
        if openmetrics:
            from dynamo_tpu.telemetry.openmetrics import to_openmetrics

            return to_openmetrics(text)
        return text


class InflightGuard:
    """RAII inflight counter (reference: metrics.rs InflightGuard :41)."""

    def __init__(self, metrics: FrontendMetrics, model: str):
        self.metrics = metrics
        self.model = model

    def __enter__(self):
        with self.metrics._lock:
            self.metrics.inflight[self.model] += 1
        return self

    def __exit__(self, *exc):
        with self.metrics._lock:
            self.metrics.inflight[self.model] -= 1
        return False

"""Frontend admission control: inflight cap + SLO-burn load shedding.

The goodput-preserving half of the overload plane (docs/operations.md
"Overload & draining"): when demand exceeds capacity, answering a
bounded subset of requests fast beats answering all of them late.

Two gates, checked before a request touches the pipeline:

1. **Inflight cap** (`--max-inflight`): a hard ceiling on concurrently
   served requests across all models. Everything past it is shed.

2. **Burn-rate shedder** (`--shed-burn-threshold`): watches the
   endpoint's short-window SLO burn rate (telemetry/slo.py — 1.0 means
   spending the error budget exactly). Past the threshold, shedding
   ramps LINEARLY with the overshoot (threshold → 0%, 2x threshold →
   100%) and only ever hits work below the priority floor — requests
   carrying `x-priority: 1` (or higher) ride through, so paying/critical
   traffic keeps its SLA while best-effort load absorbs the degradation.

Both answer HTTP 429 with a `Retry-After` computed from the endpoint's
live latency sketches. Default-off: no cap + no threshold = the gate is
never consulted (bit-identical serving).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.frontend.metrics import FrontendMetrics

#: requests at or above this x-priority are never burn-shed
PRIORITY_FLOOR = 1


@dataclass(frozen=True)
class ShedDecision:
    reason: str  # frontend_inflight | burn
    retry_after_s: float
    message: str


class AdmissionController:
    def __init__(
        self,
        metrics: FrontendMetrics,
        max_inflight: Optional[int] = None,
        burn_threshold: Optional[float] = None,
        rng=None,
    ):
        self.metrics = metrics
        self.max_inflight = max_inflight
        self.burn_threshold = burn_threshold
        self._rng = rng or random.random

    @property
    def enabled(self) -> bool:
        return self.max_inflight is not None or self.burn_threshold is not None

    @staticmethod
    def priority_from(headers) -> int:
        """`x-priority` header (int; default 0 = best-effort; >=1 is
        never burn-shed). Malformed values read as 0, never an error."""
        try:
            return int(headers.get("x-priority", 0))
        except (TypeError, ValueError):
            return 0

    def _burn_rate(self, endpoint: str) -> float:
        tracker = self.metrics.slo.get(endpoint)
        if tracker is None or not tracker.windows:
            return 0.0
        # the SHORT window pages first — that's the one shedding acts on
        return tracker.burn_rate(min(tracker.windows))

    def check(self, endpoint: str, priority: int = 0) -> Optional[ShedDecision]:
        """None = admit; a ShedDecision = reject with 429."""
        if self.max_inflight is not None:
            inflight = self.metrics.total_inflight()
            if inflight >= self.max_inflight:
                self.metrics.shed("frontend_inflight")
                return ShedDecision(
                    reason="frontend_inflight",
                    retry_after_s=self.metrics.retry_after_s(endpoint),
                    message=(
                        f"{inflight} requests already in flight "
                        f"(--max-inflight {self.max_inflight})"
                    ),
                )
        thr = self.burn_threshold
        if thr is not None and priority < PRIORITY_FLOOR:
            burn = self._burn_rate(endpoint)
            if burn > thr:
                # linear ramp: thr -> 0% shed, 2*thr -> 100% shed.
                # thr == 0 reads as "shed best-effort whenever burning
                # at all" — full shed, never a division by zero.
                frac = (
                    min(1.0, (burn - thr) / thr) if thr > 0 else 1.0
                )
                if self._rng() < frac:
                    self.metrics.shed("burn")
                    return ShedDecision(
                        reason="burn",
                        retry_after_s=self.metrics.retry_after_s(endpoint),
                        message=(
                            f"SLO burn rate {burn:.2f} over threshold "
                            f"{thr:.2f}; shedding best-effort work "
                            "(send x-priority >= 1 to bypass)"
                        ),
                    )
        return None

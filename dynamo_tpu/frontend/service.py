"""ModelManager + ModelPipeline + ModelWatcher.

A ModelPipeline is the canonical serving chain for one model:
  OpenAI request -> preprocess (template+tokenize) -> engine source
  (local AsyncEngine, or PushRouter to remote workers) -> postprocess
  (detokenize + stop + chunks)
(reference: build_pipeline — entrypoint/input/common.rs:121-150).

The ModelManager maps model name -> pipeline; the ModelWatcher feeds it
from a MODEL_ROOT prefix watch so frontends attach/detach models at
runtime (discovery/watcher.rs:69, model_manager.rs:33).
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Callable, Optional

from dynamo_tpu.model_card import ModelDeploymentCard, ModelEntry, load_card
from dynamo_tpu.preprocessor import OpenAIPreprocessor, load_tokenizer
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    CompletionRequest,
    EmbeddingData,
    EmbeddingRequest,
    EmbeddingResponse,
    ResponsesRequest,
    StreamOptions,
    Usage,
    combine_usages,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu import telemetry

logger = logging.getLogger(__name__)


class ModelPipeline:
    def __init__(
        self,
        card: ModelDeploymentCard,
        engine_fn: Callable[[Context, PreprocessedRequest], AsyncIterator[dict]],
        close_fn: Optional[Callable] = None,
        embed_fn: Optional[Callable] = None,
    ):
        self.card = card
        self.preprocessor = OpenAIPreprocessor(
            load_tokenizer(card.tokenizer), model_name=card.name
        )
        self.engine_fn = engine_fn
        self.close_fn = close_fn
        #: async (prompts: list[list[int]]) -> list of vectors
        self.embed_fn = embed_fn
        #: async (pixels: np [B,H,W,3]) -> np [B, n, H] projected image
        #: embeddings — attached by multimodal deployments (the encode
        #: worker); enables image_pixels content parts
        self.image_encode_fn = None
        #: async () -> cleared page count (the /clear_kv_blocks fan-out)
        self.flush_fn = None
        #: async (instance_id) -> reply dict: flip one worker into
        #: graceful drain (POST /v1/admin/drain; distributed pipelines
        #: only — docs/operations.md "Overload & draining")
        self.drain_fn = None
        #: async (instance_id, successor=None) -> reply dict: retire one
        #: worker by live KV handover (POST /v1/admin/handover;
        #: distributed pipelines only — docs/operations.md "Rolling
        #: upgrades & worker handover")
        self.handover_fn = None

    async def chat_stream(
        self, request: ChatCompletionRequest, context: Optional[Context] = None
    ) -> AsyncIterator[ChatCompletionChunk]:
        ctx = context or Context()
        with telemetry.span(
            "preprocess", service="frontend",
            attrs={"model": self.card.name},
        ) as sp:
            messages = [
                m.model_dump(exclude_none=True) for m in request.messages
            ]
            if any(isinstance(m.get("content"), list) for m in messages):
                messages = await self._encode_image_parts(messages)
            pre = self.preprocessor.preprocess_chat_messages(messages, request)
            self._clamp(pre)
            pre.deadline = ctx.deadline  # rides every wire hop from here
            sp.set_attr("input_tokens", len(pre.token_ids))
        include_usage = bool(
            request.stream_options and request.stream_options.include_usage
        ) or not request.stream
        async for chunk in self._choices_stream(
            pre, ctx, include_usage, n=request.n or 1
        ):
            yield chunk

    async def completion_stream(
        self, request: CompletionRequest, context: Optional[Context] = None
    ) -> AsyncIterator[ChatCompletionChunk]:
        ctx = context or Context()
        with telemetry.span(
            "preprocess", service="frontend",
            attrs={"model": self.card.name},
        ) as sp:
            pre = self.preprocessor.preprocess_completion(request)
            self._clamp(pre)
            pre.deadline = ctx.deadline  # rides every wire hop from here
            sp.set_attr("input_tokens", len(pre.token_ids))
        include_usage = bool(
            request.stream_options and request.stream_options.include_usage
        ) or not request.stream
        async for chunk in self._choices_stream(
            pre, ctx, include_usage, n=request.n or 1
        ):
            yield chunk

    def _one_choice(self, pre: PreprocessedRequest, ctx: Context, include_usage):
        stream = self.engine_fn(ctx, pre)
        return self.preprocessor.postprocess_chat_stream(
            stream, pre.request_id, pre, include_usage=include_usage
        )

    async def _choices_stream(
        self, pre: PreprocessedRequest, ctx: Context, include_usage: bool,
        n: int,
    ) -> AsyncIterator[ChatCompletionChunk]:
        """OpenAI `n`: one engine generation per choice, streamed
        interleaved with choice indices rewritten (the engine batches the
        sibling generations like any other concurrent requests — the
        prefix cache makes their shared prompt prefill nearly free)."""
        if n <= 1:
            async for chunk in self._one_choice(pre, ctx, include_usage):
                yield chunk
            return
        import dataclasses

        done = object()
        queue: asyncio.Queue = asyncio.Queue()
        usages: list[Usage] = []

        def sub_pre(i: int) -> PreprocessedRequest:
            return dataclasses.replace(
                pre,
                request_id=f"{pre.request_id}-{i}",
                seed=None if pre.seed is None else pre.seed + i,
            )

        async def pump(i: int):
            try:
                async for chunk in self._one_choice(sub_pre(i), ctx, include_usage):
                    # All chunks of one completion share one id and one
                    # usage block: restore the parent id and fold the
                    # per-choice usage into a single trailing chunk.
                    chunk.id = pre.request_id
                    if chunk.usage is not None:
                        usages.append(chunk.usage)
                        if not chunk.choices:
                            continue  # usage-only trailer; re-emitted combined
                        chunk.usage = None
                    for c in chunk.choices:
                        c.index = i
                    await queue.put(chunk)
            except Exception as e:  # surfaced on the consumer side
                await queue.put(e)
            finally:
                await queue.put(done)

        tasks = [asyncio.create_task(pump(i)) for i in range(n)]
        finished = 0
        try:
            while finished < n:
                item = await queue.get()
                if item is done:
                    finished += 1
                    continue
                if isinstance(item, Exception):
                    raise item
                yield item
            combined = combine_usages(usages)
            if combined is not None:
                yield ChatCompletionChunk(
                    id=pre.request_id,
                    model=self.card.name,
                    choices=[],
                    usage=combined,
                )
        finally:
            for t in tasks:
                t.cancel()

    async def _encode_image_parts(self, messages: list[dict]) -> list[dict]:
        """Turn image_pixels content parts into image_embed parts via the
        attached encoder (reference: the multimodal encode worker +
        `connect` tensor hand-off, examples/multimodal)."""
        import base64

        import numpy as np

        out = []
        for m in messages:
            content = m.get("content")
            if not isinstance(content, list):
                out.append(m)
                continue
            parts = []
            for part in content:
                if (
                    isinstance(part, dict)
                    and part.get("type") == "image_pixels"
                ):
                    if self.image_encode_fn is None:
                        raise ValueError(
                            "image_pixels content requires an image "
                            "encoder (multimodal deployment)"
                        )
                    raw = part["data"]
                    if isinstance(raw, str):
                        raw = base64.b64decode(raw)
                    pixels = np.frombuffer(raw, np.float32).reshape(
                        part["shape"]
                    )
                    embeds = await self.image_encode_fn(pixels[None])
                    parts.append(
                        {
                            "type": "image_embed",
                            "embedding": np.asarray(embeds[0], np.float32),
                        }
                    )
                else:
                    parts.append(part)
            out.append({**m, "content": parts})
        return out

    def responses_stream(
        self, request: ResponsesRequest, context: Optional[Context] = None
    ) -> AsyncIterator[ChatCompletionChunk]:
        """Responses API rides the chat pipeline: input messages map onto a
        chat request (instructions -> system) and the caller shapes the
        chunk stream into Responses objects/events."""
        chat = ChatCompletionRequest(
            model=request.model,
            messages=request.as_chat_messages(),
            max_tokens=request.max_output_tokens,
            temperature=request.temperature,
            top_p=request.top_p,
            stream=request.stream,
            stream_options=StreamOptions(include_usage=True),
            ext=request.ext,
            nvext=request.nvext,
        )
        return self.chat_stream(chat, context)

    async def embed(self, request: EmbeddingRequest) -> EmbeddingResponse:
        """OpenAI embeddings over this model (reference: embeddings route,
        http/service/openai.rs). Accepts a string, list of strings, token
        list, or list of token lists."""
        if self.embed_fn is None:
            raise ValueError(
                f"model {self.card.name!r} does not serve embeddings"
            )
        if request.encoding_format not in (None, "float", "base64"):
            raise ValueError(
                f"unsupported encoding_format {request.encoding_format!r}; "
                "use 'float' or 'base64'"
            )
        raw = request.input
        if isinstance(raw, str):
            batch = [raw]
        elif isinstance(raw, list) and raw and isinstance(raw[0], int):
            batch = [raw]
        elif not raw:
            raise ValueError("input must be non-empty")
        else:
            batch = raw
        tok = self.preprocessor.tokenizer
        prompts = [
            p if isinstance(p, list) else tok.encode(p) for p in batch
        ]
        for p in prompts:
            if not p:
                raise ValueError("input item tokenized to zero tokens")
            if len(p) > self.card.context_length:
                raise ValueError(
                    f"input of {len(p)} tokens exceeds context window "
                    f"{self.card.context_length}"
                )
        vectors = await self.embed_fn(prompts)
        ntok = sum(len(p) for p in prompts)
        data = []
        for i, vec in enumerate(vectors):
            if request.encoding_format == "base64":
                import base64

                import numpy as np

                emb = base64.b64encode(
                    np.asarray(vec, np.float32).tobytes()
                ).decode()
            else:
                emb = [float(x) for x in vec]
            data.append(EmbeddingData(index=i, embedding=emb))
        return EmbeddingResponse(
            model=self.card.name,
            data=data,
            usage=Usage(prompt_tokens=ntok, total_tokens=ntok),
        )

    def _clamp(self, pre: PreprocessedRequest) -> None:
        room = self.card.context_length - len(pre.token_ids) - 1
        if room < 0:
            raise ValueError(
                f"prompt of {len(pre.token_ids)} tokens exceeds context "
                f"window {self.card.context_length}"
            )
        pre.max_tokens = max(1, min(pre.max_tokens, room)) if room else 1

    async def close(self) -> None:
        if self.close_fn:
            res = self.close_fn()
            if asyncio.iscoroutine(res):
                await res


def local_pipeline(card: ModelDeploymentCard, async_engine) -> ModelPipeline:
    """Single-process pipeline over an in-process AsyncEngine."""
    pipeline = ModelPipeline(
        card,
        engine_fn=async_engine.generate,
        embed_fn=getattr(async_engine, "embed", None),
    )
    if hasattr(async_engine, "submit"):
        # AsyncEngineRunner: the engine thread is the only thread allowed
        # to touch the allocator — route the flush through it.
        async def flush_fn() -> int:
            return await async_engine.submit(
                lambda eng: eng.allocator.clear_cache()
            )

        pipeline.flush_fn = flush_fn
    elif hasattr(async_engine, "allocator"):
        # Loop-driven test engines (mock): no engine thread to race.
        async def flush_fn() -> int:
            return async_engine.allocator.clear_cache()

        pipeline.flush_fn = flush_fn
    return pipeline


def router_pipeline(
    card: ModelDeploymentCard, router: PushRouter, kv_router=None, fabric=None
) -> ModelPipeline:
    """Distributed pipeline: push preprocessed requests to workers. With a
    KvRouter attached, per-token and completion feedback keep its local
    in-flight bookkeeping current (reference: kv_router.rs:204-210)."""

    async def engine_fn(ctx: Context, pre: PreprocessedRequest):
        instance_id = pre.annotations.get("instance_id")
        try:
            async for item in router.generate(
                pre.to_dict(), context=ctx, instance_id=instance_id
            ):
                if kv_router is not None and isinstance(item, dict):
                    kv_router.on_tokens(
                        pre.request_id, len(item.get("token_ids", ()))
                    )
                yield item
        finally:
            if kv_router is not None:
                kv_router.on_complete(pre.request_id)

    async def close_fn():
        router.close()
        embed_router.close()
        flush_router.close()
        drain_router.close()
        handover_router.close()
        if kv_router is not None:
            await kv_router.stop()

    # Embedding calls ride the same worker instances on their "embed"
    # ingress handler; KV-affinity is meaningless for them (no decode), so
    # the side router always balances round-robin.
    embed_router = PushRouter(
        router.source, "embed", mode=RouterMode.ROUND_ROBIN
    )

    async def embed_fn(prompts):
        async for reply in embed_router.generate({"prompts": prompts}):
            return reply["embeddings"]
        raise RuntimeError("embed worker returned no reply")

    flush_router = PushRouter(
        router.source, "flush", mode=RouterMode.DIRECT
    )
    drain_router = PushRouter(
        router.source, "drain", mode=RouterMode.DIRECT
    )
    handover_router = PushRouter(
        router.source, "handover", mode=RouterMode.DIRECT
    )

    async def drain_fn(instance_id: str) -> dict:
        """Flip ONE worker into graceful drain (its `drain` ingress
        handler answers immediately; the wind-down runs worker-side)."""
        async for reply in drain_router.generate(
            {}, instance_id=instance_id, max_attempts=1
        ):
            return reply if isinstance(reply, dict) else {}
        return {}

    async def handover_fn(instance_id: str, successor=None) -> dict:
        """Retire ONE worker by live KV handover (its `handover` ingress
        handler acks immediately; migration + drain run worker-side —
        docs/operations.md "Rolling upgrades & worker handover")."""
        async for reply in handover_router.generate(
            {"successor": successor}, instance_id=instance_id,
            max_attempts=1,
        ):
            return reply if isinstance(reply, dict) else {}
        return {}

    async def flush_fn() -> int:
        """Fan /clear_kv_blocks out to EVERY live worker instance. A dead
        instance (lease not yet expired) must not abort the fan-out —
        the rest still flush and partial counts survive."""
        cleared = 0
        for inst in router.source.list():
            try:
                async for reply in flush_router.generate(
                    {}, instance_id=inst.instance_id
                ):
                    cleared += int(reply.get("cleared_pages", 0))
            except Exception as e:
                logger.warning(
                    "flush on %s failed: %s", inst.instance_id, e
                )
        if fabric is not None:
            # Broadcast for fleet members the frontend has no route to
            # (disaggregated prefill workers consume queues, not RPC).
            from dynamo_tpu.subjects import FLUSH_SUBJECT

            await fabric.publish(FLUSH_SUBJECT, {"source": "frontend"})
        return cleared

    pipeline = ModelPipeline(
        card, engine_fn=engine_fn, close_fn=close_fn, embed_fn=embed_fn
    )
    pipeline.flush_fn = flush_fn
    pipeline.drain_fn = drain_fn
    pipeline.handover_fn = handover_fn
    return pipeline


class ModelManager:
    def __init__(self):
        self.pipelines: dict[str, ModelPipeline] = {}

    def add(self, name: str, pipeline: ModelPipeline) -> None:
        self.pipelines[name] = pipeline
        logger.info("model attached: %s", name)

    async def remove(self, name: str) -> None:
        p = self.pipelines.pop(name, None)
        if p is not None:
            await p.close()
            logger.info("model detached: %s", name)

    def get(self, name: str) -> Optional[ModelPipeline]:
        return self.pipelines.get(name)

    def list_models(self) -> list[str]:
        return sorted(self.pipelines)


class ModelWatcher:
    """Attach/detach models from MODEL_ROOT watch events."""

    def __init__(self, runtime, manager: ModelManager,
                 stream_replay: bool = False, kv_economy: bool = False):
        self.runtime = runtime
        self.manager = manager
        #: crash-replayed streams (--stream-replay, default OFF): the
        #: generate PushRouter re-dispatches a mid-stream worker death
        #: to a survivor as prompt+emitted-tokens so the client stream
        #: continues uninterrupted (docs/operations.md)
        self.stream_replay = stream_replay
        #: the KV economy (--kv-economy, default OFF): KV-routed models
        #: get an EconomyPolicy — tier-discounted warmth scores plus
        #: per-prefix hot-KV migration (docs/operations.md "The KV
        #: economy"). Off keeps routing bit-identical to before.
        self.kv_economy = kv_economy
        #: started TierMaps, stopped alongside the watcher
        self._tier_maps: list = []
        self._task: Optional[asyncio.Task] = None
        #: model -> set of entry keys currently backing it
        self._entries: dict[str, set[str]] = {}
        #: fleet trace plane: this process's finished spans (frontend,
        #: router, kv.choose) + fleet events (shed episodes, stream
        #: replays, kv resyncs) ship to the metrics service on a 1 s
        #: cadence — the frontend has no metrics publish loop to ride
        self._shipper = None

    async def start(self) -> None:
        from dynamo_tpu.runtime.component import MODEL_ROOT
        from dynamo_tpu.telemetry.traceplane import TelemetryShipper

        self._shipper = TelemetryShipper(
            self.runtime.fabric, source="frontend"
        )
        self._shipper.start()
        watch = await self.runtime.fabric.watch_prefix(MODEL_ROOT + "/")
        self._task = asyncio.get_running_loop().create_task(self._pump(watch))

    async def _pump(self, watch) -> None:
        async for ev in watch:
            try:
                if ev.kind == "put":
                    await self._on_put(ev.key, ev.value)
                elif ev.kind == "reset":
                    # fabric session re-established: the server replays
                    # live entries as puts next. Forget entry->key
                    # bookkeeping so replays rebuild it; attached models
                    # stay up (their push routers keep serving) and
                    # truly-deleted entries detach on the next delete or
                    # when their instances prune.
                    self._entries.clear()
                else:
                    await self._on_delete(ev.key)
            except Exception:
                logger.exception("model watcher event failed for %s", ev.key)

    async def _on_put(self, key: str, value: bytes) -> None:
        entry = ModelEntry.unpack(value)
        keys = self._entries.setdefault(entry.model, set())
        keys.add(key)
        if self.manager.get(entry.model) is not None:
            return  # already attached; this is another worker for it
        card = await load_card(self.runtime.fabric, entry)
        ep = (
            self.runtime.namespace(entry.namespace)
            .component(entry.component)
            .endpoint(entry.endpoint)
        )
        mode = RouterMode(entry.router_mode)
        if mode == RouterMode.KV:
            from dynamo_tpu.kv_router import KvRouter

            src = await ep.instance_source()
            economy = None
            if self.kv_economy:
                from dynamo_tpu.kv_economy import (
                    EconomyPolicy, TierMap, cost_model_from_card,
                )

                tier_map = TierMap(self.runtime.fabric)
                await tier_map.start()
                self._tier_maps.append(tier_map)
                economy = EconomyPolicy(
                    cost_model_from_card(card), tier_map=tier_map
                )
            kv_router = KvRouter(
                self.runtime.fabric,
                entry.component,
                src,
                block_size=card.kv_page_size,
                salt=card.name,
                economy=economy,
            )
            await kv_router.start()
            router = PushRouter(
                src, ep.name, mode=mode, kv_chooser=kv_router.choose,
                replay=self.stream_replay,
            )
            self.manager.add(
                entry.model,
                router_pipeline(
                    card, router, kv_router=kv_router,
                    fabric=self.runtime.fabric,
                ),
            )
            return
        router = await ep.router(mode=mode, replay=self.stream_replay)
        self.manager.add(
            entry.model,
            router_pipeline(card, router, fabric=self.runtime.fabric),
        )

    async def _on_delete(self, key: str) -> None:
        for model, keys in list(self._entries.items()):
            if key in keys:
                keys.discard(key)
                if not keys:
                    del self._entries[model]
                    await self.manager.remove(model)
                return

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        for tm in self._tier_maps:
            try:
                await tm.stop()
            except Exception:
                logger.warning("tier map stop failed", exc_info=True)
        self._tier_maps.clear()
        if self._shipper is not None:
            try:
                await self._shipper.stop()
            except Exception:
                logger.warning("telemetry shipper stop failed", exc_info=True)
            self._shipper = None

"""`dynamo-tpu run` — the one-command launcher.

  dynamo-tpu run in=http out=jax model=llama3-1b            # single process
  dynamo-tpu run in=text out=echo                           # REPL chat
  dynamo-tpu run in=batch:prompts.jsonl out=jax model=tiny  # batch file
  dynamo-tpu run in=dyn out=jax model=llama3-8b --fabric host:port
                                                            # join as worker
  dynamo-tpu run in=http out=dyn --fabric host:port         # frontend only
  dynamo-tpu run in=http 'out=ext:python -m my_engine_shim' # subprocess
                                                            # engine harness

`out=ext:<command...>` runs the command as a supervised subprocess
speaking the external-engine wire protocol (docs/external_engines.md
"Level 2") — the reference's `dynamo-run in=http out=vllm` shape
(launch/dynamo-run/src/subprocess/vllm_inc.py). Quote the whole
`out=ext:...` token when the engine command takes flags that collide
with dynamo-tpu's own (e.g. --model).

(reference: `dynamo run in=<http|text|stdin|batch:f|dyn://...>
out=<engine>` — launch/dynamo-run/src/lib.rs:44, opt.rs:7.)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys
from typing import Optional

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.logging_config import configure_logging

logger = logging.getLogger(__name__)


def _engine_config(args, eos_token_ids: tuple = ()) -> EngineConfig:
    return EngineConfig(
        model=args.model,
        num_pages=args.num_pages,
        page_size=args.page_size,
        max_pages_per_seq=args.max_context // args.page_size,
        prefill_chunk=args.prefill_chunk,
        max_seqs=args.max_seqs,
        dtype=args.dtype,
        dp=args.dp,
        tp=args.tp,
        sp=getattr(args, "sp", 1),
        ep=getattr(args, "ep", 1),
        topology=getattr(args, "topology", "") or "",
        eos_token_ids=tuple(eos_token_ids) or (0,),
        host_kv_cache_bytes=getattr(args, "host_kv_bytes", 0),
        disk_kv_cache_bytes=getattr(args, "disk_kv_bytes", 0),
        disk_kv_cache_dir=getattr(args, "disk_kv_dir", None),
        spec_ngram=getattr(args, "spec_ngram", 0),
        spec_draft_model=getattr(args, "spec_draft", None),
        spec_draft_tokens=getattr(args, "spec_draft_tokens", 4),
        spec_draft_checkpoint=getattr(args, "spec_draft_checkpoint", None),
        max_waiting=getattr(args, "max_waiting", None),
        overlap_decode=getattr(args, "overlap_decode", True),
        mixed_steps=getattr(args, "mixed_steps", True),
        fleet_telemetry=getattr(args, "fleet_telemetry", True),
        flight_recorder=getattr(args, "flight_recorder", True),
        stall_watchdog=getattr(args, "stall_watchdog", True),
        stall_hard_deadline_s=getattr(args, "stall_hard_deadline", None),
        quantize=getattr(args, "quantize", None),
        kv_quantize=getattr(args, "kv_quantize", None),
        attention_impl=getattr(args, "attention_impl", "auto"),
        prefill_token_budget=getattr(args, "prefill_budget", None),
        prefill_budget_policy=getattr(args, "prefill_policy", "fixed"),
        prefill_budget_max=getattr(args, "prefill_budget_max", None),
        **(
            {"decode_steps": args.decode_steps}
            if getattr(args, "decode_steps", None) is not None
            else {}
        ),
        decode_kstep=getattr(args, "decode_kstep", 1),
    )


def _disagg_config(args):
    if not args.disagg:
        return None
    from dynamo_tpu.disagg import DisaggConfig

    return DisaggConfig(
        max_local_prefill_length=args.max_local_prefill,
        transfer_timeout_s=getattr(args, "transfer_timeout", 30.0),
    )


def _card(args):
    import os

    from dynamo_tpu.model_card import ModelDeploymentCard

    tokenizer = {"kind": "byte"}
    context_length = args.max_context
    eos: tuple[int, ...] = ()
    if args.tokenizer:
        tokenizer = {"kind": "hf", "path": args.tokenizer}
    elif args.model.endswith(".gguf") and os.path.isfile(args.model):
        # Serve the model's own embedded vocabulary + limits.
        from dynamo_tpu.gguf import read_gguf

        g = read_gguf(args.model)
        if g.tokenizer_vocab() is not None:
            tokenizer = {"kind": "gguf", "path": args.model}
            eos_id = g.tokenizer_vocab().get("eos_token_id")
            if eos_id is not None:
                eos = (int(eos_id),)
        context_length = min(context_length, g.context_length())
    elif os.path.isdir(args.model) and os.path.exists(
        os.path.join(args.model, "tokenizer_config.json")
    ):
        tokenizer = {"kind": "hf", "path": args.model}
    return ModelDeploymentCard(
        name=args.model,
        tokenizer=tokenizer,
        context_length=context_length,
        kv_page_size=args.page_size,
        **({"eos_token_ids": eos} if eos else {}),
    )


async def _make_local_pipeline(args):
    from dynamo_tpu.engine.async_engine import AsyncEngineRunner, EchoEngine
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.frontend.service import local_pipeline

    card = _card(args)
    if args.out == "echo":
        return local_pipeline(card, EchoEngine()), None
    if args.out == "mock":
        from dynamo_tpu.mocker import MockEngine

        return local_pipeline(card, MockEngine()), None
    if args.out.startswith("ext:"):
        from dynamo_tpu.external import SubprocessEngine

        engine = SubprocessEngine(args.ext_cmd, name="ext")
        await engine.start()
        return local_pipeline(card, engine), engine
    engine = JaxEngine(
        _engine_config(args, card.eos_token_ids),
        checkpoint_path=args.checkpoint,
    )
    runner = AsyncEngineRunner(engine)
    runner.start()
    return local_pipeline(card, runner), runner


async def _stop_engine(runner) -> None:
    """AsyncEngineRunner.stop() is sync; SubprocessEngine.stop() is a
    coroutine — stop either."""
    if runner is None:
        return
    res = runner.stop()
    if asyncio.iscoroutine(res):
        await res


async def _run_http(args) -> None:
    from dynamo_tpu.frontend import HttpService, ModelManager
    from dynamo_tpu.frontend.service import ModelWatcher

    manager = ModelManager()
    runner = None
    watcher = None
    if args.out == "dyn":
        from dynamo_tpu.runtime import DistributedRuntime

        rt = await DistributedRuntime.create(args.fabric)
        watcher = ModelWatcher(
            rt, manager,
            stream_replay=getattr(args, "stream_replay", False),
            kv_economy=getattr(args, "kv_economy", False),
        )
        await watcher.start()
    else:
        pipeline, runner = await _make_local_pipeline(args)
        manager.add(args.model, pipeline)
    svc = HttpService(
        manager, host=args.host, port=args.port,
        max_inflight=getattr(args, "max_inflight", None),
        shed_burn_threshold=getattr(args, "shed_burn_threshold", None),
        request_timeout_s=getattr(args, "request_timeout", None),
    )
    await svc.start()
    print(f"listening on http://{args.host}:{svc.port}/v1", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await svc.stop()
        await _stop_engine(runner)


async def _run_text(args) -> None:
    from dynamo_tpu.protocols.openai import ChatCompletionRequest, ChatMessage

    pipeline, runner = await _make_local_pipeline(args)
    print(f"chat with {args.model} (out={args.out}); /quit to exit", flush=True)
    history: list[ChatMessage] = []
    try:
        while True:
            try:
                line = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: input("> ")
                )
            except EOFError:
                break
            if line.strip() in ("/quit", "/exit"):
                break
            history.append(ChatMessage(role="user", content=line))
            req = ChatCompletionRequest(
                model=args.model, messages=history, stream=True,
                max_tokens=args.max_tokens,
            )
            text = []
            async for chunk in pipeline.chat_stream(req):
                for c in chunk.choices:
                    if c.delta.content:
                        text.append(c.delta.content)
                        print(c.delta.content, end="", flush=True)
            print()
            history.append(ChatMessage(role="assistant", content="".join(text)))
    finally:
        await _stop_engine(runner)


async def _run_batch(args, path: str) -> None:
    from dynamo_tpu.protocols.openai import ChatCompletionRequest, ChatMessage

    pipeline, runner = await _make_local_pipeline(args)
    try:
        with open(path) as f:
            lines = [json.loads(l) for l in f if l.strip()]
        for i, item in enumerate(lines):
            prompt = item.get("prompt") or item.get("text") or ""
            req = ChatCompletionRequest(
                model=args.model,
                messages=[ChatMessage(role="user", content=prompt)],
                stream=True,
                max_tokens=item.get("max_tokens", args.max_tokens),
            )
            text = []
            async for chunk in pipeline.chat_stream(req):
                for c in chunk.choices:
                    if c.delta.content:
                        text.append(c.delta.content)
            print(json.dumps({"index": i, "prompt": prompt, "output": "".join(text)}), flush=True)
    finally:
        await _stop_engine(runner)


def _run_spmd_follower(args) -> None:
    """Follower host of a cross-host SPMD serving group: build the
    identical engine replica and block in the lockstep serve loop."""
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.spmd import SpmdDriver

    card = _card(args)
    engine = JaxEngine(
        _engine_config(args, card.eos_token_ids),
        checkpoint_path=args.checkpoint,
    )
    drv = SpmdDriver(engine)
    if drv.is_leader:  # pragma: no cover — arg-mismatch guard
        raise RuntimeError("follower entry reached on process 0")
    print(
        f"spmd follower {args.host_id} up (model={args.model})", flush=True
    )
    drv.serve()
    print(f"spmd follower {args.host_id} released", flush=True)


async def _run_worker(args) -> None:
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.worker import Worker

    rt = await DistributedRuntime.create(args.fabric)
    # progress line BEFORE engine construction: lets a supervisor
    # distinguish "loading/compiling" (slow but alive) from a wedged
    # device tunnel (this line never appears)
    print(f"worker booting (model={args.model}, role={args.role})",
          flush=True)
    if args.role == "prefill":
        from dynamo_tpu.disagg.prefill_worker import PrefillWorker

        pw = PrefillWorker(
            rt, _engine_config(args), namespace=args.namespace,
            checkpoint_path=args.checkpoint,
            advertise_host=args.host,
        )
        await pw.start()
        print(f"prefill worker {pw.instance_id} up (model={args.model})", flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await pw.stop()
        return
    external = None
    if args.out.startswith("ext:"):
        from dynamo_tpu.external import SubprocessEngine

        external = SubprocessEngine(args.ext_cmd, name="ext")
        await external.start()
    mock_args = None
    if args.out == "mock" and getattr(args, "mock_step", None):
        from dynamo_tpu.mocker import MockEngineArgs

        mock_args = MockEngineArgs(
            page_size=args.page_size,
            salt=args.model,
            decode_s_per_step=args.mock_step,
        )
    worker = Worker(
        rt,
        _card(args),
        engine_config=(
            _engine_config(args, _card(args).eos_token_ids)
            if args.out == "jax"
            else None
        ),
        engine_kind="external" if external is not None else args.out,
        engine=external,
        mock_args=mock_args,
        namespace=args.namespace,
        component=args.component,
        endpoint=args.endpoint,
        checkpoint_path=args.checkpoint,
        router_mode=args.router_mode,
        enable_disagg=args.disagg,
        disagg_config=_disagg_config(args),
        kv_remote=getattr(args, "kv_remote", False),
        echo_delay=getattr(args, "echo_delay", 0.0),
        advertise_host=args.host,
        drain_budget_s=getattr(args, "drain_budget", 30.0),
        kv_sequencing=getattr(args, "kv_sequencing", True),
        kv_economy=getattr(args, "kv_economy", False),
    )
    await worker.start()
    print(f"worker {worker.instance_id} up (model={args.model})", flush=True)
    # SIGTERM = graceful drain (docs/operations.md "Overload & draining"):
    # deregister, finish in-flight within --drain-budget, exit 0. SIGINT
    # keeps its fast KeyboardInterrupt teardown for interactive use.
    import signal as _signal

    term = asyncio.Event()
    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(_signal.SIGTERM, term.set)
    except (NotImplementedError, RuntimeError):  # non-main thread / win
        pass
    try:
        waits = [
            asyncio.ensure_future(term.wait()),
            asyncio.ensure_future(worker.drained.wait()),
        ]
        try:
            # wakes on SIGTERM or on an admin-triggered drain completing
            await asyncio.wait(waits, return_when=asyncio.FIRST_COMPLETED)
        finally:
            for w in waits:
                w.cancel()
        if not worker.drained.is_set():
            print(f"worker {worker.instance_id} draining", flush=True)
            await worker.drain()
        print(f"worker {worker.instance_id} drained; exiting", flush=True)
    finally:
        await worker.stop()
        if external is not None:
            await external.stop()


async def _run_ctl(args) -> None:
    """llmctl parity (reference launch/llmctl/src/main.rs:114-139): list,
    add, remove model registrations against the fabric store."""
    from dynamo_tpu.model_card import (
        ModelDeploymentCard,
        ModelEntry,
        model_key,
        register_llm,
    )
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.component import INSTANCE_ROOT, MODEL_ROOT, Instance

    rt = await DistributedRuntime.create(args.fabric)
    try:
        fabric = rt.fabric
        if args.ctl_cmd == "list":
            models = await fabric.get_prefix(MODEL_ROOT + "/")
            print(f"models ({len(models)}):")
            for key, raw in sorted(models.items()):
                try:
                    e = ModelEntry.unpack(raw)
                    print(
                        f"  {e.model}  ->  {e.namespace}/{e.component}/"
                        f"{e.endpoint}  (router={e.router_mode})  [{key}]"
                    )
                except Exception:
                    print(f"  {key}  (unreadable)")
            instances = await fabric.get_prefix(INSTANCE_ROOT + "/")
            print(f"instances ({len(instances)}):")
            for key, raw in sorted(instances.items()):
                try:
                    inst = Instance.unpack(raw)
                    print(
                        f"  {inst.instance_id}  {inst.namespace}/"
                        f"{inst.component}/{inst.endpoint}  at "
                        f"{inst.host}:{inst.port}"
                    )
                except Exception:
                    print(f"  {key}  (unreadable)")
        elif args.ctl_cmd == "add":
            from dynamo_tpu.model_card import CARD_OBJ_PREFIX

            card = ModelDeploymentCard(
                name=args.model, tokenizer={"kind": "byte"}, context_length=4096
            )
            # Never clobber a live model's real card with this placeholder.
            existing = await fabric.obj_get(CARD_OBJ_PREFIX + args.model)
            await register_llm(
                fabric, card, args.namespace, args.component, args.endpoint,
                router_mode=args.router_mode,
                publish_card=existing is None,
            )
            print(f"registered {args.model} -> "
                  f"{args.namespace}/{args.component}/{args.endpoint}"
                  + (" (kept existing card)" if existing is not None else ""))
        elif args.ctl_cmd == "remove":
            base = model_key(args.model)
            keys = await fabric.get_prefix(base)
            n = 0
            for key in keys:
                # Exact model only: 'llama3' must not remove 'llama3-70b'.
                if key == base or key.startswith(base + "/"):
                    if await fabric.delete(key):
                        n += 1
            print(f"removed {n} registration(s) for {args.model}")
    finally:
        await rt.close()


async def _run_serve(args) -> None:
    """Orchestrate a service graph: one OS process per replica (the
    reference's circus-arbiter local serving, sdk cli/serving.py:152)."""
    import subprocess

    from dynamo_tpu.sdk.config import load_config, replica_count
    from dynamo_tpu.sdk.decorators import service_meta
    from dynamo_tpu.sdk.graph import discover_graph
    from dynamo_tpu.sdk.serving import resolve_service

    root = resolve_service(args.graph)
    config = load_config(args.config) if args.config else {}

    fabric_server = None
    fabric_addr = args.fabric
    if fabric_addr is None:
        from dynamo_tpu.runtime.fabric import FabricServer

        fabric_server = FabricServer(port=args.fabric_port)
        await fabric_server.start()
        fabric_addr = fabric_server.address
        print(f"fabric on {fabric_addr}", flush=True)

    # SIGTERM/SIGINT must run the cleanup below, or every replica (and the
    # locally spawned fabric) outlives the orchestrator.
    import signal as _signal

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)

    procs: list[tuple[str, "subprocess.Popen"]] = []
    child_died = False
    try:
        for cls in discover_graph(root):
            meta = service_meta(cls)
            svc_cfg = config.get(meta.name, {})
            replicas = replica_count(svc_cfg, meta.workers)
            spec = f"{cls.__module__}:{cls.__name__}"
            for _ in range(replicas):
                cmd = [
                    sys.executable, "-m", "dynamo_tpu.sdk.serving", spec,
                    "--fabric", fabric_addr,
                ]
                if args.config:
                    cmd += ["-f", args.config]
                print(f"spawning {meta.name}: {' '.join(cmd)}", flush=True)
                procs.append((meta.name, subprocess.Popen(cmd)))
        print(f"graph up: {len(procs)} service processes", flush=True)
        # Supervise: a dead child means a degraded graph — tear down and
        # exit nonzero so the outer supervisor (systemd/k8s) restarts us.
        while not stop.is_set():
            for name, p in procs:
                code = p.poll()
                if code is not None:
                    print(
                        f"service {name} (pid {p.pid}) exited with {code}; "
                        "stopping graph", file=sys.stderr, flush=True,
                    )
                    child_died = True
                    stop.set()
                    break
            try:
                await asyncio.wait_for(stop.wait(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
    finally:
        for _, p in procs:
            if p.poll() is None:
                p.terminate()
        for _, p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if fabric_server is not None:
            await fabric_server.stop()
        if child_died:
            sys.exit(1)


async def _run_metrics(args) -> None:
    from dynamo_tpu.metrics_service import MetricsService
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create(args.fabric)
    svc = MetricsService(
        rt.fabric, component=args.component, host=args.host, port=args.port,
        trace_sample_rate=getattr(args, "trace_sample_rate", None),
        trace_window_s=getattr(args, "trace_window", 2.0),
        trace_keep=getattr(args, "trace_keep", 512),
    )
    await svc.start()
    print(
        f"metrics service on {args.host}:{svc.port} "
        f"(/metrics, /v1/fleet, /v1/fleet/events, /v1/traces)",
        flush=True,
    )
    try:
        await asyncio.Event().wait()
    finally:
        await svc.stop()
        await rt.close()


async def _run_planner(args) -> None:
    import shlex

    from dynamo_tpu.planner import (
        ClosedLoopPlanner,
        ControlConfig,
        ControlRunner,
        LoadPlanner,
        LocalConnector,
        PerfInterpolator,
        PlannerConfig,
        SlaPlanner,
    )
    from dynamo_tpu.planner.planner import PlannerRunner, SlaTargets
    from dynamo_tpu.planner.service import (
        FleetFlipper,
        FleetHandover,
        FleetObserver,
        rolling_upgrade,
    )
    from dynamo_tpu.runtime import DistributedRuntime

    cfg = PlannerConfig(
        interval_s=args.interval,
        min_decode=args.min_decode,
        max_decode=args.max_decode,
        min_prefill=args.min_prefill,
        max_prefill=args.max_prefill,
    )
    if args.mode == "closed":
        planner = ClosedLoopPlanner(
            ControlConfig(
                interval_s=args.interval,
                min_decode=args.min_decode,
                max_decode=args.max_decode,
                min_prefill=args.min_prefill,
                max_prefill=args.max_prefill,
                ttft_target_ms=args.ttft_ms,
                itl_target_ms=args.itl_ms,
                cooldown_s=args.cooldown,
                flip_cooldown_s=args.flip_cooldown,
                max_actions_per_tick=args.max_actions,
                allow_flips=args.flip,
            )
        )
    elif args.mode == "sla":
        if not args.perf_table:
            print("--perf-table is required in SLA mode", file=sys.stderr)
            sys.exit(2)
        with open(args.perf_table) as f:
            table = json.load(f)
        if table.get("configs"):
            # Multi-(tp,dp) table from the profiler sweep: re-select
            # against the PLANNER's targets (which may differ from the
            # profile-time SLA) on per-chip SLA-feasible rate.
            from dynamo_tpu.planner.perf_model import select_parallel_config

            chosen = select_parallel_config(
                table["configs"], args.ttft_ms, args.itl_ms
            )
            table = dict(table, **{
                "ttft_vs_rate": chosen["ttft_vs_rate"],
                "itl_vs_rate": chosen["itl_vs_rate"],
            })
            print(
                f"planner: perf table selects tp={chosen['tp']} "
                f"dp={chosen['dp']} for ttft<={args.ttft_ms}ms "
                f"itl<={args.itl_ms}ms",
                flush=True,
            )
        planner = SlaPlanner(
            cfg,
            SlaTargets(ttft_ms=args.ttft_ms, itl_ms=args.itl_ms),
            ttft_vs_rate=PerfInterpolator(*zip(*table["ttft_vs_rate"])),
            itl_vs_rate=PerfInterpolator(*zip(*table["itl_vs_rate"])),
        )
    else:
        planner = LoadPlanner(cfg)

    extra = shlex.split(args.worker_args)

    def spawn_cmd(role: str) -> list[str]:
        cmd = [
            sys.executable, "-m", "dynamo_tpu.cli.run", "run",
            "in=dyn", "out=jax",
            "--fabric", args.fabric,
            "--role", role,
            "--namespace", args.namespace,
            "--component", args.component if role == "decode" else "prefill",
            "--model", args.model,
        ]
        if args.checkpoint:
            cmd += ["--checkpoint", args.checkpoint]
        return cmd + extra

    rt = await DistributedRuntime.create(args.fabric)
    observer = FleetObserver(
        rt, namespace=args.namespace, decode_component=args.component
    )
    await observer.start()
    if args.connector == "kube":
        from dynamo_tpu.operator.kube import InClusterKube
        from dynamo_tpu.planner.kube_connector import KubeConnector

        role_services = dict(kv.split("=", 1) for kv in args.role_service)
        connector = KubeConnector(
            InClusterKube(),
            cr_name=args.cr_name,
            namespace=args.k8s_namespace,
            role_services=role_services,
        )
    else:
        connector = LocalConnector(spawn_cmd)
    if getattr(args, "rolling_upgrade", False):
        # one sweep, then exit: replace every worker one at a time with
        # live KV handover (docs/operations.md "Rolling upgrades &
        # worker handover")
        print(
            f"rolling upgrade starting (cooldown="
            f"{args.upgrade_cooldown}s)",
            flush=True,
        )
        try:
            # give the instance watches a moment to prime
            await asyncio.sleep(0.5)
            summary = await rolling_upgrade(
                observer, connector, FleetHandover(observer),
                cooldown_s=args.upgrade_cooldown,
            )
            print(json.dumps({"rolling_upgrade": summary}), flush=True)
            failed = any(v["failed"] for v in summary.values())
            if failed:
                sys.exit(3)
        finally:
            await observer.stop()
            await rt.close()
        return
    if args.mode == "closed":
        from dynamo_tpu.subjects import PLANNER_SUBJECT
        from dynamo_tpu.telemetry.traceplane import TelemetryShipper

        async def status_fn(frame: dict) -> None:
            await rt.fabric.publish(PLANNER_SUBJECT, frame)

        # fleet event timeline: planner decisions buffered by the
        # ControlRunner ship to fleet.events on a 1 s cadence
        shipper = TelemetryShipper(rt.fabric, source="planner")
        shipper.start()
        economy = None
        if getattr(args, "kv_economy", False):
            from dynamo_tpu.kv_economy import cost_model_from_card
            from dynamo_tpu.planner.service import FleetKvEconomy

            # no card in the planner process — the 1B-class shape
            # defaults; only the flops/byte RATIO gates decisions
            economy = FleetKvEconomy(observer, cost_model_from_card(None))
        runner = ControlRunner(
            planner, connector, observer.observe,
            flipper=FleetFlipper(observer) if args.flip else None,
            handover=(
                FleetHandover(observer, economy=economy)
                if getattr(args, "handover", True)
                else None
            ),
            prewarm=economy.prewarm if economy is not None else None,
            status_fn=status_fn,
            # HOLD while the control plane is degraded (no broker):
            # signals are frozen and actuation would fly blind
            degraded_fn=lambda: bool(
                getattr(rt.fabric, "degraded", False)
            ),
        )
    else:
        shipper = None
        runner = PlannerRunner(planner, connector, observer.observe)
    print(
        f"planner up (mode={args.mode}, connector={args.connector}, "
        f"interval={args.interval}s)",
        flush=True,
    )
    try:
        await runner.run()
    finally:
        if hasattr(connector, "stop_all"):
            connector.stop_all()
        if shipper is not None:
            await shipper.stop()
        await observer.stop()
        await rt.close()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamo-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    runp = sub.add_parser("run", help="serve / chat / batch / worker")
    runp.add_argument("io", nargs="*", help="in=<http|text|batch:file|dyn> out=<jax|echo|mock|dyn>")
    runp.add_argument("--model", default="tiny")
    runp.add_argument("--checkpoint", default=None, help="local HF checkpoint dir")
    runp.add_argument("--tokenizer", default=None, help="local tokenizer dir")
    runp.add_argument(
        "--fabric", default=None,
        help="fabric broker address(es): host:port, or a comma list "
             "a:4222,b:4222 for an HA pair — the client rotates through "
             "them, follows NotPrimary redirects, and rides out a "
             "broker failover (docs/operations.md 'Control-plane HA')",
    )
    runp.add_argument("--host", default="127.0.0.1")
    runp.add_argument("--port", type=int, default=8080)
    runp.add_argument(
        "--router-mode", default="round_robin", dest="router_mode",
        choices=["round_robin", "random", "kv"],
        help="how frontends route to this worker's endpoint",
    )
    runp.add_argument(
        "--role", default="decode", choices=["decode", "prefill"],
        help="worker role when in=dyn (prefill = queue consumer)",
    )
    runp.add_argument(
        "--disagg", action="store_true",
        help="decode worker: send long prefills to the prefill fleet",
    )
    runp.add_argument(
        "--max-local-prefill", type=int, default=512, dest="max_local_prefill",
        help="uncached prefill tokens above which prefill goes remote",
    )
    runp.add_argument(
        "--echo-delay", type=float, default=0.0, dest="echo_delay",
        help="out=echo: seconds per emitted token (stream-timing tests)",
    )
    runp.add_argument(
        "--mock-step", type=float, default=None, dest="mock_step",
        help="out=mock (in=dyn): simulated engine step seconds — slows "
             "the mock's batched decode tick for stream-timing/chaos "
             "tests (default: MockEngineArgs.decode_s_per_step)",
    )
    runp.add_argument(
        "--max-waiting", type=int, default=None, dest="max_waiting",
        help="bounded admission: cap on the engine's waiting queue — a "
             "full queue answers 'overloaded' (HTTP 429 + Retry-After at "
             "the frontend) instead of queueing forever (default: "
             "unbounded; docs/operations.md 'Overload & draining')",
    )
    runp.add_argument(
        "--max-inflight", type=int, default=None, dest="max_inflight",
        help="frontend admission cap: reject with 429 + Retry-After once "
             "this many requests are in flight (default: unbounded)",
    )
    runp.add_argument(
        "--request-timeout", type=float, default=None,
        dest="request_timeout", metavar="SECONDS",
        help="server-default end-to-end deadline; per-request "
             "x-request-timeout overrides it. Expired requests are "
             "dropped before admission and error-finished mid-decode "
             "(default: none)",
    )
    runp.add_argument(
        "--shed-burn-threshold", type=float, default=None,
        dest="shed_burn_threshold", metavar="RATE",
        help="SLO-burn load shedder: when the endpoint's short-window "
             "burn rate exceeds this (1.0 = spending the error budget "
             "exactly), shed best-effort requests (x-priority < 1) with "
             "probability ramping to 100%% at 2x the threshold "
             "(default: off)",
    )
    runp.add_argument(
        "--stream-replay", action="store_true", dest="stream_replay",
        help="crash-replayed streams (frontend, in=http out=dyn): when "
             "a worker dies mid-stream, re-dispatch the request to a "
             "survivor as prompt + tokens-emitted-so-far — the client "
             "stream continues with no duplicate and no missing token "
             "(bit-identical for greedy; sampled streams resume under a "
             "derived seed). Default off; router behavior is identical "
             "to before when off",
    )
    runp.add_argument(
        "--drain-budget", type=float, default=30.0, dest="drain_budget",
        metavar="SECONDS",
        help="graceful drain budget: on SIGTERM (or POST /v1/admin/"
             "drain) the worker deregisters, finishes in-flight "
             "requests up to this long, then exits 0",
    )
    runp.add_argument(
        "--no-kv-sequencing", action="store_false", dest="kv_sequencing",
        default=True,
        help="disable KV event sequence stamping + the rolling block-set "
             "digest (docs/operations.md 'KV index consistency'): the "
             "event wire reverts to the pre-sequencing format and "
             "indexers lose gap/drift detection for this worker",
    )
    runp.add_argument(
        "--transfer-timeout", type=float, default=30.0,
        dest="transfer_timeout",
        help="seconds to wait for the remote-prefill KV landing before "
             "falling back to local prefill",
    )
    runp.add_argument(
        "--trace", action="store_true",
        help="enable distributed request tracing (in-memory ring served "
             "at /v1/traces; equivalently DYNTPU_TRACING=1 or "
             "DYNTPU_TRACE_RING=<n> — docs/observability.md)",
    )
    runp.add_argument(
        "--log-file", default=None, dest="log_file", metavar="NAME|PATH",
        help="also log (JSONL) to this file; a bare name lands in "
             "DYNTPU_LOG_DIR (default artifacts/log), never the CWD",
    )
    runp.add_argument("--namespace", default="dynamo")
    runp.add_argument("--component", default="backend")
    runp.add_argument("--endpoint", default="generate")
    runp.add_argument("--num-pages", type=int, default=512, dest="num_pages")
    runp.add_argument("--page-size", type=int, default=64, dest="page_size")
    runp.add_argument(
        "--decode-steps", type=int, default=None, dest="decode_steps",
        help="decode steps fused per dispatch (host sync per K tokens/seq;"
             " raise to ~64 on a remote/tunneled TPU where the sync RTT"
             " dominates a step). Default: engine default (8)",
    )
    runp.add_argument(
        "--decode-kstep", type=int, default=1, dest="decode_kstep",
        help="fuse K decode iterations into ONE on-device program per "
             "dispatch: sampling, stop checks, and paged-KV writes run "
             "on device, the host syncs once per K tokens (vLLM's "
             "--num-scheduler-steps analogue). 1 (default) = classic "
             "per-step loop, bit-identical streams; K>1 stays bit-exact "
             "(including on multi-host SPMD meshes) and auto-disables "
             "under speculation and logprobs rows",
    )
    runp.add_argument(
        "--host-kv-bytes", type=int, default=0, dest="host_kv_bytes",
        help="KVBM G2: host-DRAM KV tier byte budget (0 = off); evicted "
             "device pages offload here and onboard on prefix hit",
    )
    runp.add_argument(
        "--disk-kv-bytes", type=int, default=0, dest="disk_kv_bytes",
        help="KVBM G3: disk KV tier byte budget (0 = off)",
    )
    runp.add_argument(
        "--disk-kv-dir", default=None, dest="disk_kv_dir",
        help="directory for the disk KV tier (required with --disk-kv-bytes)",
    )
    runp.add_argument(
        "--kv-remote", action="store_true", dest="kv_remote",
        help="KVBM G4: serve KV blocks to peers and onboard prefixes a "
             "peer already computed (cross-worker, over the transfer plane)",
    )
    runp.add_argument(
        "--kv-economy", action="store_true", dest="kv_economy",
        help="the KV economy (docs/operations.md 'The KV economy'): on "
             "a frontend, KV routing scores lower-tier residency at a "
             "promotion-cost discount and migrates hot prefixes to the "
             "chosen worker when the prefill flops saved beat the bytes "
             "moved; on a worker, publishes tier residency hints, "
             "serves migrate_prefix, and demotes cold pages under HBM "
             "watermark pressure. Default off; routing and the wire are "
             "bit-identical to before when off",
    )
    runp.add_argument(
        "--spec-ngram", type=int, default=0, dest="spec_ngram",
        help="speculative decoding: draft tokens per step proposed by "
             "prompt lookup and verified in one forward pass (0 = off)",
    )
    runp.add_argument(
        "--spec-draft", default=None, dest="spec_draft",
        help="draft-model speculative decoding: a small same-family "
             "model (e.g. llama3-draft for llama3-1b/8b targets; must "
             "share the target's vocabulary) proposes greedy drafts "
             "verified + accepted ON DEVICE per decode step — bit-exact "
             "greedy, exact rejection sampling for temperature>0. "
             "Composes with the overlap pipeline and mixed steps "
             "(unlike --spec-ngram)",
    )
    runp.add_argument(
        "--spec-draft-tokens", type=int, default=4,
        dest="spec_draft_tokens",
        help="drafts proposed and verified per spec step (with "
             "--spec-draft; default 4)",
    )
    runp.add_argument(
        "--spec-draft-checkpoint", default=None,
        dest="spec_draft_checkpoint",
        help="checkpoint dir for the draft weights (default: the draft "
             "model's own default checkpoint, else random init)",
    )
    runp.add_argument(
        "--no-overlap-decode", action="store_false", dest="overlap_decode",
        default=True,
        help="disable the overlapped decode loop (speculative next-step "
             "dispatch with one-step-lagged host readback; on by default "
             "including multi-host SPMD, auto-off with --spec-ngram)",
    )
    runp.add_argument(
        "--no-mixed-steps", action="store_false", dest="mixed_steps",
        default=True,
        help="disable stall-free mixed prefill+decode steps (one fused "
             "dispatch carrying a bounded prefill chunk plus the decode "
             "batch, so decodes emit a token every step while a prompt "
             "burst drains; on by default including multi-host SPMD, "
             "auto-off with --spec-ngram)",
    )
    runp.add_argument(
        "--no-fleet-telemetry", action="store_false",
        dest="fleet_telemetry", default=True,
        help="disable the live fleet telemetry plane (worker SLO "
             "sketches, live MFU gauge, fleet-frame publishing; on by "
             "default — host-side metrics only, the token path is "
             "identical either way; docs/observability.md)",
    )
    runp.add_argument(
        "--no-flight-recorder", action="store_false",
        dest="flight_recorder", default=True,
        help="disable the per-step flight recorder (bounded ring served "
             "at /v1/debug/flight and shipped in metrics frames; on by "
             "default, <1%% overhead, host-side only — "
             "docs/observability.md 'Debugging a slow or stuck worker')",
    )
    runp.add_argument(
        "--no-stall-watchdog", action="store_false",
        dest="stall_watchdog", default=True,
        help="disable the per-request stall watchdog (structured "
             "diagnosis of wedged streams: flight window + thread "
             "stacks + trace ids, dynamo_tpu_stalls_total{cause})",
    )
    runp.add_argument(
        "--stall-hard-deadline", type=float, default=None,
        dest="stall_hard_deadline", metavar="SECONDS",
        help="error-finish a stream stalled past this many seconds "
             "instead of hanging the client (default: diagnose-only)",
    )
    runp.add_argument(
        "--quantize", default=None, choices=["int8"],
        help="weight-only quantization (per-output-channel int8 scales)",
    )
    runp.add_argument(
        "--kv-quantize", default=None, choices=["int8", "fp8"],
        dest="kv_quantize",
        help="KV-cache page quantization: pages store int8 (or fp8) rows "
        "with per-token f32 scales, dequantized inside the Pallas "
        "page-walk kernels — halves KV HBM traffic and ~doubles "
        "effective cache capacity (docs/engine.md 'Quantized KV pages')",
    )
    runp.add_argument(
        "--attention-impl", default="auto", dest="attention_impl",
        choices=["auto", "xla", "pallas", "hybrid"],
        help="decode attention kernels (auto = pallas on TPU, else xla; "
        "hybrid = pallas under large-batch XLA-gather fallback)",
    )
    runp.add_argument("--max-context", type=int, default=4096, dest="max_context")
    runp.add_argument("--prefill-chunk", type=int, default=512, dest="prefill_chunk")
    runp.add_argument(
        "--prefill-budget", type=int, default=None, dest="prefill_budget",
        help="prefill tokens per step across sequences (default 4x "
        "prefill-chunk); the saturation-TTFT knob (docs/PERF.md)",
    )
    runp.add_argument(
        "--prefill-policy", default="fixed", dest="prefill_policy",
        choices=["fixed", "adaptive"],
        help="adaptive grows the step budget with the un-prefilled "
        "backlog (to 4x the budget) so arrival bursts drain in O(1) "
        "dispatches; fixed always spends at most --prefill-budget",
    )
    runp.add_argument(
        "--prefill-budget-max", type=int, default=None,
        dest="prefill_budget_max",
        help="adaptive-policy ceiling (default 4x the budget): bounds "
        "the worst-case single prefill dispatch = the longest decode "
        "stall (ITL spike) a running sequence can observe",
    )
    runp.add_argument("--max-seqs", type=int, default=32, dest="max_seqs")
    runp.add_argument("--max-tokens", type=int, default=256, dest="max_tokens")
    runp.add_argument("--dtype", default="bfloat16")
    runp.add_argument("--dp", type=int, default=1)
    runp.add_argument("--tp", type=int, default=1)
    runp.add_argument(
        "--sp", type=int, default=1,
        help="sequence-parallel devices: long prefills use ring attention",
    )
    runp.add_argument(
        "--ep", type=int, default=1,
        help="expert-parallel devices (MoE models shard experts over them)",
    )
    runp.add_argument(
        "--topology", default="",
        help="combined mesh layout 'tp=N,dp=M[,ep=K][,sp=J]' — overrides "
             "the individual --dp/--tp/--sp/--ep flags; the product must "
             "match the device count (docs/migrating.md)",
    )
    runp.add_argument(
        "--coordinator", default=None,
        help="multi-host: coordinator host:port (same on every host)",
    )
    runp.add_argument(
        "--num-hosts", type=int, default=1, dest="num_hosts",
        help="multi-host: total participating host processes",
    )
    runp.add_argument(
        "--host-id", type=int, default=0, dest="host_id",
        help="multi-host: this process's rank (0..num-hosts-1)",
    )

    fabricp = sub.add_parser("fabric", help="start the fabric server")
    fabricp.add_argument("--host", default="127.0.0.1")
    fabricp.add_argument("--port", type=int, default=4222)
    fabricp.add_argument(
        "--persist-dir", default=None, dest="persist_dir",
        help="WAL directory: state survives server restarts (and, with "
             "--standby-of, makes a promotion's fence bump durable)",
    )
    fabricp.add_argument(
        "--standby-of", default=None, dest="standby_of", metavar="ADDR",
        help="control-plane HA: run as the WARM STANDBY of the primary "
             "at host:port — bootstrap from its snapshot, tail its "
             "journal, answer clients NotPrimary+redirect, and promote "
             "when it is unreachable past --detector-budget "
             "(docs/operations.md 'Control-plane HA')",
    )
    fabricp.add_argument(
        "--peer", action="append", default=[], metavar="ADDR",
        help="other broker addresses (repeatable). On startup a primary "
             "defers to any peer serving at a higher fence instead of "
             "split-braining — give the restarted old primary its "
             "standby's address",
    )
    fabricp.add_argument(
        "--detector-budget", type=float, default=3.0,
        dest="detector_budget", metavar="SECONDS",
        help="standby: promote after the primary has been unreachable "
             "this long (default 3.0)",
    )
    fabricp.add_argument(
        "--no-auto-promote", action="store_false", dest="auto_promote",
        default=True,
        help="standby: never promote on its own — only an explicit "
             "`run fabric --promote` / repl.promote admin op",
    )
    fabricp.add_argument(
        "--promote", default=None, metavar="ADDR",
        help="do not start a broker: tell the STANDBY at host:port to "
             "promote NOW, print its reply, and exit (the manual "
             "failover drill)",
    )

    ctlp = sub.add_parser(
        "ctl", help="inspect/edit model + instance registrations (llmctl)"
    )
    ctlp.add_argument("--fabric", required=True, help="fabric host:port")
    ctl_sub = ctlp.add_subparsers(dest="ctl_cmd", required=True)
    ctl_sub.add_parser("list", help="list models and live instances")
    addp = ctl_sub.add_parser("add", help="register a model entry")
    addp.add_argument("model")
    addp.add_argument("--namespace", default="dynamo")
    addp.add_argument("--component", default="backend")
    addp.add_argument("--endpoint", default="generate")
    addp.add_argument(
        "--router-mode", default="round_robin", dest="router_mode",
        choices=["round_robin", "random", "kv"],
    )
    rmp = ctl_sub.add_parser("remove", help="remove a model's registrations")
    rmp.add_argument("model")

    servep = sub.add_parser("serve", help="serve a service graph (SDK DSL)")
    servep.add_argument("graph", help="pkg.module:RootService")
    servep.add_argument("-f", "--config", default=None, help="YAML config")
    servep.add_argument(
        "--fabric", default=None,
        help="existing fabric host:port (default: spawn one locally)",
    )
    servep.add_argument(
        "--fabric-port", type=int, default=4222, dest="fabric_port",
        help="port for the locally spawned fabric",
    )

    buildp = sub.add_parser(
        "build", help="freeze a service graph into a build manifest"
    )
    buildp.add_argument("graph", help="pkg.module:RootService")
    buildp.add_argument("-f", "--config", default=None, help="YAML config")
    buildp.add_argument("-o", "--output", default="dist", help="output dir")
    buildp.add_argument("--image", default="dynamo-tpu:latest")

    deployp = sub.add_parser(
        "deploy", help="render Kubernetes manifests for a graph"
    )
    deployp.add_argument("graph", help="pkg.module:RootService")
    deployp.add_argument("-f", "--config", default=None, help="YAML config")
    deployp.add_argument("-o", "--output", default="dist", help="output dir")
    deployp.add_argument("--image", default="dynamo-tpu:latest")
    deployp.add_argument(
        "--fabric-host", default="dynamo-fabric", dest="fabric_host",
        help="k8s service name for the fabric control plane",
    )
    deployp.add_argument(
        "--cr", action="store_true",
        help="emit a DynamoGraphDeployment custom resource (for the "
             "operator) instead of raw Deployments/Services",
    )
    deployp.add_argument(
        "--fabric-external", action="store_true", dest="fabric_external",
        help="with --cr: the fabric at --fabric-host is platform-managed "
             "(helm chart); the operator won't render a per-graph fabric",
    )
    deployp.add_argument(
        "--name", default=None,
        help="CR name with --cr (default: derived from the root service)",
    )

    operp = sub.add_parser(
        "operator", help="run the Kubernetes operator (reconciles "
                         "DynamoGraphDeployments; in-cluster credentials)"
    )
    operp.add_argument("--namespace", default="default")
    operp.add_argument("--interval", type=float, default=5.0)

    sub.add_parser("env", help="print the serving environment report")

    routerp = sub.add_parser(
        "router", help="standalone KV-router service (routing-as-a-service)"
    )
    routerp.add_argument("--fabric", required=True, help="fabric host:port")
    routerp.add_argument("--namespace", default="dynamo")
    routerp.add_argument("--component", default="backend")
    routerp.add_argument("--endpoint", default="generate")
    routerp.add_argument(
        "--block-size", type=int, default=64, dest="block_size",
        help="token-block size (must match the workers' page size)",
    )
    routerp.add_argument(
        "--salt", default=None,
        help="hash salt — REQUIRED, must be the served model name "
             "(workers content-address KV blocks with salt=<model>)",
    )
    routerp.add_argument(
        "--host", default="127.0.0.1",
        help="address this router advertises to frontends (must be "
             "routable from other machines in multi-host deployments)",
    )
    routerp.add_argument(
        "--shards", type=int, default=1,
        help="index shards (each with its own event pump thread) — scale "
             "event application past one pump at high fleet event rates",
    )

    metricsp = sub.add_parser("metrics", help="Prometheus metrics service")
    metricsp.add_argument("--fabric", required=True, help="fabric host:port")
    metricsp.add_argument("--component", default="backend")
    metricsp.add_argument("--host", default="127.0.0.1")
    metricsp.add_argument("--port", type=int, default=9091)
    metricsp.add_argument(
        "--log-file", default=None, dest="log_file", metavar="NAME|PATH",
        help="also log (JSONL) to this file; a bare name lands in "
             "DYNTPU_LOG_DIR (default artifacts/log), never the CWD",
    )
    metricsp.add_argument(
        "--trace-sample-rate", type=int, default=None,
        dest="trace_sample_rate", metavar="N",
        help="fleet trace plane: keep 1-in-N HEALTHY traces (anomalous "
             "ones — slow/error/replayed/incomplete — are always kept); "
             "0 keeps none but the anomalies. Default 10, or "
             "DYNTPU_TRACE_SAMPLE_RATE",
    )
    metricsp.add_argument(
        "--trace-window", type=float, default=2.0, dest="trace_window",
        metavar="SECONDS",
        help="trace assembly quiet window before a trace finalizes "
             "through the tail sampler (stragglers arriving later "
             "attach to kept traces; default 2.0)",
    )
    metricsp.add_argument(
        "--trace-keep", type=int, default=512, dest="trace_keep",
        metavar="N",
        help="kept-trace ring capacity at the metrics service "
             "(LRU-evicted; default 512)",
    )

    planp = sub.add_parser("planner", help="autoscale the worker fleet")
    planp.add_argument("--fabric", required=True, help="fabric host:port")
    planp.add_argument(
        "--mode", default="load", choices=["load", "sla", "closed"],
        help="load: KV/queue thresholds; sla: offline perf tables; "
             "closed: the live closed loop — scales on the fleet's "
             "OBSERVED SLO burn/attainment (worker SLO sketches) with "
             "hysteresis bands, per-role cooldowns, and a per-tick "
             "action clamp (docs/operations.md 'Closed-loop "
             "autoscaling & role flips')",
    )
    planp.add_argument(
        "--flip", action="store_true",
        help="closed mode: prefer flipping an idle worker between "
             "prefill/decode roles (drain + re-register; hot KV pages "
             "survive) over kill+spawn. Default off.",
    )
    planp.add_argument(
        "--cooldown", type=float, default=30.0,
        help="closed mode: seconds between scale actions on one role",
    )
    planp.add_argument(
        "--flip-cooldown", type=float, default=60.0, dest="flip_cooldown",
        help="closed mode: seconds between role flips fleet-wide",
    )
    planp.add_argument(
        "--max-actions", type=int, default=2, dest="max_actions",
        help="closed mode: hard per-tick actuation clamp (scales+flips)",
    )
    planp.add_argument(
        "--no-handover", action="store_false", dest="handover",
        help="closed mode: scale-down kills workers instead of retiring "
             "them by live KV handover (docs/operations.md 'Rolling "
             "upgrades & worker handover'). Default: handover preferred, "
             "kill as fallback.",
    )
    planp.add_argument(
        "--rolling-upgrade", action="store_true", dest="rolling_upgrade",
        help="run ONE rolling-upgrade sweep instead of the control loop: "
             "replace every worker one at a time (spawn replacement -> "
             "wait registered -> handover -> cooldown), then exit. Zero "
             "dropped streams; in-flight work continues on warm KV.",
    )
    planp.add_argument(
        "--upgrade-cooldown", type=float, default=5.0,
        dest="upgrade_cooldown",
        help="rolling upgrade: seconds between replaced workers",
    )
    planp.add_argument("--namespace", default="dynamo")
    planp.add_argument("--component", default="backend")
    planp.add_argument("--interval", type=float, default=10.0)
    planp.add_argument("--min-decode", type=int, default=1, dest="min_decode")
    planp.add_argument("--max-decode", type=int, default=8, dest="max_decode")
    planp.add_argument("--min-prefill", type=int, default=0, dest="min_prefill")
    planp.add_argument("--max-prefill", type=int, default=4, dest="max_prefill")
    planp.add_argument(
        "--ttft-ms", type=float, default=200.0, dest="ttft_ms",
        help="SLA mode: time-to-first-token target",
    )
    planp.add_argument(
        "--itl-ms", type=float, default=20.0, dest="itl_ms",
        help="SLA mode: inter-token-latency target",
    )
    planp.add_argument(
        "--perf-table", default=None, dest="perf_table",
        help="SLA mode: JSON from benchmarks/profile_sla.py "
             '({"ttft_vs_rate": [[rate, ms], ...], "itl_vs_rate": [...]})',
    )
    planp.add_argument("--model", default="tiny", help="model spawned workers serve")
    planp.add_argument(
        "--checkpoint", default=None, help="checkpoint dir for spawned workers"
    )
    planp.add_argument(
        "--kv-economy", action="store_true", dest="kv_economy",
        help="price scale decisions with the KV-economy CostModel "
             "(docs/operations.md 'The KV economy'): scale-down hands "
             "over only when the victim's resident KV is worth the "
             "bytes, and each scale-up is followed by a prefix "
             "pre-warm of the newcomer from the hottest peer",
    )
    planp.add_argument(
        "--worker-args", default="", dest="worker_args",
        help="extra flags appended to spawned worker commands",
    )
    planp.add_argument(
        "--connector", default="local", choices=["local", "kube"],
        help="local: spawn worker processes on this host; kube: edit the "
             "DynamoGraphDeployment CR and let the operator reconcile",
    )
    planp.add_argument(
        "--cr-name", default=None, dest="cr_name",
        help="kube connector: DynamoGraphDeployment name",
    )
    planp.add_argument(
        "--k8s-namespace", default="default", dest="k8s_namespace",
        help="kube connector: namespace of the CR",
    )
    def _role_service(value: str) -> str:
        if "=" not in value:
            raise argparse.ArgumentTypeError(
                f"expected role=ServiceName, got {value!r}"
            )
        return value

    planp.add_argument(
        "--role-service", action="append", default=[], dest="role_service",
        type=_role_service,
        help="kube connector: role=ServiceName mapping (repeatable), e.g. "
             "--role-service decode=Worker --role-service "
             "prefill=PrefillWorkerService",
    )

    return p


def _ext_command(
    argv: list[str], out_value: str, tail: list[str], extra: list[str]
) -> list[str]:
    """Assemble the external-engine command from `out=ext:<cmd>` plus any
    argv tokens dynamo-tpu itself did not claim, in their original order.
    The quoted form (`'out=ext:python -m pkg --flag'`) is exact; unquoted
    trailing tokens pass through only if no dynamo-tpu option consumed
    them first (collisions like --model need the quoted form)."""
    import shlex

    cmd = shlex.split(out_value[len("ext:"):])
    pool = list(tail) + list(extra)
    seen_out = False
    for tok in argv:
        if not seen_out:
            seen_out = tok == "out=" + out_value
            continue
        if tok in pool:
            pool.remove(tok)
            cmd.append(tok)
    cmd += pool  # anything left (defensive: tokens before out=)
    if not cmd:
        raise SystemExit("out=ext: needs a command, e.g. "
                         "'out=ext:python -m my_shim'")
    return cmd


def main(argv: Optional[list[str]] = None) -> None:
    p = build_parser()
    raw_argv = list(argv) if argv is not None else sys.argv[1:]
    args, extra_argv = p.parse_known_args(argv)
    if extra_argv and not any(a.startswith("out=ext:") for a in raw_argv):
        p.error(f"unrecognized arguments: {' '.join(extra_argv)}")
    if args.cmd == "planner" and args.connector == "kube":
        if not args.cr_name:
            p.error("--cr-name is required with --connector kube")
        if not args.role_service:
            # Without mappings the connector falls back to service==role,
            # which never matches real CR service names — the planner would
            # start healthy and silently never scale.
            p.error(
                "--connector kube requires at least one --role-service "
                "mapping (e.g. --role-service decode=Worker)"
            )
    configure_logging(log_file=getattr(args, "log_file", None))
    # chaos harness: subprocess workers join fault-injection scenarios
    # via DYNTPU_FAULTS (no-op when unset — dynamo_tpu/testing/faults.py)
    from dynamo_tpu.testing.faults import install_from_env

    install_from_env()
    if getattr(args, "trace", False):
        from dynamo_tpu import telemetry

        telemetry.configure(enabled=True)

    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    # Manifest/introspection commands don't touch the native hot path —
    # dispatch them before the (possibly minutes-long) native compile.
    if args.cmd in ("build", "deploy"):
        from dynamo_tpu.sdk.build import (
            build_manifest,
            render_k8s,
            write_build,
            write_k8s,
        )
        from dynamo_tpu.sdk.config import load_config

        config = load_config(args.config) if args.config else {}
        manifest = build_manifest(args.graph, config, image=args.image)
        path = write_build(manifest, args.output)
        print(f"wrote {path} ({len(manifest['services'])} services)")
        if args.cmd == "deploy":
            if args.cr:
                import yaml as _yaml

                from dynamo_tpu.sdk.build import _k8s_name

                name = args.name or _k8s_name(args.graph.split(":")[-1])
                cr = {
                    "apiVersion": "dynamo.tpu/v1alpha1",
                    "kind": "DynamoGraphDeployment",
                    "metadata": {"name": name},
                    "spec": {
                        "image": manifest["image"],
                        "fabricHost": args.fabric_host,
                        "services": manifest["services"],
                    },
                }
                if args.fabric_external:
                    # target a platform-managed fabric: the operator must
                    # not render (and fight over) a per-graph one
                    cr["spec"]["fabricExternal"] = True
                os.makedirs(args.output, exist_ok=True)
                kpath = os.path.join(args.output, "graph-deployment.yaml")
                with open(kpath, "w") as f:
                    _yaml.safe_dump(cr, f, sort_keys=False)
                print(f"wrote {kpath} (DynamoGraphDeployment/{name})")
            else:
                objs = render_k8s(manifest, fabric_host=args.fabric_host)
                kpath = write_k8s(objs, args.output)
                print(f"wrote {kpath} ({len(objs)} objects)")
        return

    if args.cmd == "operator":
        from dynamo_tpu.operator.controller import main as operator_main

        operator_main(
            ["--namespace", args.namespace, "--interval", str(args.interval)]
        )
        return

    if args.cmd == "env":
        import json as _json

        from dynamo_tpu.sdk.build import env_report

        print(_json.dumps(env_report(), indent=2))
        return

    # Compile the native hot-path core before serving so no request admission
    # or router construction ever waits on g++ (falls back to Python if the
    # toolchain is missing).
    from dynamo_tpu.native import ensure_built

    ensure_built()

    if args.cmd == "fabric":
        if getattr(args, "promote", None):
            from dynamo_tpu.runtime.fabric.replica import promote_standby

            reply = asyncio.run(promote_standby(args.promote))
            print(json.dumps({"promote": args.promote, "reply": reply}),
                  flush=True)
            sys.exit(0 if reply.get("ok") else 1)
        if getattr(args, "standby_of", None) or getattr(args, "peer", None):
            # HA broker (standby, or a primary that can be fenced by
            # peers); the flag-less path below stays the single-broker
            # server, bit-identical to before
            from dynamo_tpu.runtime.fabric.replica import FabricNode

            async def _ha_main() -> None:
                node = FabricNode(
                    args.host, args.port,
                    persist_dir=args.persist_dir,
                    standby_of=args.standby_of,
                    peers=tuple(args.peer),
                    detector_budget_s=args.detector_budget,
                    auto_promote=args.auto_promote,
                )
                await node.start()
                print(
                    f"fabric {node.role} on {node.address}"
                    + (
                        # live primary address, not args.standby_of: a
                        # primary-eligible node that DEFERRED to a
                        # higher-fenced peer is a standby of that peer
                        f" (standby of {node.server.primary_address})"
                        if node.role == "standby"
                        else ""
                    ),
                    flush=True,
                )
                if node.role == "primary":
                    node.promoted.clear()  # report only LATER promotions
                try:
                    while True:
                        await node.promoted.wait()
                        print(
                            f"fabric PROMOTED to primary on "
                            f"{node.address} (fence "
                            f"{node.fabric.fence})",
                            flush=True,
                        )
                        node.promoted.clear()
                        # a later demotion re-arms the wait
                finally:
                    await node.stop()

            asyncio.run(_ha_main())
            return
        from dynamo_tpu.runtime.fabric.server import _amain

        asyncio.run(_amain(args))
        return

    if args.cmd == "planner":
        asyncio.run(_run_planner(args))
        return

    if args.cmd == "metrics":
        asyncio.run(_run_metrics(args))
        return

    if args.cmd == "router":
        from dynamo_tpu.kv_router.service import run_router

        asyncio.run(run_router(args))
        return

    if args.cmd == "serve":
        asyncio.run(_run_serve(args))
        return

    if args.cmd == "ctl":
        asyncio.run(_run_ctl(args))
        return

    if any(t.startswith("out=ext:") for t in args.io):
        # ext mode: in=/out= must be unique, and every OTHER io token —
        # including stray k=v ones like `config=prod.yaml` or a second
        # `out=jax` — belongs to the engine command. The plain dict parse
        # below would silently swallow them (or worse, reroute the whole
        # invocation to a different engine via last-wins out=).
        io = {}
        leftover = []
        for tok in args.io:
            k, sep, v = tok.partition("=")
            if sep and k in ("in", "out"):
                if k in io:
                    p.error(
                        f"duplicate {k}= with out=ext: — quote the whole "
                        f"engine command ('out=ext:python -m ...')"
                    )
                io[k] = v
                continue
            leftover.append(tok)
        inp = io.get("in", "text")
        args.out = io["out"]
        args.ext_cmd = _ext_command(raw_argv, args.out, leftover, extra_argv)
    else:
        io = dict(kv.split("=", 1) for kv in args.io if "=" in kv)
        inp = io.get("in", "text")
        args.out = io.get("out", "jax")

    if getattr(args, "coordinator", None):
        if inp != "dyn" or args.out != "jax":
            # The lockstep group only exists behind the worker path
            # (in=dyn builds an SpmdEngineRunner on host 0). Any other
            # input on ANY host would build a plain runner whose first
            # jitted dispatch blocks forever in cross-host collectives
            # with no followers participating. Gate BEFORE init_multihost
            # — that call blocks until every host joins, so a post-init
            # check would hang instead of failing fast.
            print(
                "multi-host SPMD serving requires `run in=dyn out=jax` "
                "on every host (put an `in=http` frontend in a separate "
                "process, attached over the fabric)",
                file=sys.stderr,
            )
            sys.exit(2)
        from dynamo_tpu.parallel.mesh import init_multihost

        n = init_multihost(args.coordinator, args.num_hosts, args.host_id)
        print(
            f"multi-host up: host {args.host_id}/{args.num_hosts}, "
            f"{n} global devices",
            flush=True,
        )
        if args.host_id > 0:
            # Follower replica of a cross-host SPMD group: no fabric, no
            # ingress — just mirror the leader's lockstep broadcasts
            # until its shutdown (engine/spmd.py).
            _run_spmd_follower(args)
            return

    if inp == "dyn":
        asyncio.run(_run_worker(args))
    elif inp == "http":
        asyncio.run(_run_http(args))
    elif inp.startswith("batch:"):
        asyncio.run(_run_batch(args, inp.split(":", 1)[1]))
    elif inp in ("text", "stdin"):
        asyncio.run(_run_text(args))
    else:
        print(f"unknown in={inp}", file=sys.stderr)
        sys.exit(2)


if __name__ == "__main__":
    main()

"""Sharding rules: logical tensor dims -> mesh axes.

The recipe (scaling-book style): pick a mesh, annotate param/activation
shardings with PartitionSpecs, jit, and let XLA insert the ICI collectives.

Megatron-style TP layout for Llama:
- wq/wk/wv: shard the head (output) dim on "tp" — each device owns a head
  subset, attention is embarrassingly parallel across heads.
- wo / w_down: shard the *input* dim on "tp" — the following matmul produces
  partial sums; XLA inserts one psum (all-reduce) per layer, the minimal TP
  collective count.
- embed/lm_head: shard the vocab/hidden dim on "tp".
- KV pages: shard kv-heads on "tp" — KV stays resident beside its heads,
  no KV collectives during decode.
- Request batch dims shard on "dp".
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.llama import LlamaConfig


def llama_param_specs(cfg: LlamaConfig, quantized: bool = False) -> dict:
    specs = {
        "embed": P(None, "tp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        },
        "final_norm": P(None),
    }
    if cfg.attention_bias:
        # biases shard with their projection's output dim
        specs["layers"]["bq"] = P(None, "tp")
        specs["layers"]["bk"] = P(None, "tp")
        specs["layers"]["bv"] = P(None, "tp")
    if getattr(cfg, "qk_norm", False):
        # per-head-dim norms apply identically on every (tp-sharded) head
        specs["layers"]["q_norm"] = P(None, None)
        specs["layers"]["k_norm"] = P(None, None)
    if getattr(cfg, "post_block_norms", False):
        # Gemma2 post-sublayer norms act on the replicated hidden dim
        specs["layers"]["post_attn_norm"] = P(None, None)
        specs["layers"]["post_mlp_norm"] = P(None, None)
    if quantized:
        # int8 per-output-channel scales [L, 1, out] shard with their
        # weight's output dim (w_down's output is the unsharded hidden)
        for name in ("wq", "wk", "wv", "w_gate", "w_up"):
            specs["layers"][name + "_scale"] = P(None, None, "tp")
        specs["layers"]["wo_scale"] = P(None, None, None)
        specs["layers"]["w_down_scale"] = P(None, None, None)
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def kv_cache_spec(shard_heads: bool = True) -> P:
    # [L, P, S, Hkv, D] — kv heads ride with their tp shard. MQA-shaped
    # caches (MLA's shared latent: Hkv=1) replicate instead.
    return P(None, None, None, "tp" if shard_heads else None, None)


def batch_spec(ndim: int) -> P:
    # [B, ...] request-batch tensors shard over dp.
    return P(*(("dp",) + (None,) * (ndim - 1)))


def shardings_for(mesh: Mesh, specs: Any):
    """Map a pytree of PartitionSpecs to NamedShardings on `mesh`."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )

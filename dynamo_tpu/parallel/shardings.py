"""Sharding resolution: logical axis names -> mesh axes -> PartitionSpecs.

The recipe (scaling-book style): pick a mesh, annotate param/activation
shardings with PartitionSpecs, jit, and let XLA insert the ICI
collectives. Since the logical-axis refactor the per-model layout lives
with the MODELS as logical names (`models/llama.py::llama_logical_axes`
and friends) and the mesh placement lives in ONE rule table
(`parallel/logical.py::DEFAULT_RULES`); this module resolves the two
into the PartitionSpecs the engine places arrays with.

The resolved layout is the Megatron-style TP recipe:
- wq/wk/wv: shard the head (output) dim on "tp" — each device owns a
  head subset, attention is embarrassingly parallel across heads.
- wo / w_down: shard the *input* dim on "tp" — the following matmul
  produces partial sums; XLA inserts one psum (all-reduce) per layer,
  the minimal TP collective count.
- embed/lm_head: shard the hidden/vocab dim on "tp".
- MoE routed experts: expert dim on "ep" (EP placement), expert
  intermediate dim on "tp" where the model names it "mlp".
- KV pages: shard kv-heads on "tp" — KV stays resident beside its
  heads, no KV collectives during decode.
- Request batch dims shard on "dp".
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.llama import LlamaConfig, llama_logical_axes
from dynamo_tpu.parallel.logical import (
    L,
    LogicalAxisRules,
    resolve,
)


def llama_param_specs(
    cfg: LlamaConfig, quantized: bool = False,
    rules: Optional[LogicalAxisRules] = None,
) -> dict:
    """PartitionSpecs for llama-family params: `llama_logical_axes`
    resolved through the rule table (default table when None)."""
    return resolve(llama_logical_axes(cfg, quantized=quantized), rules)


def kv_logical_axes(shard_heads: bool = True):
    """[L, P, S, Hkv, D] page pool: kv heads ride with their tp shard.
    MQA-shaped caches (MLA's shared latent: Hkv=1) replicate instead."""
    return L(
        "layers", "kv_pages", "kv_seq",
        "kv_heads" if shard_heads else None, None,
    )


def kv_cache_spec(
    shard_heads: bool = True,
    rules: Optional[LogicalAxisRules] = None,
) -> P:
    return resolve(kv_logical_axes(shard_heads), rules)


def batch_spec(
    ndim: int, rules: Optional[LogicalAxisRules] = None
) -> P:
    # [B, ...] request-batch tensors shard over dp.
    return resolve(L(*(("batch",) + (None,) * (ndim - 1))), rules)


def shardings_for(mesh: Mesh, specs: Any):
    """Map a pytree of PartitionSpecs to NamedShardings on `mesh`."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )

"""t5x-style logical axis sharding: one rule table for the whole fleet.

Models name each tensor dimension ONCE with a *logical* axis name
("heads", "mlp", "expert", ...) instead of hard-coding mesh axes in
per-model spec tables. A single `LogicalAxisRules` table maps logical
names to mesh axes ("dp"/"sp"/"ep"/"tp"), so changing the parallel
layout — tp=8×dp=2 on a v5e-16, EP over a pod slice — is a rule-table
edit (or a `--topology` knob), not a per-model rewrite.

Resolution semantics (t5x `logical_to_mesh_axes`):
- rules are scanned IN ORDER; the first rule whose logical name matches
  the dim wins,
- a rule mapping to a mesh axis already used by an earlier dim of the
  SAME array is skipped (a mesh axis can shard at most one dim), and
  the scan continues to any fallback rule for that name,
- a dim named `None` — or whose every candidate mesh axis is taken —
  resolves to `None` (replicated),
- a logical name with NO rule at all raises `UnknownLogicalAxisError`:
  new model axes must be placed deliberately, and
  `scripts/dryrun_70b.py --check-rules` turns that into a fast tier-1
  failure instead of an on-chip surprise.

The mesh axis names stay this repo's ("dp", "sp", "ep", "tp") — the
shard_map kernels and the scaling-book layout notes reference them by
name — so the rule table is where t5x's "data"/"model" indirection
lives, not a mesh rename.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P


class UnknownLogicalAxisError(ValueError):
    """A model declared a logical axis name the rule table doesn't know."""


class AxisNames(tuple):
    """Logical dim names for ONE array — one entry per (leading) dim,
    `None` for deliberately-unsharded dims. A tuple subclass so jax
    pytree utilities can treat a whole name-tuple as a leaf (t5x
    idiom): `is_leaf=lambda x: isinstance(x, AxisNames)`."""

    def __new__(cls, *names: Optional[str]) -> "AxisNames":
        return super().__new__(cls, names)

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return f"AxisNames({', '.join(repr(n) for n in self)})"


def L(*names: Optional[str]) -> AxisNames:
    """Shorthand constructor: ``L("layers", None, "heads")``."""
    return AxisNames(*names)


#: one (logical_name, mesh_axis | None) pair per rule, scanned in order
LogicalRules = Sequence[Tuple[str, Optional[str]]]


@dataclass(frozen=True)
class LogicalAxisRules:
    """The ONE table mapping logical axis names to mesh axes.

    `rules` is ordered: earlier rules win, later rules with the same
    logical name act as fallbacks when the preferred mesh axis is
    already used by another dim of the same array.
    """

    rules: Tuple[Tuple[str, Optional[str]], ...]

    def __post_init__(self) -> None:
        object.__setattr__(self, "rules", tuple(
            (str(n), a if a is None else str(a)) for n, a in self.rules
        ))

    def known(self, name: str) -> bool:
        return any(n == name for n, _ in self.rules)

    def mesh_axis(self, name: str) -> Optional[str]:
        """First-listed mesh axis for `name` (provenance reporting)."""
        for n, a in self.rules:
            if n == name:
                return a
        raise UnknownLogicalAxisError(
            f"logical axis {name!r} has no rule; known axes: "
            f"{sorted({n for n, _ in self.rules})}"
        )

    def spec(self, axes) -> P:
        """Resolve one array's `AxisNames` to a PartitionSpec.

        A raw PartitionSpec passes through untouched — the escape hatch
        for layouts the logical vocabulary can't express yet.
        """
        if isinstance(axes, P):
            return axes
        used: set[str] = set()
        out = []
        for name in axes:
            if name is None:
                out.append(None)
                continue
            assigned: Optional[str] = None
            known = False
            for n, a in self.rules:
                if n != name:
                    continue
                known = True
                if a is None:
                    break  # explicitly replicated
                if a not in used:
                    assigned = a
                    used.add(a)
                    break
                # mesh axis taken by an earlier dim: try a fallback rule
            if not known:
                raise UnknownLogicalAxisError(
                    f"logical axis {name!r} (of {tuple(axes)!r}) has no "
                    f"rule; known axes: "
                    f"{sorted({n for n, _ in self.rules})}"
                )
            out.append(assigned)
        return P(*out)

    def tree_specs(self, tree):
        """Resolve a pytree of AxisNames (dicts mirroring a param tree)
        to the same tree of PartitionSpecs."""
        import jax

        return jax.tree.map(
            self.spec, tree,
            is_leaf=lambda x: isinstance(x, (AxisNames, P)),
        )

    def doc(self) -> list:
        """[[logical, mesh_axis|None], ...] — rule-table provenance for
        /v1/debug/mesh."""
        return [[n, a] for n, a in self.rules]


#: The default table. Mirrors the Megatron-style TP layout the ad-hoc
#: spec tables hard-coded (docstring of parallel/shardings.py), plus the
#: EP placement for routed experts:
#: - head/mlp/vocab/embedding-hidden dims shard on "tp" (innermost ICI
#:   ring: the per-layer all-reduce is latency-critical),
#: - the routed-expert dim shards on "ep",
#: - request batch dims shard on "dp" (DCN-friendly: no per-layer
#:   collective crosses it),
#: - layer stacks, KV page pools, and sequence dims stay replicated.
DEFAULT_RULES = LogicalAxisRules(rules=(
    ("batch", "dp"),
    ("embed", "tp"),
    ("vocab", "tp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("mlp", "tp"),
    ("expert", "ep"),
    ("layers", None),
    ("kv_pages", None),
    ("kv_seq", None),
    ("kv_latent", None),
))


_ACTIVE_RULES: LogicalAxisRules = DEFAULT_RULES


def default_rules() -> LogicalAxisRules:
    """The process-wide rule table resolvers use when none is passed."""
    return _ACTIVE_RULES


def set_rules(rules: Optional[LogicalAxisRules]) -> LogicalAxisRules:
    """Swap the process-wide table (tests / exotic topologies); returns
    the previous table so callers can restore it."""
    global _ACTIVE_RULES
    prev = _ACTIVE_RULES
    _ACTIVE_RULES = rules if rules is not None else DEFAULT_RULES
    return prev


def resolve(tree, rules: Optional[LogicalAxisRules] = None):
    """Module-level convenience: resolve a tree of AxisNames through
    `rules` (default: the active table)."""
    return (rules or default_rules()).tree_specs(tree)

from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.parallel.shardings import (
    batch_spec,
    kv_cache_spec,
    llama_param_specs,
    shardings_for,
)

__all__ = [
    "MeshConfig",
    "make_mesh",
    "batch_spec",
    "kv_cache_spec",
    "llama_param_specs",
    "shardings_for",
]

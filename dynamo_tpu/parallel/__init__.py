from dynamo_tpu.parallel.context import (
    dense_gqa_attention,
    ring_attention,
    ulysses_attention,
)
from dynamo_tpu.parallel.logical import (
    DEFAULT_RULES,
    AxisNames,
    L,
    LogicalAxisRules,
    UnknownLogicalAxisError,
    default_rules,
    resolve,
    set_rules,
)
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh, parse_topology
from dynamo_tpu.parallel.shardings import (
    batch_spec,
    kv_cache_spec,
    kv_logical_axes,
    llama_param_specs,
    shardings_for,
)

__all__ = [
    "dense_gqa_attention",
    "ring_attention",
    "ulysses_attention",
    "DEFAULT_RULES",
    "AxisNames",
    "L",
    "LogicalAxisRules",
    "UnknownLogicalAxisError",
    "default_rules",
    "resolve",
    "set_rules",
    "MeshConfig",
    "make_mesh",
    "parse_topology",
    "batch_spec",
    "kv_cache_spec",
    "kv_logical_axes",
    "llama_param_specs",
    "shardings_for",
]

from dynamo_tpu.parallel.context import (
    dense_gqa_attention,
    ring_attention,
    ulysses_attention,
)
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.parallel.shardings import (
    batch_spec,
    kv_cache_spec,
    llama_param_specs,
    shardings_for,
)

__all__ = [
    "dense_gqa_attention",
    "ring_attention",
    "ulysses_attention",
    "MeshConfig",
    "make_mesh",
    "batch_spec",
    "kv_cache_spec",
    "llama_param_specs",
    "shardings_for",
]

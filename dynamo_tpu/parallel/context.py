"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context prefill support, first-class for TPU (the reference has no
sequence parallelism at all — SURVEY.md §5.7 — it leans on paged KV +
disagg prefill; on TPU the ICI ring makes sequence parallelism natural, so
long prompts can be prefilling across an "sp" mesh axis instead of being
chunk-serialized on one chip).

Two interchangeable strategies over the same [B, T, H, D] contract, both
expressed as shard_map programs whose collectives XLA lowers onto ICI:

- **ring_attention**: Q stays put; K/V chunks rotate around the sp ring via
  `lax.ppermute`, with flash-style online-softmax accumulation per step.
  Communication O(T/sp) per step, overlapping compute; memory O(T/sp).
  (Liu et al., "Ring Attention with Blockwise Transformers", 2023 —
  PAPERS.md.)
- **ulysses_attention**: two `all_to_all`s re-shard sequence->heads, run
  dense local attention over the full sequence on a head subset, and shard
  back (Jacobs et al., "DeepSpeed Ulysses", 2023). Cheaper at moderate T
  when heads divide sp; requires Hq % sp == 0 and Hkv % sp == 0.

Both support GQA (Hq = G * Hkv) and causal masking, accumulate in f32, and
are validated against dense attention on an 8-device CPU mesh
(tests/test_context_parallel.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from dynamo_tpu.platform import get_shard_map

shard_map = get_shard_map()


def dense_gqa_attention(
    q: jax.Array,  # [B, Tq, Hq, D]
    k: jax.Array,  # [B, Tk, Hkv, D]
    v: jax.Array,  # [B, Tk, Hkv, D]
    q_offset=0,  # absolute position of q[0] (for causal masking)
    k_offset=0,
    causal: bool = True,
) -> jax.Array:
    """Reference dense attention, GQA-grouped, f32 accumulation.

    Returns [B, Tq, Hq, D] in q.dtype.
    """
    b, tq, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, tq, hkv, g, d).astype(jnp.float32)
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k.astype(jnp.float32)) * scale
    if causal:
        q_pos = q_offset + jnp.arange(tq)
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = k_pos[None, :] <= q_pos[:, None]  # [Tq, Tk]
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, tq, hq, d).astype(q.dtype)


def _ring_shard(q, k, v, *, axis_name: str, causal: bool):
    """Per-shard body: local q chunk attends every k/v chunk as it passes by
    on the ring. Runs under shard_map; shapes are per-device."""
    sp = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, tl, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, tl, hkv, g, d).astype(jnp.float32) * scale
    q_pos = my * tl + jnp.arange(tl)  # absolute positions of local queries

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(s, carry):
        m, l, acc, k_cur, v_cur = carry

        # After s rotations we hold the chunk originally on device (my - s).
        chunk = (my - s) % sp
        k_pos = chunk * tl + jnp.arange(tl)

        def attend(m, l, acc):
            scores = jnp.einsum(
                "btkgd,bskd->bkgts", qg, k_cur.astype(jnp.float32)
            )  # [B, Hkv, G, Tl, Tl]
            if causal:
                mask = k_pos[None, :] <= q_pos[:, None]
                scores = jnp.where(mask[None, None, None], scores, -1e30)
            m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
            p = jnp.exp(scores - m_new)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * corr + jnp.einsum(
                "bkgts,bskd->bkgtd", p, v_cur.astype(jnp.float32)
            )
            return m_new, l_new, acc_new

        if causal:
            # An entirely-future chunk (chunk > my) is fully masked: skip its
            # einsums — otherwise ~half the ring's FLOPs are dead compute.
            m, l, acc = lax.cond(
                chunk <= my, attend, lambda m, l, acc: (m, l, acc), m, l, acc
            )
        else:
            m, l, acc = attend(m, l, acc)

        # Rotate K/V to the next device (the last step's rotate closes the
        # ring back to the owner — harmless, and keeps the loop uniform).
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_nxt, v_nxt

    # pcast-to-varying: the carry is device-varying over sp (vma typing).
    # Pre-vma jax (no lax.pcast) treats every shard_map value as varying
    # already, so the cast degrades to identity there.
    def _vary(x):
        pcast = getattr(lax, "pcast", None)
        if pcast is None:
            return x
        return pcast(x, axis_name, to="varying")

    m0 = _vary(jnp.full((b, hkv, g, tl, 1), -jnp.inf, jnp.float32))
    l0 = _vary(jnp.zeros((b, hkv, g, tl, 1), jnp.float32))
    a0 = _vary(jnp.zeros((b, hkv, g, tl, d), jnp.float32))
    m, l, acc, _, _ = lax.fori_loop(0, sp, step, (m0, l0, a0, k, v))
    # Causal => every query row attends at least itself, so l > 0.
    out = acc / l
    return (
        out.transpose(0, 3, 1, 2, 4).reshape(b, tl, hq, d).astype(q.dtype)
    )


def ring_attention(
    q: jax.Array,  # [B, T, Hq, D] — T sharded over `axis_name`
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,  # [B, T, Hkv, D]
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axis: str | None = None,  # shard B over this mesh axis (dp)
    head_axis: str | None = None,  # shard heads over this mesh axis (tp)
) -> jax.Array:
    """Sequence-parallel causal attention over the sp ring. T must divide
    evenly by the sp axis size.

    When the ambient mesh also carries dp/tp axes, pass them as
    batch_axis/head_axis so the region stays batch- and head-sharded —
    omitting them would all-gather every head and batch row onto every
    device inside the shard_map (O(tp·dp) redundant attention work on the
    long-prompt path whose point is reducing per-chip memory)."""
    sp = mesh.shape[axis_name]
    if q.shape[1] % sp:
        raise ValueError(f"T={q.shape[1]} not divisible by sp={sp}")
    spec = P(batch_axis, axis_name, head_axis, None)
    fn = shard_map(
        partial(_ring_shard, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def _ulysses_shard(q, k, v, *, axis_name: str, causal: bool):
    """seq-shard -> all_to_all -> head-shard dense attention -> all_to_all."""
    sp = lax.psum(1, axis_name)
    # [B, Tl, H, D] -> gather seq, scatter heads -> [B, T, H/sp, D]
    def to_heads(x):
        # split heads into sp groups; concat_dimension=seq, split=heads
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = dense_gqa_attention(qh, kh, vh, causal=causal)
    return to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism: both head
    counts and T must divide the sp axis size."""
    sp = mesh.shape[axis_name]
    hq, hkv = q.shape[2], k.shape[2]
    if q.shape[1] % sp:
        raise ValueError(f"T={q.shape[1]} not divisible by sp={sp}")
    if hq % sp or hkv % sp:
        raise ValueError(
            f"heads (Hq={hq}, Hkv={hkv}) must divide sp={sp} for ulysses; "
            "use ring_attention otherwise"
        )
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(_ulysses_shard, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)

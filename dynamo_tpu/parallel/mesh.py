"""Device mesh construction.

TPU-first parallelism lives here: all intra-engine parallelism (tensor /
data / expert / sequence) is expressed as shardings over a single
`jax.sharding.Mesh`, with XLA inserting the ICI collectives. This replaces
what the reference delegates to its GPU engines via NCCL (SURVEY.md §2.9:
TP/PP/DP/EP are engine-delegated flags like `tensor-parallel-size`; here the
engine is ours, so the mesh IS the parallelism implementation).

Axis conventions (scaling-book style):
- "dp"  — data parallel over the request batch
- "tp"  — tensor parallel over heads / hidden / vocab
- "ep"  — expert parallel for MoE (maps onto "tp" devices for dense models)
- "sp"  — sequence/context parallel (ring attention), optional
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshConfig:
    """Parallel layout of one engine worker."""

    dp: int = 1
    tp: int = 1
    sp: int = 1
    #: expert parallel (MoE expert dim; 1 for dense models)
    ep: int = 1
    axis_names: tuple[str, ...] = ("dp", "sp", "ep", "tp")

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.sp, self.ep, self.tp)

    @property
    def num_devices(self) -> int:
        return self.dp * self.sp * self.ep * self.tp

    @staticmethod
    def single_device() -> "MeshConfig":
        return MeshConfig(dp=1, tp=1, sp=1)


def init_multihost(
    coordinator: str, num_hosts: int, host_id: int
) -> int:
    """Join a multi-host JAX process group: every host calls this with the
    same coordinator address BEFORE first device use; afterwards
    jax.devices() is the GLOBAL device list (reference parity:
    MultiNodeConfig + the leader/worker barrier, engines.rs:44, §2.9).

    This wires the process-group bring-up (coordinator rendezvous, global
    device visibility, collective transport). Cross-host SPMD *serving* —
    every host running the engine step in lockstep over globally-sharded
    batch arrays — is driven by engine/spmd.py: the leader broadcasts the
    admission event log, every host replays it through its own
    deterministic scheduler replica, and identical jit dispatches execute
    over the shared mesh.

    Returns the number of global devices. Idempotent for identical
    arguments; raises on a conflicting re-init.
    """
    args = (coordinator, num_hosts, host_id)
    prev = getattr(init_multihost, "_args", None)
    if prev is not None:
        if prev != args:
            raise RuntimeError(
                f"init_multihost already joined {prev}; cannot re-join as "
                f"{args}"
            )
        return len(jax.devices())
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # CPU multi-process collectives need an explicit implementation;
        # must be set before the backend initializes.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older jax: option absent
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_hosts,
        process_id=host_id,
    )
    init_multihost._args = args
    return len(jax.devices())


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh laid out so "tp" is the innermost (fastest-ICI) axis.

    TP collectives (per-layer all-reduce) are latency-critical, so they ride
    the innermost device ring; DP gradients-of-nothing (inference) only
    all-gathers tokens rarely.
    """
    config = config or MeshConfig.single_device()
    # Multi-process: jax.devices() is already the GLOBAL list after
    # init_multihost; the mesh spans every host's chips and the engine
    # runs multi-controller lockstep over it (engine/spmd.py drives the
    # replicated schedulers; reference parity: MultiNodeConfig,
    # engines.rs:43-50).
    if devices is None and jax.process_count() > 1:
        world = jax.devices()
        if config.num_devices != len(world):
            # devices[:n] of the global list would be host 0's chips
            # only — a "cross-host" mesh no other host can address.
            # Partial-fleet meshes must pass an explicit device list.
            raise ValueError(
                f"mesh {config.shape} uses {config.num_devices} of "
                f"{len(world)} global devices; a multi-process mesh must "
                "span the whole fleet (or pass devices= explicitly)"
            )
    devices = list(devices if devices is not None else jax.devices())
    n = config.num_devices
    if len(devices) < n:
        raise ValueError(
            f"mesh {config.shape} needs {n} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:n]).reshape(config.shape)
    return Mesh(arr, axis_names=config.axis_names)

"""Device mesh construction.

TPU-first parallelism lives here: all intra-engine parallelism (tensor /
data / expert / sequence) is expressed as shardings over a single
`jax.sharding.Mesh`, with XLA inserting the ICI collectives. This replaces
what the reference delegates to its GPU engines via NCCL (SURVEY.md §2.9:
TP/PP/DP/EP are engine-delegated flags like `tensor-parallel-size`; here the
engine is ours, so the mesh IS the parallelism implementation).

Axis conventions (scaling-book style):
- "dp"  — data parallel over the request batch
- "tp"  — tensor parallel over heads / hidden / vocab
- "ep"  — expert parallel for MoE (maps onto "tp" devices for dense models)
- "sp"  — sequence/context parallel (ring attention), optional
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshConfig:
    """Parallel layout of one engine worker."""

    dp: int = 1
    tp: int = 1
    sp: int = 1
    #: expert parallel (MoE expert dim; 1 for dense models)
    ep: int = 1
    axis_names: tuple[str, ...] = ("dp", "sp", "ep", "tp")

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.sp, self.ep, self.tp)

    @property
    def num_devices(self) -> int:
        return self.dp * self.sp * self.ep * self.tp

    @staticmethod
    def single_device() -> "MeshConfig":
        return MeshConfig(dp=1, tp=1, sp=1)


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh laid out so "tp" is the innermost (fastest-ICI) axis.

    TP collectives (per-layer all-reduce) are latency-critical, so they ride
    the innermost device ring; DP gradients-of-nothing (inference) only
    all-gathers tokens rarely.
    """
    config = config or MeshConfig.single_device()
    devices = list(devices if devices is not None else jax.devices())
    n = config.num_devices
    if len(devices) < n:
        raise ValueError(
            f"mesh {config.shape} needs {n} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:n]).reshape(config.shape)
    return Mesh(arr, axis_names=config.axis_names)

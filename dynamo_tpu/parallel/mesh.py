"""Device mesh construction.

TPU-first parallelism lives here: all intra-engine parallelism (tensor /
data / expert / sequence) is expressed as shardings over a single
`jax.sharding.Mesh`, with XLA inserting the ICI collectives. This replaces
what the reference delegates to its GPU engines via NCCL (SURVEY.md §2.9:
TP/PP/DP/EP are engine-delegated flags like `tensor-parallel-size`; here the
engine is ours, so the mesh IS the parallelism implementation).

Axis conventions (scaling-book style):
- "dp"  — data parallel over the request batch
- "tp"  — tensor parallel over heads / hidden / vocab
- "ep"  — expert parallel for MoE (maps onto "tp" devices for dense models)
- "sp"  — sequence/context parallel (ring attention), optional
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshConfig:
    """Parallel layout of one engine worker."""

    dp: int = 1
    tp: int = 1
    sp: int = 1
    #: expert parallel (MoE expert dim; 1 for dense models)
    ep: int = 1
    axis_names: tuple[str, ...] = ("dp", "sp", "ep", "tp")

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.sp, self.ep, self.tp)

    @property
    def num_devices(self) -> int:
        return self.dp * self.sp * self.ep * self.tp

    @staticmethod
    def single_device() -> "MeshConfig":
        return MeshConfig(dp=1, tp=1, sp=1)


def init_multihost(
    coordinator: str, num_hosts: int, host_id: int
) -> int:
    """Join a multi-host JAX process group: every host calls this with the
    same coordinator address BEFORE first device use; afterwards
    jax.devices() is the GLOBAL device list (reference parity:
    MultiNodeConfig + the leader/worker barrier, engines.rs:44, §2.9).

    This wires the process-group bring-up (coordinator rendezvous, global
    device visibility, collective transport). Cross-host SPMD *serving* —
    every host running the engine step in lockstep over globally-sharded
    batch arrays — is driven by engine/spmd.py: the leader broadcasts the
    admission event log, every host replays it through its own
    deterministic scheduler replica, and identical jit dispatches execute
    over the shared mesh.

    Returns the number of global devices. Idempotent for identical
    arguments; raises on a conflicting re-init.
    """
    args = (coordinator, num_hosts, host_id)
    prev = getattr(init_multihost, "_args", None)
    if prev is not None:
        if prev != args:
            raise RuntimeError(
                f"init_multihost already joined {prev}; cannot re-join as "
                f"{args}"
            )
        return len(jax.devices())
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # CPU multi-process collectives need an explicit implementation;
        # must be set before the backend initializes.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older jax: option absent
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_hosts,
        process_id=host_id,
    )
    init_multihost._args = args
    return len(jax.devices())


def parse_topology(spec: str) -> dict:
    """Parse a `--topology tp=N,dp=M[,ep=K][,sp=J]` knob into MeshConfig
    field overrides. Unknown axes and non-positive sizes raise — a typo'd
    topology must fail at config parse, not as a mesh-shape surprise."""
    out: dict[str, int] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition("=")
        key = key.strip()
        if not sep or key not in ("dp", "tp", "sp", "ep"):
            raise ValueError(
                f"topology term {part!r}: expected axis=N with axis in "
                "dp/tp/sp/ep (e.g. 'tp=8,dp=2')"
            )
        if key in out:
            raise ValueError(f"topology names {key!r} twice: {spec!r}")
        try:
            n = int(val)
        except ValueError:
            raise ValueError(
                f"topology term {part!r}: size must be an integer"
            ) from None
        if n < 1:
            raise ValueError(f"topology term {part!r}: size must be >= 1")
        out[key] = n
    if not out:
        raise ValueError(f"empty topology spec {spec!r}")
    return out


def _hybrid_device_grid(
    config: MeshConfig, devices: Sequence[jax.Device]
):
    """Lay multi-slice/multi-granule TPU fleets out hybrid: ICI inside a
    slice, DCN across (mesh_utils.create_hybrid_device_mesh). The OUTER
    mesh axis — "dp" here — absorbs the DCN dim, so no per-layer tp/ep
    collective ever crosses the slow inter-slice links. Returns None
    when the fleet isn't hybrid (single slice, CPU devices, dp not a
    multiple of the granule count) — the caller falls back to the plain
    row-major reshape, which keeps every CPU test bit-identical."""
    if any(d.platform != "tpu" for d in devices):
        return None
    granules = sorted(
        {
            getattr(d, "slice_index", getattr(d, "process_index", 0))
            for d in devices
        }
    )
    if len(granules) <= 1:
        return None
    num = len(granules)
    if config.dp % num or config.num_devices != len(devices):
        return None
    try:
        from jax.experimental import mesh_utils

        return mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(config.dp // num, config.sp, config.ep, config.tp),
            dcn_mesh_shape=(num, 1, 1, 1),
            devices=devices,
        )
    except Exception:  # noqa: BLE001 — jaxlib without hybrid support /
        # topology info: the plain reshape still yields a working mesh
        return None


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh laid out so "tp" is the innermost (fastest-ICI) axis.

    TP collectives (per-layer all-reduce) are latency-critical, so they ride
    the innermost device ring; DP gradients-of-nothing (inference) only
    all-gathers tokens rarely. Multi-slice TPU fleets go through
    `create_hybrid_device_mesh` so "dp" rides the DCN links between
    slices while tp/ep stay on in-slice ICI.
    """
    config = config or MeshConfig.single_device()
    # Multi-process: jax.devices() is already the GLOBAL list after
    # init_multihost; the mesh spans every host's chips and the engine
    # runs multi-controller lockstep over it (engine/spmd.py drives the
    # replicated schedulers; reference parity: MultiNodeConfig,
    # engines.rs:43-50).
    if devices is None and jax.process_count() > 1:
        world = jax.devices()
        if config.num_devices != len(world):
            # devices[:n] of the global list would be host 0's chips
            # only — a "cross-host" mesh no other host can address.
            # Partial-fleet meshes must pass an explicit device list.
            raise ValueError(
                f"mesh {config.shape} uses {config.num_devices} of "
                f"{len(world)} global devices; a multi-process mesh must "
                "span the whole fleet (or pass devices= explicitly)"
            )
    devices = list(devices if devices is not None else jax.devices())
    n = config.num_devices
    if len(devices) < n:
        raise ValueError(
            f"mesh {config.shape} needs {n} devices, have {len(devices)}"
        )
    arr = _hybrid_device_grid(config, devices[:n])
    if arr is None:
        arr = np.asarray(devices[:n]).reshape(config.shape)
    return Mesh(arr, axis_names=config.axis_names)

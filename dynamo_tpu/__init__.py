"""dynamo_tpu — a TPU-native distributed LLM inference-serving framework.

Capabilities (modeled on NVIDIA Dynamo's feature set, re-designed TPU-first;
see SURVEY.md at the repo root for the structural map of the reference):

- OpenAI-compatible HTTP frontend with SSE streaming (`dynamo_tpu.frontend`)
- Lease-based service discovery + message fabric (`dynamo_tpu.runtime`)
- Content-addressed token blocks (`dynamo_tpu.tokens`)
- KV-cache-aware routing: radix prefix index + cost scheduler (`dynamo_tpu.router`)
- JAX/XLA/Pallas inference engine with paged KV cache and continuous
  batching over `jax.sharding.Mesh` (`dynamo_tpu.engine`, `dynamo_tpu.models`,
  `dynamo_tpu.ops`, `dynamo_tpu.parallel`)
- Disaggregated prefill/decode with KV transfer over ICI/DCN (`dynamo_tpu.disagg`)
- Multi-tier KV block manager HBM -> host DRAM -> disk (`dynamo_tpu.kvbm`)
- Load/SLA autoscaling planner (`dynamo_tpu.planner`)
"""

__version__ = "0.1.0"

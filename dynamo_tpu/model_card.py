"""Model deployment cards + model registry.

A ModelDeploymentCard is the canonical metadata for a served model
(tokenizer spec, context window, eos ids); workers publish it once
(card body in the fabric object store, entry key under MODEL_ROOT bound to
the worker's lease) and frontends attach models dynamically from a prefix
watch. Parity: reference ModelDeploymentCard (lib/llm/src/model_card/
model.rs:86, move_to_nats :230) + ModelWatcher/MODEL_ROOT_PATH
(discovery/watcher.rs:69).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import msgpack

from dynamo_tpu.runtime.component import MODEL_ROOT

CARD_OBJ_PREFIX = "cards/"


@dataclass
class ModelDeploymentCard:
    name: str
    tokenizer: dict = field(default_factory=lambda: {"kind": "byte"})
    context_length: int = 4096
    eos_token_ids: tuple[int, ...] = (0,)
    kv_page_size: int = 64
    chat_capable: bool = True
    extra: dict[str, Any] = field(default_factory=dict)

    def pack(self) -> bytes:
        d = dict(self.__dict__)
        d["eos_token_ids"] = list(self.eos_token_ids)
        return msgpack.packb(d, use_bin_type=True)

    @staticmethod
    def unpack(data: bytes) -> "ModelDeploymentCard":
        d = msgpack.unpackb(data, raw=False)
        d["eos_token_ids"] = tuple(d.get("eos_token_ids", ()))
        return ModelDeploymentCard(**d)


@dataclass
class ModelEntry:
    """MODEL_ROOT entry: which component serves this model."""

    model: str
    namespace: str
    component: str
    endpoint: str
    card_object: str
    router_mode: str = "round_robin"

    def pack(self) -> bytes:
        return msgpack.packb(dict(self.__dict__), use_bin_type=True)

    @staticmethod
    def unpack(data: bytes) -> "ModelEntry":
        return ModelEntry(**msgpack.unpackb(data, raw=False))


def model_key(model: str, instance_suffix: str = "") -> str:
    return f"{MODEL_ROOT}/{model}" + (f"/{instance_suffix}" if instance_suffix else "")


async def register_llm(
    fabric,
    card: ModelDeploymentCard,
    namespace: str,
    component: str,
    endpoint: str,
    lease_id: Optional[str] = None,
    router_mode: str = "round_robin",
    publish_card: bool = True,
) -> ModelEntry:
    """Publish card + model entry (reference: register_llm — _core.pyi:838).

    publish_card=False registers the entry against the EXISTING card object
    (ctl add on a live model must not clobber the workers' real card)."""
    obj = CARD_OBJ_PREFIX + card.name
    if publish_card:
        await fabric.obj_put(obj, card.pack())
    entry = ModelEntry(
        model=card.name,
        namespace=namespace,
        component=component,
        endpoint=endpoint,
        card_object=obj,
        router_mode=router_mode,
    )
    suffix = lease_id or ""
    await fabric.put(model_key(card.name, suffix), entry.pack(), lease_id=lease_id)
    return entry


async def load_card(fabric, entry: ModelEntry) -> ModelDeploymentCard:
    data = await fabric.obj_get(entry.card_object)
    if data is None:
        raise KeyError(f"card object {entry.card_object} missing")
    return ModelDeploymentCard.unpack(data)

"""ctypes loader for libdynamo_native (native/ — the C++ hot-path core).

Follows the environment's binding constraints (no pybind11): a plain C ABI
loaded with ctypes. Set DYNTPU_NO_NATIVE=1 to force the pure-Python
fallbacks everywhere.

Build discipline:
- `ensure_built()` — blocking compile+load; call it once from process entry
  points (CLI/worker startup) before serving.
- `lib()` — never blocks the caller on a compile: returns the loaded CDLL,
  or None while a background build (started on first miss) is running.
  Callers must keep a Python fallback path (tokens/blocks.py,
  kv_router/indexer.py do).
- Builds are cross-process safe: compiled under an flock to a temp name in
  native/build/, then os.replace'd into place so a concurrent loader never
  dlopens a half-written ELF.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

logger = logging.getLogger(__name__)

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "libdynamo_native.so"
_SOURCES = [
    _NATIVE_DIR / "dynamo_native.cpp",
    _NATIVE_DIR / "pool.cpp",
    _NATIVE_DIR / "host_tier.cpp",
    _NATIVE_DIR / "codec.cpp",
    _NATIVE_DIR / "kv_events.cpp",
    _NATIVE_DIR / "xxh3.h",
]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_thread: Optional[threading.Thread] = None
_build_failed = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, u32, sz = ctypes.c_uint64, ctypes.c_uint32, ctypes.c_size_t
    p = ctypes.c_void_p
    lib.dyn_xxh3_64.restype = u64
    lib.dyn_xxh3_64.argtypes = [ctypes.c_char_p, sz, u64]
    lib.dyn_hash_token_blocks.restype = sz
    lib.dyn_hash_token_blocks.argtypes = [p, sz, sz, u64, u64, p, p]
    lib.dyn_radix_new.restype = p
    lib.dyn_radix_free.argtypes = [p]
    lib.dyn_radix_intern.restype = u32
    lib.dyn_radix_intern.argtypes = [p, ctypes.c_char_p]
    lib.dyn_radix_apply.argtypes = [p, u32, ctypes.c_int, p, sz]
    lib.dyn_radix_remove_worker.restype = sz
    lib.dyn_radix_remove_worker.argtypes = [p, u32]
    lib.dyn_radix_take_worker.restype = sz
    lib.dyn_radix_take_worker.argtypes = [p, u32, p, sz]
    lib.dyn_radix_digest.restype = sz
    lib.dyn_radix_digest.argtypes = [p, u32, u64, p]
    lib.dyn_radix_clear.argtypes = [p]
    lib.dyn_radix_find.restype = sz
    lib.dyn_radix_find.argtypes = [p, p, sz, p, p, sz, p]
    lib.dyn_radix_num_blocks.restype = sz
    lib.dyn_radix_num_blocks.argtypes = [p]
    lib.dyn_radix_blocks_for.restype = sz
    lib.dyn_radix_blocks_for.argtypes = [p, u32]
    lib.dyn_radix_events_applied.restype = u64
    lib.dyn_radix_events_applied.argtypes = [p]
    # pool.cpp — device page pool
    i64 = ctypes.c_int64
    lib.dyn_pool_new.restype = p
    lib.dyn_pool_new.argtypes = [u32]
    lib.dyn_pool_delete.argtypes = [p]
    lib.dyn_pool_num_free.restype = sz
    lib.dyn_pool_num_free.argtypes = [p]
    lib.dyn_pool_free_list_len.restype = sz
    lib.dyn_pool_free_list_len.argtypes = [p]
    lib.dyn_pool_peek_reclaimable.restype = sz
    lib.dyn_pool_peek_reclaimable.argtypes = [p, p, sz]
    lib.dyn_pool_allocate.restype = ctypes.c_int
    lib.dyn_pool_allocate.argtypes = [p, sz, p]
    lib.dyn_pool_release.restype = i64
    lib.dyn_pool_release.argtypes = [p, p, sz]
    lib.dyn_pool_register.restype = ctypes.c_int
    lib.dyn_pool_register.argtypes = [p, u32, u64]
    lib.dyn_pool_lookup.restype = sz
    lib.dyn_pool_lookup.argtypes = [p, p, sz, p]
    lib.dyn_pool_match_length.restype = sz
    lib.dyn_pool_match_length.argtypes = [p, p, sz]
    lib.dyn_pool_clear_cache.restype = sz
    lib.dyn_pool_clear_cache.argtypes = [p]
    lib.dyn_pool_evicted_pending.restype = sz
    lib.dyn_pool_evicted_pending.argtypes = [p]
    lib.dyn_pool_drain_evicted.restype = sz
    lib.dyn_pool_drain_evicted.argtypes = [p, p, p, sz]
    # host_tier.cpp — KVBM G2 slab store
    lib.dyn_host_new.restype = p
    lib.dyn_host_new.argtypes = [u64, u64, ctypes.c_int]
    lib.dyn_host_delete.argtypes = [p]
    lib.dyn_host_len.restype = sz
    lib.dyn_host_len.argtypes = [p]
    lib.dyn_host_used_bytes.restype = u64
    lib.dyn_host_used_bytes.argtypes = [p]
    lib.dyn_host_capacity_slots.restype = u64
    lib.dyn_host_capacity_slots.argtypes = [p]
    lib.dyn_host_contains.restype = ctypes.c_int
    lib.dyn_host_contains.argtypes = [p, u64]
    lib.dyn_host_peek_lru.restype = u64
    lib.dyn_host_peek_lru.argtypes = [p, p]
    lib.dyn_host_reserve.restype = p
    lib.dyn_host_reserve.argtypes = [p, u64]
    lib.dyn_host_get.restype = p
    lib.dyn_host_get.argtypes = [p, u64]
    lib.dyn_host_pop.restype = ctypes.c_int
    lib.dyn_host_pop.argtypes = [p, u64]
    lib.dyn_host_clear.argtypes = [p]
    # codec.cpp — two-part frame codec
    lib.dyn_frame_prefix.argtypes = [p, sz, p, sz, p]
    lib.dyn_frame_parse_prefix.restype = ctypes.c_int
    lib.dyn_frame_parse_prefix.argtypes = [p, p, p]
    lib.dyn_frame_check.restype = ctypes.c_int
    lib.dyn_frame_check.argtypes = [p, p, sz, p, sz]
    # kv_events.cpp — external-engine KV-event publisher
    lib.dyn_kv_pub_connect.restype = p
    lib.dyn_kv_pub_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
    ]
    lib.dyn_kv_pub_publish.restype = ctypes.c_int
    lib.dyn_kv_pub_publish.argtypes = [p, ctypes.c_int, p, sz, i64]
    lib.dyn_kv_pub_last_error.restype = ctypes.c_char_p
    lib.dyn_kv_pub_last_error.argtypes = [p]
    lib.dyn_kv_pub_close.argtypes = [p]
    return lib


def _stale() -> bool:
    if not _LIB_PATH.exists():
        return True
    mtime = _LIB_PATH.stat().st_mtime
    return any(s.exists() and s.stat().st_mtime > mtime for s in _SOURCES)


def _build() -> bool:
    """Compile under an inter-process lock; atomic rename into place."""
    build_dir = _NATIVE_DIR / "build"
    tmp = build_dir / f".tmp.{os.getpid()}.so"
    try:
        build_dir.mkdir(parents=True, exist_ok=True)
        lock_path = build_dir / ".build.lock"
        with open(lock_path, "w") as lock_f:
            import fcntl

            fcntl.flock(lock_f, fcntl.LOCK_EX)
            try:
                if not _stale():  # another process built it while we waited
                    return True
                proc = subprocess.run(
                    ["make", "-s", "-C", str(_NATIVE_DIR),
                     f"LIB=build/{tmp.name}"],
                    capture_output=True, text=True, timeout=180,
                )
                if proc.returncode != 0:
                    logger.warning("native build failed:\n%s", proc.stderr[-2000:])
                    return False
                os.replace(tmp, _LIB_PATH)
                return True
            finally:
                tmp.unlink(missing_ok=True)
                fcntl.flock(lock_f, fcntl.LOCK_UN)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build unavailable: %s", e)
        tmp.unlink(missing_ok=True)
        return False


def _load() -> Optional[ctypes.CDLL]:
    """Must be called with _lock held. Latches failure: a present-but-
    unloadable .so (corrupt/ABI mismatch) must not be retried per request."""
    global _lib, _build_failed
    try:
        _lib = _configure(ctypes.CDLL(str(_LIB_PATH)))
    except (OSError, AttributeError) as e:
        # AttributeError: a .so from an older source revision is missing
        # newly-declared symbols (git checkouts can leave mtimes that
        # defeat _stale's strict >) — same contract as unloadable: return
        # None, pure-Python fallbacks cover the gap
        logger.warning("could not load %s: %s", _LIB_PATH, e)
        _lib = None
        _build_failed = True
    return _lib


def ensure_built(timeout_s: float = 180.0) -> Optional[ctypes.CDLL]:
    """Blocking build+load. Call from process entry points before serving."""
    global _build_failed
    if os.environ.get("DYNTPU_NO_NATIVE"):
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        t = _build_thread
    if t is not None:
        t.join(timeout=timeout_s)
    # Compile OUTSIDE _lock: concurrent lib() callers must stay non-blocking
    # (they fall back to Python while this thread builds). _build() itself is
    # flock-serialized, so parallel ensure_built calls don't race the .so.
    built = (not _stale()) or _build()
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not built:
            _build_failed = True
            return None
        return _load()


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None. Never compiles on the caller's
    thread: a stale/missing .so kicks off one background build and this
    returns None until it lands (pure-Python fallbacks cover the gap)."""
    global _build_thread, _build_failed
    if os.environ.get("DYNTPU_NO_NATIVE"):
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not _stale():
            return _load()
        if _build_thread is None or not _build_thread.is_alive():

            def _bg():
                global _build_failed
                ok = _build()
                with _lock:
                    if ok:
                        _load()  # latches _build_failed itself on error
                    else:
                        _build_failed = True

            _build_thread = threading.Thread(
                target=_bg, name="dynamo-native-build", daemon=True
            )
            _build_thread.start()
        return None

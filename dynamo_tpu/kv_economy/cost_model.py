"""CostModel: one pricing function for every KV move in the fleet.

Moving a cached prefix instead of recomputing it trades TRANSFER bytes
against PREFILL flops. The exchange rate is deterministic from the
model shape and the canonical quantized wire format:

- bytes moved  = blocks · block_bytes          (wire bytes per block)
- flops saved  = 2 · P · T                     (P params, T cached tokens)

`_handover_ab` (bench.py) has priced whole-worker handovers with these
exact formulas since PR 12; this module factors them out so the router
(per-request migration), the planner (flip vs handover vs migration),
and the bench all consult ONE function — a threshold change moves every
consumer at once.

Tier residency discounts the same way: a block parked in host or disk
is worth less than an HBM-resident one because promoting it back costs
tier-bandwidth seconds. `tier_discount` prices that against the prefill
seconds the block saves, yielding a [0, 1] multiplier for the indexer's
warmth scores.

Everything here is pure arithmetic — no I/O, no clocks — so the modeled
quantities the acceptance tests pin are deterministic by construction.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Optional

#: flops-saved per byte-moved below which a migration is NOT worth it.
#: 1.0 = break even against a (pessimistic) 1 flop/s-per-byte/s fabric;
#: real blocks sit orders of magnitude above this (a page_size=16 block
#: of a 1B model saves 2·1e9·16 flops for ~100KB moved ≈ 3e5 flops/B),
#: so the threshold only suppresses degenerate moves — tiny models,
#: huge pages, or a single-block delta on a fat-KV config.
DEFAULT_MIN_FLOPS_PER_BYTE = float(
    os.environ.get("DYN_KV_ECONOMY_MIN_FLOPS_PER_BYTE", "1.0")
)

#: default tier bandwidths for promotion pricing (bytes/s): host slab
#: memcpy vs NVMe read — deliberately conservative, overridable per
#: CostModel instance
HOST_TIER_BYTES_PER_S = 8e9
DISK_TIER_BYTES_PER_S = 1e9

#: default sustained prefill rate used to convert saved flops into saved
#: seconds for tier discounting (order v5e bf16; only the RATIO against
#: tier bandwidth matters, so coarse is fine)
PREFILL_FLOPS_PER_S = 1e14


@dataclass(frozen=True)
class MigrationPrice:
    """One priced KV move."""

    blocks: int
    bytes_moved: int
    cached_tokens: int
    flops_saved: int

    @property
    def flops_saved_per_byte(self) -> float:
        if self.bytes_moved <= 0:
            return 0.0
        return self.flops_saved / self.bytes_moved


@dataclass(frozen=True)
class CostModel:
    """Pricing for KV movement, fixed by model + wire shape.

    `params` is the model parameter count P; `block_bytes` the canonical
    quantized wire bytes of ONE KV block (k.nbytes + v.nbytes per
    block); `page_size` tokens per block.
    """

    params: int
    block_bytes: int
    page_size: int
    min_flops_per_byte: float = DEFAULT_MIN_FLOPS_PER_BYTE
    #: migrations below this many blocks never pay for their fixed
    #: offer/transfer round trips
    min_blocks: int = 2
    host_bytes_per_s: float = HOST_TIER_BYTES_PER_S
    disk_bytes_per_s: float = DISK_TIER_BYTES_PER_S
    prefill_flops_per_s: float = PREFILL_FLOPS_PER_S

    # -- the PR 12 handover accounting, verbatim ---------------------------

    def flops_saved(self, cached_tokens: int) -> int:
        """Standard 2·P·T prefill flops over the cached tokens."""
        return 2 * self.params * cached_tokens

    def bytes_moved(self, blocks: int) -> int:
        return blocks * self.block_bytes

    def price(self, blocks: int) -> MigrationPrice:
        """Price moving `blocks` prefix blocks (bytes out, flops back)."""
        cached_tokens = blocks * self.page_size
        return MigrationPrice(
            blocks=blocks,
            bytes_moved=self.bytes_moved(blocks),
            cached_tokens=cached_tokens,
            flops_saved=self.flops_saved(cached_tokens),
        )

    def worth_it(self, price: MigrationPrice) -> bool:
        """Does the prefill saved pay for the bytes moved at the
        configured exchange rate?"""
        if price.blocks < self.min_blocks:
            return False
        return price.flops_saved_per_byte >= self.min_flops_per_byte

    def should_migrate(self, delta_blocks: int) -> bool:
        """Router entry point: migrate when the REMOTE worker's extra
        `delta_blocks` of prefix (beyond what the chosen worker holds)
        saves more flops than its bytes cost to move."""
        return delta_blocks > 0 and self.worth_it(self.price(delta_blocks))

    # -- modeled TTFT (the deterministic bench/acceptance quantity) --------

    @staticmethod
    def modeled_ttft_ratio(
        total_tokens: int, cached_tokens: int, prefill_chunk: int
    ) -> float:
        """Warm/cold TTFT as prefill-chunk dispatches skipped: the warm
        continuation prefills only the uncached tail. Deterministic from
        the workload shape — the pinned contract number (bench.py
        handover_ab / prefix_migration_ab)."""
        uncached = total_tokens - cached_tokens
        chunks_cold = math.ceil(total_tokens / prefill_chunk)
        chunks_warm = max(1, math.ceil(uncached / prefill_chunk))
        return chunks_warm / max(1, chunks_cold)

    # -- tier discounting --------------------------------------------------

    def tier_discount(self, tier: Optional[str]) -> float:
        """Warmth multiplier for a block resident in `tier`: the share
        of a block's prefill savings left after paying its promotion.
        HBM (None/"device") costs nothing to use → 1.0; host/disk divide
        the saved seconds by saved + promote seconds."""
        if tier in (None, "", "device", "hbm"):
            return 1.0
        bw = {
            "host": self.host_bytes_per_s,
            "disk": self.disk_bytes_per_s,
        }.get(tier)
        if bw is None or bw <= 0:
            return 0.0
        saved_s = self.flops_saved(self.page_size) / self.prefill_flops_per_s
        promote_s = self.block_bytes / bw
        if saved_s <= 0:
            return 0.0
        return saved_s / (saved_s + promote_s)


def block_wire_bytes(
    layers: int, kv_heads: int, page_size: int, head_dim: int, itemsize: int
) -> int:
    """Canonical wire bytes of one block ([L, Hkv, S, D] k + v) — for
    callers that know the model shape but have no exported batch to
    measure (router-side CostModel construction)."""
    return 2 * layers * kv_heads * page_size * head_dim * itemsize


#: fallback model shape for cards that don't publish one (a 1B-class
#: config); only the params/block_bytes RATIO gates migrations, and any
#: transformer's ratio clears the break-even threshold by orders of
#: magnitude, so coarse defaults never flip a decision the shape-aware
#: path would make differently
_DEFAULT_SHAPE = {
    "params": 1_000_000_000,
    "layers": 16,
    "kv_heads": 8,
    "head_dim": 64,
    "kv_itemsize": 1,  # canonical wire format is quantized int8
}


def cost_model_from_card(card) -> CostModel:
    """Build the router-side CostModel from a ModelDeploymentCard.

    Workers that publish their shape in `card.extra` (params, layers,
    kv_heads, head_dim, kv_itemsize) get exact pricing; others get the
    1B-class defaults above."""
    extra = getattr(card, "extra", None) or {}

    def _num(key: str) -> int:
        try:
            v = int(extra.get(key) or 0)
        except (TypeError, ValueError):
            v = 0
        return v if v > 0 else _DEFAULT_SHAPE[key]

    page_size = int(getattr(card, "kv_page_size", 0) or 0) or 16
    return CostModel(
        params=_num("params"),
        block_bytes=block_wire_bytes(
            _num("layers"), _num("kv_heads"), page_size,
            _num("head_dim"), _num("kv_itemsize"),
        ),
        page_size=page_size,
    )

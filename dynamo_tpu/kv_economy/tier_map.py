"""TierMap: router-side view of which blocks live in LOWER tiers where.

The indexer (kv_router/indexer.py) scores workers by device-resident
prefix depth, fed by the sequenced kv_events stream. Blocks a worker
demoted to host/disk left that stream (`removed`) but are still
servable — the worker re-onboards them on a prefix hit, paying the
tier's promotion bandwidth. This map rides the existing advisory
`kvbm_tier.{instance_id}` hint subjects (the same ones the worker-side
BlockDirectory consumes) and answers, per (worker, hash), WHICH tier
holds it — so the router can extend a worker's warmth score past its
HBM with `CostModel.tier_discount(tier)` applied.

Same trust model as BlockDirectory: hints are stores-only, LRU-capped,
and best-effort — a stale entry costs one discounted score, never
correctness (the worker re-checks its tiers at admission).
"""

from __future__ import annotations

import asyncio
import logging
from collections import OrderedDict
from typing import Optional, Sequence

import msgpack

from dynamo_tpu.subjects import KVBM_TIER_SUBJECT

logger = logging.getLogger(__name__)

#: per-worker (hash -> tier) LRU bound
MAX_HASHES_PER_WORKER = 200_000


class _TierLru:
    def __init__(self, cap: int):
        self.cap = cap
        self._d: OrderedDict[int, str] = OrderedDict()

    def put(self, h: int, tier: str) -> None:
        self._d[h] = tier
        self._d.move_to_end(h)
        while len(self._d) > self.cap:
            self._d.popitem(last=False)

    def get(self, h: int) -> Optional[str]:
        return self._d.get(h)

    def discard(self, h: int) -> None:
        self._d.pop(h, None)

    def __len__(self) -> int:
        return len(self._d)


class TierMap:
    def __init__(self, fabric, cap_per_worker: int = MAX_HASHES_PER_WORKER):
        self.fabric = fabric
        self.cap = cap_per_worker
        self._workers: dict[str, _TierLru] = {}
        self._sub = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._sub = await self.fabric.subscribe(KVBM_TIER_SUBJECT + ".>")
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                return
            try:
                worker_id = msg.header["instance_id"]
                events = msgpack.unpackb(msg.payload, raw=False)
                lru = self._workers.get(worker_id)
                if lru is None:
                    lru = self._workers[worker_id] = _TierLru(self.cap)
                for ev in events:
                    if ev.get("kind") != "stored":
                        continue
                    # pre-economy hints carry no tier field; host is the
                    # first stop of every demotion, so it is the honest
                    # default for an untagged store
                    tier = ev.get("tier") or "host"
                    for h in ev["block_hashes"]:
                        lru.put(h, tier)
            except Exception:
                logger.exception("bad tier hint on %s", msg.subject)

    # -- queries -----------------------------------------------------------

    def tier_of(self, worker_id: str, h: int) -> Optional[str]:
        lru = self._workers.get(worker_id)
        return lru.get(h) if lru is not None else None

    def chain_tiers(
        self, worker_id: str, seq_hashes: Sequence[int], start: int
    ) -> list[str]:
        """Tiers of the consecutive run of `seq_hashes[start:]` this
        worker holds in lower tiers (stops at the first miss)."""
        lru = self._workers.get(worker_id)
        out: list[str] = []
        if lru is None:
            return out
        for h in seq_hashes[start:]:
            tier = lru.get(h)
            if tier is None:
                break
            out.append(tier)
        return out

    def drop(self, worker_id: str, hashes: Sequence[int]) -> None:
        lru = self._workers.get(worker_id)
        if lru is not None:
            for h in hashes:
                lru.discard(h)

    def retain_workers(self, live: Sequence[str]) -> None:
        keep = set(live)
        for w in list(self._workers):
            if w not in keep:
                del self._workers[w]

    def stats(self) -> dict:
        return {
            "tier_workers": len(self._workers),
            "tier_hashes": sum(len(v) for v in self._workers.values()),
        }

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()

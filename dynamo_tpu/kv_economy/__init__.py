"""The KV economy: the fleet's aggregate HBM + host + disk as ONE cache.

PR 12's handover path ships arbitrary KV block forests between workers
in the canonical quantized wire format; PR 13's sequenced, digest-
verified index gives the router an honest global view of who holds
what. This package is the POLICY plane that composes them (ROADMAP
item 3 — the Dynamo-KVBM multi-tier block-management story; Mooncake's
KVCache-centric scheduling; CachedAttention's hierarchical KV reuse):

- `CostModel` — the bytes-moved vs 2·P·T prefill-flops-saved pricing,
  factored out of bench.py's `_handover_ab` so router, planner, and
  bench price a KV move with ONE function.
- `MigrationManager` — admission control for per-prefix migrations:
  single-flight per (prefix, destination), per-prefix backoff, byte
  budget — migration storms cannot starve decode.
- `TierPolicy` — demotes cold pages HBM→host→disk under watermark
  pressure through the existing KVBM tiers (kvbm/manager.py).
- `TierMap` — router-side tier-residency view (which peer holds which
  block in a LOWER tier), fed by the same kvbm_tier.* hint subjects
  the worker-side BlockDirectory consumes, so the indexer's warmth
  scores can be discounted by promotion cost.

Everything here is optional and default-off: with no `economy` object
handed to the router and no TierPolicy loop started, every byte on the
wire and every routing decision is identical to the pre-economy tree
(pinned by tests/test_kv_economy.py).
"""

from dynamo_tpu.kv_economy.cost_model import (
    CostModel,
    MigrationPrice,
    block_wire_bytes,
    cost_model_from_card,
)
from dynamo_tpu.kv_economy.migration import MigrationManager
from dynamo_tpu.kv_economy.router import EconomyPolicy
from dynamo_tpu.kv_economy.tier_map import TierMap
from dynamo_tpu.kv_economy.tier_policy import TierPolicy

__all__ = [
    "CostModel",
    "EconomyPolicy",
    "MigrationPrice",
    "MigrationManager",
    "TierMap",
    "TierPolicy",
    "block_wire_bytes",
    "cost_model_from_card",
]

"""EconomyPolicy: the bundle a KV-economy router carries.

One object glues the three policy pieces to the routing hot path:
`cost_model` prices moves, `manager` throttles them, `tier_map`
(optional) extends warmth scores into lower tiers. Handing an
EconomyPolicy to KvRouter(economy=...) switches the economy ON for
that router; the default None keeps find_best_match bit-identical to
the pre-economy tree (pinned by tests/test_kv_economy.py).
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.kv_economy.cost_model import CostModel
from dynamo_tpu.kv_economy.migration import MigrationManager
from dynamo_tpu.kv_economy.tier_map import TierMap

#: router-side wall clock bound on one migration round trip — past it
#: the request cold-prefills (the transfer may still land and warm the
#: NEXT request; the source/dest cleanup paths own their pages)
DEFAULT_MIGRATE_TIMEOUT_S = 10.0


class EconomyPolicy:
    def __init__(
        self,
        cost_model: CostModel,
        manager: Optional[MigrationManager] = None,
        tier_map: Optional[TierMap] = None,
        migrate_timeout_s: float = DEFAULT_MIGRATE_TIMEOUT_S,
    ):
        self.cost_model = cost_model
        self.manager = manager or MigrationManager()
        self.tier_map = tier_map
        self.migrate_timeout_s = migrate_timeout_s

    def scored_with_tiers(
        self, scores: dict[str, int], candidates, seq_hashes
    ) -> dict[str, float]:
        """Overlap scores extended past HBM: each candidate's device-
        resident depth continues through its lower-tier chain, every
        tiered block discounted by its promotion cost. Returns a COPY —
        the indexer's scores are never mutated."""
        if self.tier_map is None:
            return dict(scores)
        cm = self.cost_model
        out: dict[str, float] = dict(scores)
        for iid in candidates:
            base = scores.get(iid, 0)
            tiers = self.tier_map.chain_tiers(iid, seq_hashes, base)
            if tiers:
                out[iid] = base + sum(cm.tier_discount(t) for t in tiers)
        return out

    def stats(self) -> dict:
        out = self.manager.stats()
        if self.tier_map is not None:
            out.update(self.tier_map.stats())
        return out

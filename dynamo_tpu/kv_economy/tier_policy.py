"""TierPolicy: demote cold pages down the KVBM hierarchy under pressure.

The device page pool is fixed-size; what watermark pressure actually
costs is EVICTION LATENCY on the allocation path — a full pool makes
every admission wait on a synchronous offload of its LRU victims. The
policy converts that into background work: when the pool's truly-free
slots fall below the high watermark, it write-backs the coldest
reclaimable pages into host/disk AHEAD of eviction (the tiered
allocator skips re-offloading anything already tier-resident), so
later evictions drop device copies for free and the content stays
servable from the lower tiers.

Runs wherever the engine thread can call it — the worker drives
`run_once` on its publish cadence through the engine runner. Pure
policy: all mechanism lives in kvbm/manager.py.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

#: start demoting when fewer than (1-high)·pages slots are truly free
DEFAULT_HIGH_WATERMARK = 0.90
#: demote enough cold pages to restore (1-low)·pages free slots
DEFAULT_LOW_WATERMARK = 0.75
#: per-tick demotion cap (bounds the staged-gather HBM + host copies)
MAX_DEMOTE_PER_TICK = 64


class TierPolicy:
    def __init__(
        self,
        allocator,
        high_watermark: float = DEFAULT_HIGH_WATERMARK,
        low_watermark: float = DEFAULT_LOW_WATERMARK,
        max_per_tick: int = MAX_DEMOTE_PER_TICK,
    ):
        if not (0.0 < low_watermark <= high_watermark <= 1.0):
            raise ValueError(
                f"need 0 < low ({low_watermark}) <= high "
                f"({high_watermark}) <= 1"
            )
        self.allocator = allocator
        self.high = high_watermark
        self.low = low_watermark
        self.max_per_tick = max_per_tick
        self.demote_ticks = 0

    def pressure(self) -> float:
        """Fraction of the pool NOT on the free list (allocated or
        cached): 1.0 = every admission must evict."""
        alloc = self.allocator
        total = alloc.num_pages - 1
        if total <= 0:
            return 0.0
        return 1.0 - alloc._free_slots() / total

    def run_once(self) -> int:
        """One policy tick: newly demoted blocks (0 when below the high
        watermark or nothing cold is left to demote)."""
        alloc = self.allocator
        if not getattr(alloc, "_offload_enabled", False):
            return 0
        p = self.pressure()
        if p < self.high:
            return 0
        total = alloc.num_pages - 1
        want = min(self.max_per_tick, max(1, int((p - self.low) * total)))
        n = alloc.demote(want)
        if n:
            self.demote_ticks += 1
            logger.debug(
                "tier policy: demoted %d cold block(s) at pressure %.2f",
                n, p,
            )
        return n

"""MigrationManager: admission control for per-prefix KV migrations.

The router's migration decision is cheap to WANT and expensive to DO:
an unthrottled hot prefix would be pulled to every worker the selector
ever picks, saturating the transfer plane and starving decode. This
manager is the throttle, in admission order:

1. **single-flight** — one in-flight migration per (prefix, dest);
   concurrent requests for the same pull ride the first one's outcome
   (their request cold-prefills meanwhile, which is always correct).
2. **backoff** — a prefix that just migrated (anywhere) is not moved
   again inside `backoff_s`; repeats inside the window are counted as
   storm repeats (the doctor's `migration-storm` rule reads them).
3. **concurrency + byte budget** — global caps so a burst of distinct
   prefixes still cannot monopolize the transfer plane.

Deny is always safe: the request cold-prefills exactly as it would
have pre-economy. Time is injected (`clock`) so tests are
deterministic.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

#: seconds a migrated prefix is fenced against re-migration
DEFAULT_BACKOFF_S = 30.0
#: concurrent in-flight migrations fleet-wide (per router)
DEFAULT_MAX_INFLIGHT = 2
#: byte budget per rolling window (0 = unlimited)
DEFAULT_WINDOW_BYTES = 256 * 1024 * 1024
DEFAULT_WINDOW_S = 10.0


class MigrationManager:
    def __init__(
        self,
        backoff_s: float = DEFAULT_BACKOFF_S,
        max_inflight: int = DEFAULT_MAX_INFLIGHT,
        window_bytes: int = DEFAULT_WINDOW_BYTES,
        window_s: float = DEFAULT_WINDOW_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.backoff_s = backoff_s
        self.max_inflight = max_inflight
        self.window_bytes = window_bytes
        self.window_s = window_s
        self._clock = clock
        #: (prefix_key, dest) in flight right now
        self._inflight: set[tuple[int, str]] = set()
        #: prefix_key -> monotonic stamp of its last COMPLETED migration
        self._last_done: dict[int, float] = {}
        #: (stamp, bytes) of recent completions for the byte budget
        self._window: list[tuple[float, int]] = []
        # counters (worker/router metrics frames + doctor evidence)
        self.migrations_total = 0
        self.migrations_failed = 0
        self.bytes_total = 0
        self.blocks_total = 0
        self.suppressed: dict[str, int] = {}
        #: same-prefix attempts landing inside the backoff window — the
        #: thrash signal `migration-storm` alerts on
        self.storm_repeats = 0

    # -- admission ---------------------------------------------------------

    def _window_spend(self, now: float) -> int:
        self._window = [
            (t, b) for t, b in self._window if now - t <= self.window_s
        ]
        return sum(b for _, b in self._window)

    def admit(
        self, prefix_key: int, dest: str, est_bytes: int = 0
    ) -> tuple[bool, str]:
        """Try to claim (prefix, dest). Returns (admitted, reason);
        an admitted claim MUST be released via complete()."""
        now = self._clock()
        key = (prefix_key, dest)
        if key in self._inflight:
            return self._deny("inflight")
        last = self._last_done.get(prefix_key)
        if last is not None and now - last < self.backoff_s:
            self.storm_repeats += 1
            return self._deny("backoff")
        if len(self._inflight) >= self.max_inflight:
            return self._deny("concurrency")
        if self.window_bytes and (
            self._window_spend(now) + est_bytes > self.window_bytes
        ):
            return self._deny("budget")
        self._inflight.add(key)
        return True, "ok"

    def _deny(self, reason: str) -> tuple[bool, str]:
        self.suppressed[reason] = self.suppressed.get(reason, 0) + 1
        return False, reason

    def complete(
        self,
        prefix_key: int,
        dest: str,
        ok: bool,
        bytes_moved: int = 0,
        blocks: int = 0,
    ) -> None:
        """Release the single-flight claim; account the outcome. Failed
        migrations ALSO start the backoff window — retrying a broken
        transfer every request is the storm we're preventing."""
        now = self._clock()
        self._inflight.discard((prefix_key, dest))
        self._last_done[prefix_key] = now
        if len(self._last_done) > 10_000:  # memory backstop
            cutoff = now - self.backoff_s
            self._last_done = {
                k: t for k, t in self._last_done.items() if t >= cutoff
            }
        if ok:
            self.migrations_total += 1
            self.bytes_total += bytes_moved
            self.blocks_total += blocks
            if bytes_moved:
                self._window.append((now, bytes_moved))
        else:
            self.migrations_failed += 1

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        return {
            "migrations_total": self.migrations_total,
            "migrations_failed_total": self.migrations_failed,
            "migration_bytes_total": self.bytes_total,
            "migration_blocks_total": self.blocks_total,
            "migration_storm_repeats_total": self.storm_repeats,
            "migrations_inflight": len(self._inflight),
            "migrations_suppressed": dict(self.suppressed),
        }

"""Continuous-batching scheduler.

Replaces what the reference gets for free from vLLM's scheduler (and mirrors
its own mocker's simulation of it — /root/reference lib/llm/src/mocker/
scheduler.rs:197): admission with KV watermark, chunked prefill, decode
batching, and preemption-by-recompute under page pressure.

TPU-first twist: the scheduler's output is always one of a *finite family of
shapes* — a prefill chunk of exactly `prefill_chunk` tokens or a decode batch
padded to a bucket — so the engine runs a handful of XLA programs total.

Policy (one `schedule()` call = one engine step):
1. Admit waiting requests while pages + decode slots allow (prefix-cache
   lookups happen here, so admission cost reflects true page need).
2. If any running request still needs prefill: schedule one prefill chunk
   (packing multiple small prompts up to the token budget).
3. Otherwise schedule a decode batch over all running sequences, growing
   page tables by one page where the next token would overflow; preempt
   the youngest sequences if pages run out.

Decode batches are STABLE between consecutive `schedule()` calls unless
admission, chunked prefill, or a request-side event (finish, abort,
preemption) intervenes — `decode_batch_stable()` states the contract the
engine's overlapped decode pipeline relies on.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Literal, Optional

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.engine.request import FinishReason, Request, RequestState
from dynamo_tpu.tokens import TokenBlockSequence

logger = logging.getLogger(__name__)


class QueueFullError(RuntimeError):
    """Bounded admission (EngineConfig.max_waiting): the waiting queue is
    at capacity. The runner answers 'overloaded' with a Retry-After hint
    instead of queueing forever (docs/operations.md)."""


@dataclass(frozen=True)
class PrefillPiece:
    """One request's token span inside a prefill chunk."""

    request: Request
    start: int  # absolute token index where this piece begins
    length: int


@dataclass(frozen=True)
class ScheduledBatch:
    """`mixed` carries BOTH a prefill chunk and the decode batch — one
    engine step (one fused XLA program) in which every decode row emits
    a token while the prefill backlog drains (EngineConfig.mixed_steps)."""

    kind: Literal["prefill", "decode", "mixed"]
    prefill: tuple[PrefillPiece, ...] = ()
    decode: tuple[Request, ...] = ()

    @property
    def num_tokens(self) -> int:
        if self.kind == "prefill":
            return sum(p.length for p in self.prefill)
        if self.kind == "mixed":
            return sum(p.length for p in self.prefill) + len(self.decode)
        return len(self.decode)


class Scheduler:
    def __init__(self, config: EngineConfig, allocator: PageAllocator):
        self.config = config
        self.allocator = allocator
        #: emit `mixed` steps when both prefill work and running decodes
        #: exist (config.mixed_steps; the engine overrides this to False
        #: on multi-process SPMD meshes and under spec_ngram)
        self.mixed_enabled = config.mixed_steps
        self.waiting: list[Request] = []
        self.running: list[Request] = []
        #: content chains per live request (prefix registration + routing)
        self.chains: dict[str, TokenBlockSequence] = {}
        #: requests that can never make progress (engine finishes them
        #: with the given reason) — guarantees step() liveness instead
        #: of a silent busy-spin
        self.doomed: list[tuple[Request, str, FinishReason]] = []
        #: deadline-expired requests dropped pre-admission (observability)
        self.deadline_drops = 0
        #: pages of finished hold_pages requests, awaiting extraction
        self.held: dict[str, list[int]] = {}
        #: preemption-by-recompute count (page pressure) — exported as
        #: the dynamo_tpu_worker_preemptions_total fleet counter
        self.preemptions = 0

    # -- queue interface ---------------------------------------------------

    def add_request(self, request: Request) -> None:
        # Need ceil((len+1)/ps) pages <= max_pages_per_seq, i.e. room for the
        # prompt plus at least one generated token.
        if len(request.prompt_tokens) >= self.config.max_context:
            raise ValueError(
                f"prompt of {len(request.prompt_tokens)} tokens exceeds max "
                f"context {self.config.max_context} (one slot is reserved for "
                "generation)"
            )
        cap = self.config.max_waiting
        if cap is not None and len(self.waiting) >= cap:
            raise QueueFullError(
                f"waiting queue full ({len(self.waiting)}/{cap} requests); "
                "retry later or on another instance"
            )
        request.state = RequestState.WAITING
        self.waiting.append(request)

    def abort_request(self, request_id: str) -> Optional[Request]:
        for q in (self.waiting, self.running):
            for r in q:
                if r.request_id == request_id:
                    q.remove(r)
                    self._release(r)
                    self.chains.pop(request_id, None)
                    return r
        return None

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def num_waiting(self) -> int:
        return len(self.waiting)

    def can_admit_head(self) -> bool:
        """Whether the waiting-queue head could be admitted right now
        (cheap page-count check; used by the engine to decide if fused
        decode should yield to admission latency)."""
        if not self.waiting or len(self.running) >= self.config.max_seqs:
            return False
        req = self.waiting[0]
        need = -(-(len(req.prompt_tokens) + 1) // self.config.page_size)
        return self.allocator.num_free - need >= self._watermark_pages()

    def num_running(self) -> int:
        return len(self.running)

    def clamp_kstep_window(self, reqs, k: int) -> int:
        """Page-runway guarantee for on-device K-step decode windows
        (EngineConfig.decode_kstep): the fused program writes K tokens
        of KV per row with NO host allocation mid-window, so every page
        the window needs must exist before dispatch. Halve K until the
        whole batch's runway (pages to cover num_tokens + K - 1 per row,
        beyond what each row already holds) fits in the free pool — the
        engine then pre-grows via its normal growth path, which can
        still preempt-by-recompute if a race shrinks the pool. Returns
        the clamped window (>= 1); K=1 needs no runway beyond classic
        stepping's."""
        ps = self.config.page_size
        while k > 1:
            need = 0
            for req in reqs:
                need += max(
                    0,
                    -(-(req.num_tokens + k - 1) // ps) - len(req.pages),
                )
            if need <= self.allocator.num_free:
                return k
            k //= 2
        return 1

    def decode_batch_stable(self) -> bool:
        """The overlap contract (engine `overlap_decode`, docs/engine.md):
        absent request-side events, the NEXT `schedule()` call returns
        the same decode batch iff no waiting request is admissible right
        now and no running request still needs prefill — admission and
        chunked prefill are the only scheduler-side sources of batch
        change. The engine detects the request-side invalidations
        (finish, abort, preemption-recompute) per request at consume
        time; this predicate covers the scheduler side so a speculative
        next-step dispatch is only issued when it has a chance to land."""
        if any(r.state == RequestState.PREFILL for r in self.running):
            return False
        return not (self.waiting and self.can_admit_head())

    def decode_rows_stable(self, reqs) -> bool:
        """Mixed-mode overlap contract: mixed steps COUNT AS decode steps
        for the overlapped pipeline, so a speculative decode dispatch can
        still land as the decode half of the next mixed step — provided
        the decode-row set itself is stable. That holds iff no waiting
        request is admissible right now and the DECODE-state set is
        exactly `reqs` in order (a prefill piece completing its prompt
        joins decode and changes the rows; the engine checks that
        host-side via the pieces before calling)."""
        if self.waiting and self.can_admit_head():
            return False
        decodable = [r for r in self.running if r.state == RequestState.DECODE]
        return len(decodable) == len(reqs) and all(
            a is b for a, b in zip(decodable, reqs)
        )

    # -- the step ----------------------------------------------------------

    def schedule(self) -> Optional[ScheduledBatch]:
        self._admit()
        prefill = self._schedule_prefill()
        if prefill is not None and self.mixed_enabled:
            # Piggyback the decode batch onto the prefill dispatch: one
            # `mixed` step instead of a decode-stalling prefill step.
            # _schedule_decode's side effects (page growth, preemption of
            # the youngest DECODE victim) apply exactly as they would on
            # the decode step the XOR policy runs after the backlog.
            decode = self._schedule_decode()
            if decode is not None:
                return ScheduledBatch(
                    kind="mixed", prefill=prefill.prefill,
                    decode=decode.decode,
                )
        if prefill is not None:
            return prefill
        return self._schedule_decode()

    def _watermark_pages(self) -> int:
        return int(self.allocator.num_pages * self.config.admission_watermark)

    def _drop_expired_waiting(self) -> None:
        """Deadline-expired requests leave the waiting queue BEFORE
        admission: prefill flops are never spent on a client whose
        deadline already passed (docs/operations.md). Error finishes
        ride the doomed drain."""
        if not any(r.deadline for r in self.waiting):
            return
        now = time.time()
        for req in [r for r in self.waiting if r.deadline and now > r.deadline]:
            self.waiting.remove(req)
            self._release(req)  # waiting requests hold no pages; defensive
            self.chains.pop(req.request_id, None)
            self.deadline_drops += 1
            self.doomed.append(
                (req, "deadline expired before admission",
                 FinishReason.ERROR)
            )

    def _admit(self) -> None:
        ps = self.config.page_size
        self._drop_expired_waiting()
        while self.waiting and len(self.running) < self.config.max_seqs:
            req = self.waiting[0]
            # A prompt that can never fit the pool (even with everything else
            # evicted) would block the queue head forever: doom it instead.
            min_need = -(-(len(req.prompt_tokens) + 1) // ps)
            if min_need > (self.allocator.num_pages - 1) - self._watermark_pages():
                self.waiting.pop(0)
                self.doomed.append(
                    (req, f"prompt needs {min_need} pages; pool has "
                          f"{self.allocator.num_pages - 1}",
                     FinishReason.LENGTH)
                )
                continue
            chain = self.chains.get(req.request_id)
            if chain is None:
                chain = TokenBlockSequence(
                    req.prompt_tokens, block_size=ps, salt=self.config.model
                )
                self.chains[req.request_id] = chain
            # Probe the prefix cache to size the true page need. Multimodal
            # prompts bypass it: their placeholder token ids don't identify
            # the image content, so content-addressing would alias
            # different images onto the same hashes.
            use_cache = (
                self.config.enable_prefix_caching and req.mm_embeds is None
            )
            cached_blocks = (
                self.allocator.match_length(chain.sequence_hashes())
                if use_cache
                else 0
            )
            total_pages = -(-(len(req.prompt_tokens) + 1) // ps)
            need = total_pages - cached_blocks
            if self.allocator.num_free - need < self._watermark_pages():
                break  # head-of-line blocking by design (FIFO fairness)
            cached_pages = (
                self.allocator.lookup(chain.sequence_hashes())
                if use_cache
                else []
            )
            # A fully-cached prompt must still recompute its last token so
            # there are logits to sample from: cap the reuse.
            max_reuse = (len(req.prompt_tokens) - 1) // ps
            while len(cached_pages) > max_reuse:
                self.allocator.free([cached_pages.pop()])
            fresh = self.allocator.allocate(total_pages - len(cached_pages))
            if fresh is None:
                self.allocator.free(cached_pages)
                break
            req.pages = cached_pages + fresh
            req.num_cached_prompt_tokens = len(cached_pages) * ps
            req.num_computed_tokens = req.num_cached_prompt_tokens
            req.state = RequestState.PREFILL
            self.waiting.pop(0)
            self.running.append(req)
            # admission latency, per request (preempted requests re-enter
            # the queue and observe their re-admission wait too).
            # arrival_time defaults to 0.0 for directly-constructed
            # Requests (unit tests, tools) — an epoch-sized wait there is
            # garbage, not a measurement
            if req.arrival_time:
                from dynamo_tpu.telemetry import phases

                wait_ms = max(
                    0.0, (time.time() - req.arrival_time) * 1000.0
                )
                if req.trace_id is not None:
                    # traced request: the wait rides the first StepOutput
                    # onto the engine.generate span (timeline breakdown)
                    # and stamps the histogram bucket's exemplar
                    req.queue_wait_ms = wait_ms
                phases.observe(
                    "queue_wait_ms", wait_ms, trace_id=req.trace_id
                )

    def _mixed_max_pieces(self) -> Optional[int]:
        """Piece-count cap for a step that will carry the decode batch:
        the engine samples mixed steps over one combined row space of
        BUCKETED halves (decode bucket + prefill-piece bucket), so the
        cap must be computed in bucket space — the largest power-of-two
        piece bucket that still fits beside the decode bucket inside
        decode_buckets[-1]. (A raw-count cap would let the piece bucket
        round UP past the family.) Always >= 1 so a full decode bucket
        can never starve prefill; that floor is the one case where the
        combined rows exceed the family by the single-piece bucket.
        None = no decodables, no cap."""
        if not self.mixed_enabled:
            return None
        n_dec = sum(
            1 for r in self.running if r.state == RequestState.DECODE
        )
        if not n_dec:
            return None
        cap = self.config.decode_buckets[-1]
        b_dec = self.config.decode_bucket_for(n_dec)
        b_pre = 1
        while b_pre * 2 + b_dec <= cap:
            b_pre *= 2
        return b_pre

    def _prefill_step_budget(self) -> int:
        """Token budget for this prefill step. Adaptive policy: grow
        toward the whole un-prefilled backlog (capped) so a saturation
        burst drains in a few large dispatches — see EngineConfig
        docstrings and docs/PERF.md (saturation-TTFT section)."""
        base = self.config.effective_prefill_budget
        if self.config.prefill_budget_policy != "adaptive":
            return base
        pending = sum(
            len(r.prompt_tokens) - r.num_computed_tokens
            for r in self.running
            if r.state == RequestState.PREFILL
        )
        cap = self.config.effective_prefill_budget_max
        budget = max(base, min(pending, cap))
        max_pieces = self._mixed_max_pieces()
        if max_pieces is not None:
            # A mixed step's combined row count must stay inside the
            # finite shape family: clamp the GROWN budget so it can never
            # pack more pieces than the row-space cap admits (the base
            # budget always stays available).
            budget = min(
                budget, max(base, max_pieces * self.config.prefill_chunk)
            )
        return budget

    def _schedule_prefill(self) -> Optional[ScheduledBatch]:
        # Each piece is capped at prefill_chunk tokens; the step budget
        # spans sequences. The engine groups same-bucket pieces into one
        # batched [B, T] program, so packing many prompts here turns into
        # fewer, larger dispatches rather than serial B=1 launches.
        budget = self._prefill_step_budget()
        ps = self.config.page_size
        max_pieces = self._mixed_max_pieces()
        pieces: list[PrefillPiece] = []
        for req in self.running:
            if req.state != RequestState.PREFILL or budget <= 0:
                continue
            if max_pieces is not None and len(pieces) >= max_pieces:
                break  # mixed row-space cap (see _mixed_max_pieces)
            remaining = len(req.prompt_tokens) - req.num_computed_tokens
            take = min(remaining, self.config.prefill_chunk, budget)
            if take < remaining:
                # Mid-prompt chunks end on page boundaries so every chunk
                # STARTS page-aligned — the Pallas write path lands chunk
                # KV as whole-page DMA runs (ops/kv_update.py invariant).
                take = (take // ps) * ps
            if take <= 0:
                continue
            pieces.append(
                PrefillPiece(request=req, start=req.num_computed_tokens, length=take)
            )
            budget -= take
        if not pieces:
            return None
        return ScheduledBatch(kind="prefill", prefill=tuple(pieces))

    def _schedule_decode(self) -> Optional[ScheduledBatch]:
        decodable = [r for r in self.running if r.state == RequestState.DECODE]
        if not decodable:
            return None
        ps = self.config.page_size
        scheduled: list[Request] = []
        # Oldest first; preemption victims are taken from the youngest.
        for req in decodable:
            if req.state != RequestState.DECODE:
                continue  # preempted by an earlier iteration of this loop
            have = len(req.pages) * ps
            # Writing this step's KV at position num_tokens-1 needs
            # have >= num_tokens; grow exactly when it would not fit.
            # (num_tokens can never exceed max_context here: _accept_token
            # finishes requests at the boundary, so growth is always legal.)
            if req.num_tokens > have:
                got = self.allocator.allocate(1)
                if got is None:
                    if self._preempt_youngest(excluding=req, scheduled=scheduled):
                        got = self.allocator.allocate(1)
                    if got is None:
                        if not scheduled and len(self.running) == 1:
                            # Sole sequence and the pool is exhausted: no
                            # future step can free pages — doom it rather
                            # than busy-spin (engine finishes it as LENGTH).
                            self.running.remove(req)
                            self._release(req)
                            self.chains.pop(req.request_id, None)
                            self.doomed.append(
                                (req, "kv pool exhausted with no preemption "
                                      "victim",
                                 FinishReason.LENGTH)
                            )
                        continue  # stalled this step; others may progress
                req.pages.extend(got)
            scheduled.append(req)
        if not scheduled:
            return None
        cap = self.config.decode_buckets[-1]
        return ScheduledBatch(kind="decode", decode=tuple(scheduled[:cap]))

    def _preempt_youngest(
        self, excluding: Request, scheduled: Optional[list[Request]] = None
    ) -> bool:
        victims = [
            r
            for r in self.running
            if r is not excluding and r.state == RequestState.DECODE
        ]
        if not victims:
            return False
        victim = victims[-1]
        if scheduled is not None and victim in scheduled:
            # Already picked for this step's batch — pull it back out, or it
            # would decode against an empty page table (the null page).
            scheduled.remove(victim)
        logger.warning(
            "preempting %s (recompute) under page pressure", victim.request_id
        )
        self.preemptions += 1
        self._release(victim)
        # Recompute-from-scratch: prompt grows to include generated tokens.
        victim.state = RequestState.WAITING
        victim.num_emitted += len(victim.output_tokens)
        victim.prompt_tokens = victim.all_tokens
        victim.output_tokens = []
        victim.num_computed_tokens = 0
        victim.num_cached_prompt_tokens = 0
        # draft-model speculation: the draft pool's KV for this request
        # lived in the released pages — the re-admission prefill rebuilds
        # both pools from scratch
        victim.spec_draft_pos = 0
        self.running.remove(victim)
        self.waiting.insert(0, victim)
        self.chains.pop(victim.request_id, None)
        return True

    # -- completion --------------------------------------------------------

    def finish(self, request: Request) -> None:
        request.state = RequestState.FINISHED
        if request in self.running:
            self.running.remove(request)
        if request.hold_pages and request.pages:
            self.held[request.request_id] = request.pages
            request.pages = []
        else:
            self._release(request)
        self.chains.pop(request.request_id, None)

    def release_held(self, request_id: str) -> None:
        pages = self.held.pop(request_id, None)
        if pages:
            self.allocator.free(pages)

    def add_prefilled(self, request: Request, chain: TokenBlockSequence) -> None:
        """Admit a request whose prompt KV is already resident (written into
        request.pages by a remote prefill transfer) straight into decode."""
        request.state = RequestState.DECODE
        request.num_computed_tokens = len(request.prompt_tokens)
        self.chains[request.request_id] = chain
        self.running.append(request)

    def _release(self, request: Request) -> None:
        if request.pages:
            self.allocator.free(request.pages)
            request.pages = []
